"""Map-typed feature vectorizers.

Reference: ``OPMapVectorizer`` family — one vectorizer per map value type —
plus ``TextMapPivotVectorizer`` and ``MultiPickListMapVectorizer``
(core/.../impl/feature/OPMapVectorizer.scala, TextMapPivotVectorizer.scala).
Map features hold {key -> value}; the estimator discovers the key set during
fit (with allow/block lists) and each (map, key) pair becomes a column group
vectorized like its scalar value type, with the key recorded as the
``grouping`` in vector metadata.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..features.feature import Feature
from ..stages.base import SequenceEstimator, SequenceModel
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types import feature_types as ft
from ..types.feature_types import OPMap, OPVector
from .vector_metadata import (
    NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMetadata, VectorMetadata,
)
from .vectorizers import _vec_column

__all__ = ["NumericMapVectorizer", "NumericMapVectorizerModel",
           "TextMapPivotVectorizer", "TextMapPivotVectorizerModel",
           "MultiPickListMapVectorizer", "MultiPickListMapVectorizerModel",
           "SmartTextMapVectorizer", "SmartTextMapVectorizerModel",
           "GeoMapVectorizer", "GeoMapVectorizerModel",
           "GeolocationMapVectorizer", "GeolocationMapVectorizerModel",
           "transmogrify_map_group"]


def _discover_keys(col: FeatureColumn, allow: Optional[Sequence[str]],
                   block: Sequence[str]) -> List[str]:
    keys: Dict[str, None] = {}
    for m in col.values:
        for k in m:
            keys.setdefault(k, None)
    out = [k for k in keys if k not in set(block)]
    if allow:
        out = [k for k in out if k in set(allow)]
    return sorted(out)


class NumericMapVectorizer(SequenceEstimator):
    """RealMap/IntegralMap/BinaryMap... -> per-key fill + null indicators."""

    input_types = (OPMap,)

    def __init__(self, fill_with_mean: bool = True, track_nulls: bool = True,
                 allow_keys: Optional[List[str]] = None,
                 block_keys: List[str] = (), uid: Optional[str] = None):
        super().__init__(operation_name="vecNumMap", output_type=OPVector, uid=uid)
        self.fill_with_mean = fill_with_mean
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None
        self.block_keys = list(block_keys)

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        keysets, fills = [], []
        for c in cols:
            keys = _discover_keys(c, self.allow_keys, self.block_keys)
            keysets.append(keys)
            kf = {}
            for k in keys:
                vals = [float(m[k]) for m in c.values if k in m and m[k] is not None]
                kf[k] = float(np.mean(vals)) if (vals and self.fill_with_mean) else 0.0
            fills.append(kf)
        return NumericMapVectorizerModel(keysets=keysets, fills=fills,
                                         track_nulls=self.track_nulls)


class NumericMapVectorizerModel(SequenceModel):

    input_types = (OPMap,)
    def __init__(self, keysets: List[List[str]], fills: List[Dict[str, float]],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecNumMap", output_type=OPVector, uid=uid)
        self.keysets = keysets
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts, meta = [], []
        for f, keys, kf, c in zip(self.input_features, self.keysets,
                                  self.fills, cols):
            tname = f.ftype.type_name()
            width = len(keys) * (2 if self.track_nulls else 1)
            block = np.zeros((n, width), dtype=np.float32)
            for j, k in enumerate(keys):
                base = j * (2 if self.track_nulls else 1)
                fill = kf.get(k, 0.0)
                for row, m in enumerate(c.values):
                    v = m.get(k)
                    if v is None:
                        block[row, base] = fill
                        if self.track_nulls:
                            block[row, base + 1] = 1.0
                    else:
                        block[row, base] = float(v)
                meta.append(VectorColumnMetadata(f.name, tname, grouping=k))
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, tname, grouping=k,
                        indicator_value=NULL_INDICATOR))
            parts.append(block)
        return _vec_column(np.concatenate(parts, axis=1) if parts
                           else np.zeros((n, 0), np.float32),
                           VectorMetadata("num_map_vec", meta))


class TextMapPivotVectorizer(SequenceEstimator):
    """TextMap/PickListMap -> per-key TopK pivot with OTHER + null columns."""

    input_types = (OPMap,)

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True,
                 allow_keys: Optional[List[str]] = None,
                 block_keys: List[str] = (), uid: Optional[str] = None):
        super().__init__(operation_name="pivotTextMap", output_type=OPVector, uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None
        self.block_keys = list(block_keys)

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        keysets, vocabs = [], []
        for c in cols:
            keys = _discover_keys(c, self.allow_keys, self.block_keys)
            keysets.append(keys)
            kv = {}
            for k in keys:
                counts = Counter(
                    str(m[k]) for m in c.values if k in m and m[k] is not None
                )
                kv[k] = [v for v, cnt in counts.most_common(self.top_k)
                         if cnt >= self.min_support]
            vocabs.append(kv)
        return TextMapPivotVectorizerModel(keysets=keysets, vocabs=vocabs,
                                           track_nulls=self.track_nulls)


class TextMapPivotVectorizerModel(SequenceModel):

    input_types = (OPMap,)
    def __init__(self, keysets: List[List[str]],
                 vocabs: List[Dict[str, List[str]]],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="pivotTextMap", output_type=OPVector, uid=uid)
        self.keysets = keysets
        self.vocabs = vocabs
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts, meta = [], []
        for f, keys, kv, c in zip(self.input_features, self.keysets,
                                  self.vocabs, cols):
            tname = f.ftype.type_name()
            for k in keys:
                vocab = kv.get(k, [])
                index = {v: i for i, v in enumerate(vocab)}
                w = len(vocab) + 1 + (1 if self.track_nulls else 0)
                block = np.zeros((n, w), dtype=np.float32)
                for row, m in enumerate(c.values):
                    v = m.get(k)
                    if v is None:
                        if self.track_nulls:
                            block[row, w - 1] = 1.0
                    else:
                        j = index.get(str(v))
                        if j is None:
                            block[row, len(vocab)] = 1.0
                        else:
                            block[row, j] = 1.0
                parts.append(block)
                for v in vocab:
                    meta.append(VectorColumnMetadata(f.name, tname, grouping=k,
                                                     indicator_value=v))
                meta.append(VectorColumnMetadata(f.name, tname, grouping=k,
                                                 indicator_value=OTHER_INDICATOR))
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, tname, grouping=k,
                        indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1) if parts
                           else np.zeros((n, 0), np.float32),
                           VectorMetadata("text_map_vec", meta))


class MultiPickListMapVectorizer(TextMapPivotVectorizer):
    """MultiPickListMap -> per-key multi-hot pivot."""

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        keysets, vocabs = [], []
        for c in cols:
            keys = _discover_keys(c, self.allow_keys, self.block_keys)
            keysets.append(keys)
            kv = {}
            for k in keys:
                counts: Counter = Counter()
                for m in c.values:
                    if k in m and m[k] is not None:
                        counts.update(str(x) for x in m[k])
                kv[k] = [v for v, cnt in counts.most_common(self.top_k)
                         if cnt >= self.min_support]
            vocabs.append(kv)
        return MultiPickListMapVectorizerModel(keysets=keysets, vocabs=vocabs,
                                               track_nulls=self.track_nulls)


class MultiPickListMapVectorizerModel(TextMapPivotVectorizerModel):
    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts, meta = [], []
        for f, keys, kv, c in zip(self.input_features, self.keysets,
                                  self.vocabs, cols):
            tname = f.ftype.type_name()
            for k in keys:
                vocab = kv.get(k, [])
                index = {v: i for i, v in enumerate(vocab)}
                w = len(vocab) + 1 + (1 if self.track_nulls else 0)
                block = np.zeros((n, w), dtype=np.float32)
                for row, m in enumerate(c.values):
                    vs = m.get(k)
                    if not vs:
                        if self.track_nulls:
                            block[row, w - 1] = 1.0
                        continue
                    hit = False
                    for v in vs:
                        j = index.get(str(v))
                        if j is not None:
                            block[row, j] = 1.0
                            hit = True
                    if not hit:
                        block[row, len(vocab)] = 1.0
                parts.append(block)
                for v in vocab:
                    meta.append(VectorColumnMetadata(f.name, tname, grouping=k,
                                                     indicator_value=v))
                meta.append(VectorColumnMetadata(f.name, tname, grouping=k,
                                                 indicator_value=OTHER_INDICATOR))
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, tname, grouping=k,
                        indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1) if parts
                           else np.zeros((n, 0), np.float32),
                           VectorMetadata("mpl_map_vec", meta))


_NUMERIC_MAPS = (ft.RealMap, ft.IntegralMap, ft.BinaryMap, ft.CurrencyMap,
                 ft.PercentMap, ft.DateMap, ft.DateTimeMap)


def transmogrify_map_group(feats: List[Feature], top_k: int, min_support: int,
                           num_hash_features: int,
                           track_nulls: bool) -> List[Feature]:
    """Dispatch map features to the right map vectorizer (Transmogrifier map arm)."""
    numeric = [f for f in feats if issubclass(f.ftype, _NUMERIC_MAPS)]
    mpl = [f for f in feats if issubclass(f.ftype, ft.MultiPickListMap)]
    text = [f for f in feats
            if issubclass(f.ftype, ft.OPMap)
            and f not in numeric and f not in mpl
            and not issubclass(f.ftype, ft.GeolocationMap)]
    geo = [f for f in feats if issubclass(f.ftype, ft.GeolocationMap)]
    out: List[Feature] = []
    if numeric:
        s = NumericMapVectorizer(track_nulls=track_nulls)
        s.set_input(*numeric)
        out.append(s.get_output())
    if text:
        s = SmartTextMapVectorizer(top_k=top_k, min_support=min_support,
                                   num_hash_features=num_hash_features,
                                   track_nulls=track_nulls)
        s.set_input(*text)
        out.append(s.get_output())
    if mpl:
        s = MultiPickListMapVectorizer(top_k=top_k, min_support=min_support,
                                       track_nulls=track_nulls)
        s.set_input(*mpl)
        out.append(s.get_output())
    if geo:
        # geolocation maps: per-key (lat,lon,acc) via numeric path on flattened keys
        s = GeoMapVectorizer(track_nulls=track_nulls)
        s.set_input(*geo)
        out.append(s.get_output())
    return out


class GeoMapVectorizer(SequenceEstimator):
    """GeolocationMap -> per-key (lat, lon, accuracy) + null indicator."""

    input_types = (OPMap,)

    def __init__(self, track_nulls: bool = True,
                 allow_keys: Optional[List[str]] = None,
                 block_keys: List[str] = (), uid: Optional[str] = None):
        super().__init__(operation_name="vecGeoMap", output_type=OPVector, uid=uid)
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None
        self.block_keys = list(block_keys)

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        keysets = [
            _discover_keys(c, self.allow_keys, self.block_keys) for c in cols
        ]
        return GeoMapVectorizerModel(keysets=keysets, track_nulls=self.track_nulls)


class GeoMapVectorizerModel(SequenceModel):

    input_types = (OPMap,)
    def __init__(self, keysets: List[List[str]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecGeoMap", output_type=OPVector, uid=uid)
        self.keysets = keysets
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts, meta = [], []
        for f, keys, c in zip(self.input_features, self.keysets, cols):
            tname = f.ftype.type_name()
            for k in keys:
                w = 3 + (1 if self.track_nulls else 0)
                block = np.zeros((n, w), dtype=np.float32)
                for row, m in enumerate(c.values):
                    v = m.get(k)
                    if v is None or len(v) != 3:
                        if self.track_nulls:
                            block[row, 3] = 1.0
                    else:
                        block[row, :3] = v
                parts.append(block)
                for d in ("lat", "lon", "accuracy"):
                    meta.append(VectorColumnMetadata(f.name, tname, grouping=k,
                                                     descriptor_value=d))
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, tname, grouping=k,
                        indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1) if parts
                           else np.zeros((n, 0), np.float32),
                           VectorMetadata("geo_map_vec", meta))


# reference names (core/.../impl/feature/GeolocationMapVectorizer.scala)
GeolocationMapVectorizer = GeoMapVectorizer
GeolocationMapVectorizerModel = GeoMapVectorizerModel


# ---------------------------------------------------------------------------
# SmartTextMapVectorizer
# ---------------------------------------------------------------------------

class SmartTextMapVectorizer(SequenceEstimator):
    """Per-key cardinality-driven text strategy for TextMap-family features.

    Reference ``SmartTextMapVectorizer`` (core/.../impl/feature/
    SmartTextMapVectorizer.scala) — the map analogue of SmartTextVectorizer:
    computes ``TextStats`` per (map feature, key), then per key picks
    categorical pivot (cardinality <= max_cardinality), murmur3 hashing, or
    ignore (fill rate below min_fill_rate); emits per-key null indicators.
    """

    input_types = (OPMap,)

    PIVOT, HASH, IGNORE = "pivot", "hash", "ignore"

    def __init__(self, max_cardinality: int = 100, top_k: int = 20,
                 min_support: int = 10, num_hash_features: int = 512,
                 min_fill_rate: float = 0.001, track_nulls: bool = True,
                 seed: int = 42,
                 allow_keys: Optional[List[str]] = None,
                 block_keys: List[str] = (), uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtMapVec",
                         output_type=OPVector, uid=uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hash_features = num_hash_features
        self.min_fill_rate = min_fill_rate
        self.track_nulls = track_nulls
        self.seed = seed
        self.allow_keys = list(allow_keys) if allow_keys else None
        self.block_keys = list(block_keys)

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        from .vectorizers import TextStats
        keysets, strategies, vocabs = [], [], []
        allow = set(self.allow_keys) if self.allow_keys else None
        block = set(self.block_keys)
        for c in cols:
            # single pass: one TextStats per key encountered (present values
            # only; null counts derive from per-key presence vs row count)
            n = len(c)
            stats_by_key: Dict[str, TextStats] = {}
            for m in c.values:
                if not m:
                    continue
                for k, v in m.items():
                    if k in block or (allow is not None and k not in allow):
                        continue
                    st = stats_by_key.get(k)
                    if st is None:
                        st = stats_by_key[k] = TextStats(self.max_cardinality)
                    st.update(None if v is None else str(v))
            keys = sorted(stats_by_key)
            keysets.append(keys)
            strat: Dict[str, str] = {}
            vocab: Dict[str, List[str]] = {}
            for k in keys:
                stats = stats_by_key[k]
                fill = (stats.n - stats.n_null) / max(n, 1)
                if fill < self.min_fill_rate:
                    strat[k] = self.IGNORE
                    vocab[k] = []
                elif (not stats.saturated
                      and stats.cardinality <= self.max_cardinality):
                    strat[k] = self.PIVOT
                    vocab[k] = [
                        v for v, cnt in stats.value_counts.most_common(self.top_k)
                        if cnt >= self.min_support
                    ]
                else:
                    strat[k] = self.HASH
                    vocab[k] = []
            strategies.append(strat)
            vocabs.append(vocab)
        self.metadata["text_strategies"] = {
            f.name: s for f, s in zip(self.input_features, strategies)}
        return SmartTextMapVectorizerModel(
            keysets=keysets, strategies=strategies, vocabs=vocabs,
            num_hash_features=self.num_hash_features,
            track_nulls=self.track_nulls, seed=self.seed)


class SmartTextMapVectorizerModel(SequenceModel):

    input_types = (OPMap,)
    def __init__(self, keysets: List[List[str]],
                 strategies: List[Dict[str, str]],
                 vocabs: List[Dict[str, List[str]]],
                 num_hash_features: int = 512, track_nulls: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtMapVec",
                         output_type=OPVector, uid=uid)
        self.keysets = keysets
        self.strategies = strategies
        self.vocabs = vocabs
        self.num_hash_features = num_hash_features
        self.track_nulls = track_nulls
        self.seed = seed

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        from .vectorizers import _row_tokens
        from ..utils.hashing import murmur3_32
        n = len(cols[0])
        nf = self.num_hash_features
        blocks, meta = [], []
        for f, keys, strat, kv, c in zip(self.input_features, self.keysets,
                                         self.strategies, self.vocabs, cols):
            tname = f.ftype.type_name()
            # lay out the output block per key, then fill in ONE pass over the
            # rows (sparse maps touch only their present keys)
            layout: Dict[str, tuple] = {}   # key -> (strategy, offset, index)
            width = 0
            for k in keys:
                s = strat.get(k, SmartTextMapVectorizer.IGNORE)
                if s == SmartTextMapVectorizer.IGNORE:
                    continue
                if s == SmartTextMapVectorizer.PIVOT:
                    vocab = kv.get(k, [])
                    index = {v: i for i, v in enumerate(vocab)}
                    layout[k] = (s, width, index)
                    for v in vocab:
                        meta.append(VectorColumnMetadata(
                            f.name, tname, grouping=k, indicator_value=v))
                    meta.append(VectorColumnMetadata(
                        f.name, tname, grouping=k,
                        indicator_value=OTHER_INDICATOR))
                    width += len(vocab) + 1
                else:  # HASH
                    layout[k] = (s, width, None)
                    for b in range(nf):
                        meta.append(VectorColumnMetadata(
                            f.name, tname, grouping=k,
                            descriptor_value=f"hash_{b}"))
                    width += nf
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, tname, grouping=k,
                        indicator_value=NULL_INDICATOR))
                    # null indicator sits right after the key's value slots
                    layout[k] = (*layout[k][:2], layout[k][2], width)
                    width += 1
            block = np.zeros((n, width), dtype=np.float32)
            if self.track_nulls:
                for k, lay in layout.items():
                    block[:, lay[3]] = 1.0     # default null; cleared if seen
            hash_cache: Dict[str, int] = {}
            for row, m in enumerate(c.values):
                if not m:
                    continue
                for k, v in m.items():
                    lay = layout.get(k)
                    if lay is None or v is None:
                        continue
                    skind, off, index = lay[0], lay[1], lay[2]
                    if self.track_nulls:
                        block[row, lay[3]] = 0.0
                    sv = str(v)
                    if skind == SmartTextMapVectorizer.PIVOT:
                        j = index.get(sv)
                        block[row, off + (len(index) if j is None else j)] = 1.0
                    else:
                        for tok in _row_tokens(sv):
                            b = hash_cache.get(tok)
                            if b is None:
                                b = murmur3_32(tok, self.seed) % nf
                                hash_cache[tok] = b
                            block[row, off + b] += 1.0
            blocks.append(block)
        return _vec_column(np.concatenate(blocks, axis=1) if blocks
                           else np.zeros((n, 0), np.float32),
                           VectorMetadata("smart_text_map_vec", meta))
