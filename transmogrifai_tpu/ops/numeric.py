"""Numeric preparation stages.

Reference (core/.../impl/feature/, SURVEY §2.5):
 * ``NumericBucketizer`` — fixed split points -> one-hot buckets
 * ``DecisionTreeNumericBucketizer`` — supervised split points from a
   single-feature decision tree (DecisionTreeNumericBucketizer.scala:60);
   reuses the histogram tree kernel (models/gbdt_kernels) — SURVEY §7 step 6
 * ``FillMissingWithMean`` (FillMissingWithMean.scala)
 * ``OpScalarStandardScaler`` (OpScalarStandardScaler.scala:49)
 * ``ScalerTransformer``/``DescalerTransformer`` (ScalerTransformer.scala)
 * ``PercentileCalibrator`` (PercentileCalibrator.scala)
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..stages.base import (
    BinaryEstimator, BinaryModel, UnaryEstimator, UnaryModel,
    UnaryTransformer,
)
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import OPNumeric, OPVector, Real, RealNN
from .vector_metadata import VectorColumnMetadata, VectorMetadata, NULL_INDICATOR
from .vectorizers import _vec_column

__all__ = [
    "NumericBucketizer", "DecisionTreeNumericBucketizer",
    "FillMissingWithMean", "OpScalarStandardScaler", "ScalerTransformer",
    "DescalerTransformer", "PercentileCalibrator",
]


def _bucketize(vals: np.ndarray, mask: np.ndarray, splits: Sequence[float],
               parent: str, ptype: str, track_nulls: bool,
               track_invalid: bool) -> FeatureColumn:
    """One-hot bucket membership + optional null/invalid indicators."""
    splits = np.asarray(sorted(splits), np.float64)
    nb = len(splits) - 1
    idx = np.clip(np.searchsorted(splits, vals, side="right") - 1, 0, nb - 1)
    valid = mask & (vals >= splits[0]) & (vals <= splits[-1])
    parts = np.zeros((len(vals), nb), np.float32)
    parts[np.arange(len(vals))[valid], idx[valid]] = 1.0
    meta = [VectorColumnMetadata(parent, ptype, grouping=parent,
                                 indicator_value=f"{splits[i]}-{splits[i+1]}")
            for i in range(nb)]
    blocks = [parts]
    if track_invalid:
        blocks.append((mask & ~valid).astype(np.float32)[:, None])
        meta.append(VectorColumnMetadata(parent, ptype, grouping=parent,
                                         indicator_value="OutOfBounds"))
    if track_nulls:
        blocks.append((~mask).astype(np.float32)[:, None])
        meta.append(VectorColumnMetadata(parent, ptype, grouping=parent,
                                         indicator_value=NULL_INDICATOR))
    return _vec_column(np.concatenate(blocks, axis=1),
                       VectorMetadata(f"{parent}_buckets", meta))


class NumericBucketizer(UnaryTransformer):
    """Fixed split points (NumericBucketizer.scala)."""

    input_types = (OPNumeric,)

    def __init__(self, split_points: Sequence[float],
                 track_nulls: bool = True, track_invalid: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="numericBucketizer",
                         output_type=OPVector, uid=uid)
        if len(split_points) < 2 or list(split_points) != sorted(split_points):
            raise ValueError("split_points must be sorted with >= 2 entries")
        self.split_points = list(split_points)
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        f = self.input_features[0]
        vals = np.nan_to_num(np.asarray(col.values, np.float64))
        return _bucketize(vals, np.asarray(col.mask), self.split_points,
                          f.name, f.ftype.type_name(), self.track_nulls,
                          self.track_invalid)


class _BucketizerModel(BinaryModel):
    input_types = (OPNumeric, OPNumeric)
    label_input_positions = (0,)

    def __init__(self, split_points: List[float], track_nulls: bool = True,
                 track_invalid: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name="dtBucketizer", output_type=OPVector,
                         uid=uid)
        self.split_points = list(split_points)
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    def transform_columns(self, label_col, col) -> FeatureColumn:
        f = self.input_features[1]
        vals = np.nan_to_num(np.asarray(col.values, np.float64))
        if len(self.split_points) < 2:  # no informative splits found
            n = len(vals)
            meta = []
            blocks = np.zeros((n, 0), np.float32)
            if self.track_nulls:
                blocks = (~np.asarray(col.mask)).astype(np.float32)[:, None]
                meta = [VectorColumnMetadata(f.name, f.ftype.type_name(),
                                             grouping=f.name,
                                             indicator_value=NULL_INDICATOR)]
            return _vec_column(np.atleast_2d(blocks),
                               VectorMetadata(f"{f.name}_buckets", meta))
        return _bucketize(vals, np.asarray(col.mask), self.split_points,
                          f.name, f.ftype.type_name(), self.track_nulls,
                          self.track_invalid)


class DecisionTreeNumericBucketizer(BinaryEstimator):
    """Supervised bucketization: split points = the thresholds a shallow
    single-feature decision tree picks by info gain
    (DecisionTreeNumericBucketizer.scala:60).  Inputs (label, numeric)."""

    input_types = (OPNumeric, OPNumeric)
    label_input_positions = (0,)

    def __init__(self, max_splits: int = 16, max_depth: int = 4,
                 min_info_gain: float = 0.01, min_instances_per_node: int = 1,
                 track_nulls: bool = True, track_invalid: bool = False,
                 max_bins: int = 32, uid: Optional[str] = None):
        super().__init__(operation_name="dtBucketizer", output_type=OPVector,
                         uid=uid)
        self.max_splits = max_splits
        self.max_depth = max_depth
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        self.max_bins = max_bins

    def fit_columns(self, data: ColumnarDataset, label_col, col):
        from ..models.gbdt_kernels import apply_bins, grow_tree, quantile_bins

        mask = np.asarray(col.mask)
        vals = np.asarray(col.values, np.float64)
        y = np.nan_to_num(np.asarray(label_col.values, np.float64))
        X = vals[mask][:, None]
        yv = y[mask]
        splits: List[float] = []
        if X.size >= 2 and np.unique(X).size > 1:
            classes = np.unique(yv)
            k = len(classes) if len(classes) <= 20 else 1
            if k > 1:
                Y = np.equal(yv[:, None], classes[None, :]).astype(np.float32)
            else:
                Y = yv[:, None].astype(np.float32)
            edges = quantile_bins(X.astype(np.float32), self.max_bins)
            binned = apply_bins(jnp.asarray(X, jnp.float32),
                                jnp.asarray(edges))
            w = jnp.ones(len(yv), jnp.float32)
            G = jnp.asarray(Y)
            H = jnp.broadcast_to(w[:, None], Y.shape)
            feat, thresh, _ = grow_tree(
                binned, G, H, w, max_depth=self.max_depth,
                n_bins=self.max_bins, lam=1e-3,
                min_info_gain=self.min_info_gain,
                min_instances=float(self.min_instances_per_node),
                newton_leaf=False)
            th = np.asarray(thresh)
            used_bins = sorted({int(t) for t in th if t < self.max_bins - 1})
            finite_edges = np.asarray(edges)[0]
            splits = [float(finite_edges[b]) for b in used_bins
                      if np.isfinite(finite_edges[b])][: self.max_splits]
        if splits:
            # infinite outer bounds, as the reference tree bucketizer uses:
            # scoring-time values beyond the training range still land in the
            # first/last bucket instead of silently vanishing
            points = [-np.inf] + splits + [np.inf]
        else:
            points = []
        self.metadata["summary"] = {"splits": points,
                                    "foundSplits": bool(splits)}
        return _BucketizerModel(points, self.track_nulls, self.track_invalid)


class FillMissingWithMean(UnaryEstimator):
    """Impute missing with the training mean (FillMissingWithMean.scala);
    output RealNN."""

    input_types = (OPNumeric,)

    def __init__(self, default_value: float = 0.0, uid: Optional[str] = None):
        super().__init__(operation_name="fillWithMean", output_type=RealNN,
                         uid=uid)
        self.default_value = default_value

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        vals = np.asarray(col.values, np.float64)
        mask = np.asarray(col.mask)
        mean = float(vals[mask].mean()) if mask.any() else self.default_value
        return _FillModel(fill=mean)


class _FillModel(UnaryModel):
    input_types = (OPNumeric,)

    def __init__(self, fill: float, uid: Optional[str] = None):
        super().__init__(operation_name="fillWithMean", output_type=RealNN,
                         uid=uid)
        self.fill = fill

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        vals = np.nan_to_num(np.asarray(col.values, np.float64), nan=self.fill)
        out = np.where(np.asarray(col.mask), vals, self.fill)
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))


class OpScalarStandardScaler(UnaryEstimator):
    """z-score a single numeric feature (OpScalarStandardScaler.scala:49)."""

    input_types = (OPNumeric,)

    def __init__(self, with_mean: bool = True, with_std: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="stdScaler", output_type=RealNN,
                         uid=uid)
        self.with_mean = with_mean
        self.with_std = with_std

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        vals = np.asarray(col.values, np.float64)
        mask = np.asarray(col.mask)
        mean = float(vals[mask].mean()) if mask.any() else 0.0
        std = float(vals[mask].std()) if mask.any() else 1.0
        return _ScalerModel(mean=mean if self.with_mean else 0.0,
                            scale=(std if std > 0 else 1.0)
                            if self.with_std else 1.0)


class _ScalerModel(UnaryModel):
    input_types = (OPNumeric,)

    def __init__(self, mean: float, scale: float, uid: Optional[str] = None):
        super().__init__(operation_name="stdScaler", output_type=RealNN,
                         uid=uid)
        self.mean = mean
        self.scale = scale

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        mask = np.asarray(col.mask)
        vals = np.nan_to_num(np.asarray(col.values, np.float64), nan=self.mean)
        # missing rows z-score to 0 (mean imputation), not (0-mean)/scale
        vals = np.where(mask, vals, self.mean)
        out = (vals - self.mean) / self.scale
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))


_SCALERS = {
    "linear": (lambda v, a, b: a * v + b, lambda v, a, b: (v - b) / a),
    "log": (lambda v, a, b: np.log(np.maximum(v, 1e-12)),
            lambda v, a, b: np.exp(v)),
}


class ScalerTransformer(UnaryTransformer):
    """Declarative scaling with an invertible family (ScalerTransformer.scala);
    records scaler args in metadata so ``DescalerTransformer`` can undo it."""

    input_types = (OPNumeric,)

    def __init__(self, scaling_type: str = "linear", slope: float = 1.0,
                 intercept: float = 0.0, uid: Optional[str] = None):
        super().__init__(operation_name="scaler", output_type=Real, uid=uid)
        if scaling_type not in _SCALERS:
            raise ValueError(f"unknown scaling_type {scaling_type!r}")
        self.scaling_type = scaling_type
        self.slope = slope
        self.intercept = intercept

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        fwd, _ = _SCALERS[self.scaling_type]
        vals = np.asarray(col.values, np.float64)
        self.metadata["scaler"] = {"type": self.scaling_type,
                                   "slope": self.slope,
                                   "intercept": self.intercept}
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            out = fwd(vals, self.slope, self.intercept)
        # non-finite results (e.g. log of a non-positive value) fold into the
        # mask — downstream vectorizers rely on NaN-implies-masked
        mask = (np.isfinite(out) if col.mask is None
                else np.asarray(col.mask) & np.isfinite(out))
        return FeatureColumn(Real, out, mask)


class DescalerTransformer(BinaryModel):
    """Invert a ``ScalerTransformer`` applied upstream: inputs
    (scaled value, scaled source carrying scaler metadata)."""

    def __init__(self, scaling_type: str = "linear", slope: float = 1.0,
                 intercept: float = 0.0, uid: Optional[str] = None):
        super().__init__(operation_name="descaler", output_type=Real, uid=uid)
        self.scaling_type = scaling_type
        self.slope = slope
        self.intercept = intercept

    def transform_columns(self, col: FeatureColumn, *_rest) -> FeatureColumn:
        _, inv = _SCALERS[self.scaling_type]
        vals = np.asarray(col.values, np.float64)
        with np.errstate(over="ignore", invalid="ignore", divide="ignore"):
            out = inv(vals, self.slope, self.intercept)
        # e.g. exp-descale overflow or slope=0 division: mask, don't emit inf
        mask = (np.isfinite(out) if col.mask is None
                else np.asarray(col.mask) & np.isfinite(out))
        return FeatureColumn(Real, out, mask)

    input_arity = (1, 2)


class PercentileCalibrator(UnaryEstimator):
    """Map a numeric score to its training percentile bucket 0..buckets-1
    (PercentileCalibrator.scala)."""

    input_types = (OPNumeric,)

    def __init__(self, buckets: int = 100, uid: Optional[str] = None):
        super().__init__(operation_name="percentileCalibrator",
                         output_type=RealNN, uid=uid)
        self.buckets = buckets

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        vals = np.asarray(col.values, np.float64)
        mask = np.asarray(col.mask)
        qs = np.linspace(0, 1, self.buckets + 1)[1:-1]
        splits = (np.quantile(vals[mask], qs) if mask.any()
                  else np.zeros(len(qs)))
        model = _PercentileModel(splits=list(map(float, splits)),
                                 buckets=self.buckets)
        self.metadata["summary"] = {"splits": model.splits}
        return model


class _PercentileModel(UnaryModel):
    input_types = (OPNumeric,)

    def __init__(self, splits: List[float], buckets: int = 100,
                 uid: Optional[str] = None):
        super().__init__(operation_name="percentileCalibrator",
                         output_type=RealNN, uid=uid)
        self.splits = list(splits)
        self.buckets = buckets

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        vals = np.nan_to_num(np.asarray(col.values, np.float64))
        out = np.searchsorted(np.asarray(self.splits), vals,
                              side="right").astype(np.float64)
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))
