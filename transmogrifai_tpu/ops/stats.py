"""Statistics kernels — matmul/segment-sum formulations for TPU.

Reference: ``OpStatistics`` (utils/stats/OpStatistics.scala:39-202 —
correlations, chi-square, Cramér's V, pointwise mutual information) and the
column statistics used by ``SanityChecker.fitFn``
(core/.../impl/preparators/SanityChecker.scala:380-470).

Everything is one or two MXU matmuls over the (N, D) feature matrix:
 * colStats: count/mean/var/min/max via reductions
 * Pearson: gram matrix of standardized columns
 * Spearman: same on rank-transformed columns (sort-based ranks, SURVEY §7d)
 * chi²/Cramér's V: contingency tables via one-hot matmuls
In multi-chip mode these reduce over a batch-sharded mesh with psum
(see transmogrifai_tpu.parallel).
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ColStats", "col_stats", "pearson_with_label", "pearson_matrix",
           "spearman_with_label", "ranks", "cramers_v", "chi_square",
           "contingency_stats"]


class ColStats(NamedTuple):
    count: jnp.ndarray
    mean: jnp.ndarray
    variance: jnp.ndarray
    min: jnp.ndarray
    max: jnp.ndarray
    num_nonzero: jnp.ndarray


@jax.jit
def col_stats(X: jnp.ndarray, sample_weight: Optional[jnp.ndarray] = None) -> ColStats:
    """Per-column stats (Statistics.colStats parity), weighted for CV masks."""
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    wsum = jnp.maximum(w.sum(), 1e-12)
    mean = (w @ X) / wsum
    var = (w @ (X - mean) ** 2) / jnp.maximum(wsum - 1.0, 1.0)
    big = jnp.float32(3.4e38)
    wpos = w > 0
    mn = jnp.min(jnp.where(wpos[:, None], X, big), axis=0)
    mx = jnp.max(jnp.where(wpos[:, None], X, -big), axis=0)
    nnz = (w @ (X != 0).astype(jnp.float32))
    return ColStats(wsum, mean, var, mn, mx, nnz)


@jax.jit
def pearson_with_label(X: jnp.ndarray, y: jnp.ndarray,
                       sample_weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """corr(x_j, y) for every column — one matvec (SanityChecker's
    correlationsWithLabel via OpStatistics.computeCorrelationsWithLabel)."""
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n = X.shape[0]
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    wsum = jnp.maximum(w.sum(), 1e-12)
    mx = (w @ X) / wsum
    my = jnp.dot(w, y) / wsum
    Xc = X - mx
    yc = y - my
    cov = (w * yc) @ Xc / wsum
    vx = (w @ Xc ** 2) / wsum
    vy = jnp.dot(w, yc ** 2) / wsum
    return cov / jnp.sqrt(jnp.maximum(vx * vy, 1e-24))


@jax.jit
def pearson_matrix(X: jnp.ndarray,
                   sample_weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Full (D, D) correlation matrix — one gram matmul on the MXU."""
    X = jnp.asarray(X, jnp.float32)
    n = X.shape[0]
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    wsum = jnp.maximum(w.sum(), 1e-12)
    mx = (w @ X) / wsum
    Xc = (X - mx) * jnp.sqrt(w)[:, None]
    cov = Xc.T @ Xc / wsum
    sd = jnp.sqrt(jnp.maximum(jnp.diag(cov), 1e-24))
    return cov / jnp.outer(sd, sd)


@jax.jit
def ranks(x: jnp.ndarray) -> jnp.ndarray:
    """Average ranks (ties get midranks) via double argsort + segment means."""
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    order = jnp.argsort(x)
    xs = x[order]
    is_new = jnp.concatenate([jnp.ones(1, bool), xs[1:] != xs[:-1]])
    gid = jnp.cumsum(is_new) - 1
    pos = jnp.arange(1, n + 1, dtype=jnp.float32)
    gsum = jax.ops.segment_sum(pos, gid, num_segments=n)
    gcnt = jax.ops.segment_sum(jnp.ones(n, jnp.float32), gid, num_segments=n)
    midrank = gsum / jnp.maximum(gcnt, 1.0)
    r_sorted = midrank[gid]
    return jnp.zeros(n, jnp.float32).at[order].set(r_sorted)


@jax.jit
def spearman_with_label(X: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Spearman corr per column: Pearson on rank transforms (vmapped sort)."""
    Xr = jax.vmap(ranks, in_axes=1, out_axes=1)(jnp.asarray(X, jnp.float32))
    yr = ranks(jnp.asarray(y, jnp.float32))
    return pearson_with_label(Xr, yr)


@functools.partial(jax.jit, static_argnames=("n_rows", "n_cols"))
def _contingency(row_idx, col_idx, w, n_rows: int, n_cols: int):
    tbl = jnp.zeros((n_rows, n_cols), jnp.float32)
    return tbl.at[row_idx, col_idx].add(w)


def contingency_stats(table: np.ndarray) -> Dict[str, float]:
    """chi², p-value proxy, Cramér's V, PMI from a contingency table.

    OpStatistics.contingencyStats parity (utils/stats/OpStatistics.scala:188).
    """
    t = np.asarray(table, np.float64)
    n = t.sum()
    if n <= 0 or t.shape[0] < 2 or t.shape[1] < 2:
        return {"chi2": 0.0, "cramersV": 0.0, "n": float(n)}
    row = t.sum(axis=1, keepdims=True)
    col = t.sum(axis=0, keepdims=True)
    expected = row @ col / n
    with np.errstate(divide="ignore", invalid="ignore"):
        chi2 = np.nansum(np.where(expected > 0,
                                  (t - expected) ** 2 / expected, 0.0))
    k = min(t.shape[0], t.shape[1])
    phi2 = chi2 / n
    cramers = float(np.sqrt(phi2 / max(k - 1, 1)))
    # pointwise mutual information per cell (log2, as in reference)
    with np.errstate(divide="ignore", invalid="ignore"):
        joint = t / n
        pmi = np.where(joint > 0,
                       np.log2(joint / np.maximum(expected / n, 1e-300)), 0.0)
    return {"chi2": float(chi2), "cramersV": min(cramers, 1.0),
            "n": float(n), "pmi": pmi}


def chi_square(labels: np.ndarray, indicator: np.ndarray,
               n_label_classes: int) -> Dict[str, float]:
    """Chi² of a binary indicator column vs the label."""
    tbl = np.asarray(_contingency(
        jnp.asarray(labels, jnp.int32),
        jnp.asarray((indicator > 0).astype(np.int32)),
        jnp.ones(len(labels), jnp.float32), n_label_classes, 2))
    return contingency_stats(tbl)


def cramers_v(labels: np.ndarray, group_indicators: np.ndarray,
              n_label_classes: int) -> Dict[str, float]:
    """Cramér's V for a categorical group given its one-hot indicator block.

    ``group_indicators``: (N, C) one-hot columns of one categorical feature
    (from vector metadata grouping).  The contingency table is a single
    matmul: labels_onehot.T @ indicators.
    """
    # host numpy: the table is tiny (K × C) and an un-jitted device matmul
    # costs several op-by-op dispatches per call (~0.6 s each through a
    # remote-TPU tunnel, measured); one bincount-style product wins
    L = np.eye(n_label_classes, dtype=np.float32)[np.asarray(labels, np.int64)]
    G = np.asarray(group_indicators, np.float32)
    tbl = L.T @ G
    return contingency_stats(tbl)
