"""Text-processing stages.

Reference (core/.../impl/feature/, SURVEY §2.5):
 * ``OpTextTokenizer``/``TextTokenizer`` (TextTokenizer.scala:125) — the
   Lucene analyzer chain becomes a unicode-aware regex tokenizer with
   lowercasing and min-length filtering (utils/text/LuceneTextAnalyzer.scala)
 * ``OpNGram`` (OpNGram.scala), ``OpStopWordsRemover``
   (OpStopWordsRemover.scala), ``OpCountVectorizer`` (OpCountVectorizer
   .scala:44), ``OpHashingTF`` (OpHashingTF.scala:50)
 * ``OpStringIndexer``/``OpStringIndexerNoFilter`` (OpStringIndexer.scala),
   ``OpIndexToString``/``NoFilter`` (OpIndexToString.scala)
 * ``TextLenTransformer`` (TextLenTransformer.scala)
"""
from __future__ import annotations

import re
from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..stages.base import (
    SequenceEstimator, SequenceModel, UnaryEstimator, UnaryModel,
    UnaryTransformer,
)
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import (
    OPNumeric, OPVector, Real, RealNN, Text, TextList,
)
from ..utils.hashing import murmur3_32
from .vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizers import _vec_column

__all__ = [
    "TextTokenizer", "OpNGram", "OpStopWordsRemover", "OpCountVectorizer",
    "OpHashingTF", "OpStringIndexer", "OpStringIndexerNoFilter",
    "OpIndexToString", "TextLenTransformer", "ENGLISH_STOP_WORDS",
]

_TOKEN_RE = re.compile(r"[\w']+", re.UNICODE)

ENGLISH_STOP_WORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split())


class TextTokenizer(UnaryTransformer):
    """Text -> TextList of tokens (TextTokenizer.scala:125)."""

    input_types = (Text,)

    def __init__(self, to_lowercase: bool = True, min_token_length: int = 1,
                 uid: Optional[str] = None):
        super().__init__(operation_name="textTokenizer",
                         output_type=TextList, uid=uid)
        self.to_lowercase = to_lowercase
        self.min_token_length = min_token_length

    def tokenize(self, v: Optional[str]) -> List[str]:
        if v is None:
            return []
        s = v.lower() if self.to_lowercase else v
        return [t for t in _TOKEN_RE.findall(s)
                if len(t) >= self.min_token_length]

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.values):
            out[i] = tuple(self.tokenize(v))
        return FeatureColumn(TextList, out)


class OpNGram(UnaryTransformer):
    """TextList -> TextList of n-grams (OpNGram.scala)."""

    input_types = (TextList,)

    def __init__(self, n: int = 2, uid: Optional[str] = None):
        super().__init__(operation_name="ngram", output_type=TextList, uid=uid)
        if n < 1:
            raise ValueError("n must be >= 1")
        self.n = n

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, toks in enumerate(col.values):
            toks = list(toks or ())
            out[i] = tuple(" ".join(toks[j:j + self.n])
                           for j in range(len(toks) - self.n + 1))
        return FeatureColumn(TextList, out)


class OpStopWordsRemover(UnaryTransformer):
    """Drop stop words from a TextList (OpStopWordsRemover.scala)."""

    input_types = (TextList,)

    def __init__(self, stop_words: Optional[Sequence[str]] = None,
                 case_sensitive: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name="stopWordsRemover",
                         output_type=TextList, uid=uid)
        self.stop_words = list(stop_words if stop_words is not None
                               else ENGLISH_STOP_WORDS)
        self.case_sensitive = case_sensitive

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        stops = (set(self.stop_words) if self.case_sensitive
                 else {w.lower() for w in self.stop_words})
        out = np.empty(len(col), dtype=object)
        for i, toks in enumerate(col.values):
            out[i] = tuple(
                t for t in (toks or ())
                if (t if self.case_sensitive else t.lower()) not in stops)
        return FeatureColumn(TextList, out)


class OpCountVectorizer(SequenceEstimator):
    """TextList(s) -> bag-of-words counts over a learned vocabulary
    (OpCountVectorizer.scala:44)."""

    input_types = (TextList,)

    def __init__(self, vocab_size: int = 512, min_df: int = 1,
                 binary: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name="countVec", output_type=OPVector,
                         uid=uid)
        self.vocab_size = vocab_size
        self.min_df = min_df
        self.binary = binary

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        df_counts: Counter = Counter()
        for c in cols:
            for toks in c.values:
                df_counts.update(set(toks or ()))
        vocab = [w for w, n in df_counts.most_common()
                 if n >= self.min_df][: self.vocab_size]
        return OpCountVectorizerModel(vocab=sorted(vocab), binary=self.binary)


class OpCountVectorizerModel(SequenceModel):

    input_types = (TextList,)
    def __init__(self, vocab: List[str], binary: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="countVec", output_type=OPVector,
                         uid=uid)
        self.vocab = list(vocab)
        self.binary = binary

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        index = {w: i for i, w in enumerate(self.vocab)}
        n = len(cols[0])
        v = len(self.vocab)
        parts, meta = [], []
        for f, c in zip(self.input_features, cols):
            block = np.zeros((n, v), np.float32)
            for i, toks in enumerate(c.values):
                for t in toks or ():
                    j = index.get(t)
                    if j is not None:
                        block[i, j] = 1.0 if self.binary else block[i, j] + 1
            parts.append(block)
            meta.extend(VectorColumnMetadata(f.name, f.ftype.type_name(),
                                             indicator_value=w)
                        for w in self.vocab)
        return _vec_column(np.concatenate(parts, axis=1),
                           VectorMetadata("count_vec", meta))


class OpHashingTF(UnaryTransformer):
    """TextList -> hashed term frequencies (OpHashingTF.scala:50)."""

    input_types = (TextList,)

    def __init__(self, num_features: int = 512, binary: bool = False,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name="hashingTF", output_type=OPVector,
                         uid=uid)
        self.num_features = num_features
        self.binary = binary
        self.seed = seed

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        f = self.input_features[0]
        n = len(col)
        block = np.zeros((n, self.num_features), np.float32)
        for i, toks in enumerate(col.values):
            for t in toks or ():
                j = murmur3_32(t, self.seed) % self.num_features
                block[i, j] = 1.0 if self.binary else block[i, j] + 1
        meta = [VectorColumnMetadata(f.name, f.ftype.type_name(),
                                     descriptor_value=f"hash_{b}")
                for b in range(self.num_features)]
        return _vec_column(block, VectorMetadata("hash_tf", meta))


class OpStringIndexer(UnaryEstimator):
    """Text -> frequency-ranked index (OpStringIndexer.scala); unseen labels
    error ('error') or map to an extra index ('keep') per handle_invalid."""

    input_types = (Text,)

    def __init__(self, handle_invalid: str = "error",
                 uid: Optional[str] = None):
        super().__init__(operation_name="stringIndexer", output_type=RealNN,
                         uid=uid)
        if handle_invalid not in ("error", "keep", "skip"):
            raise ValueError(handle_invalid)
        self.handle_invalid = handle_invalid

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        counts = Counter(v for v in col.values if v is not None)
        labels = [w for w, _ in counts.most_common()]
        return OpStringIndexerModel(labels=labels,
                                    handle_invalid=self.handle_invalid)


class OpStringIndexerNoFilter(OpStringIndexer):
    """Unseen values map to an extra bucket (OpStringIndexerNoFilter)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(handle_invalid="keep", uid=uid)


class OpStringIndexerModel(UnaryModel):

    input_types = (Text,)
    def __init__(self, labels: List[str], handle_invalid: str = "error",
                 uid: Optional[str] = None):
        super().__init__(operation_name="stringIndexer", output_type=RealNN,
                         uid=uid)
        self.labels = list(labels)
        self.handle_invalid = handle_invalid
        self.metadata["labels"] = list(labels)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        index = {w: float(i) for i, w in enumerate(self.labels)}
        unseen = float(len(self.labels))
        out = np.zeros(len(col), np.float64)
        mask = np.ones(len(col), bool)
        for i, v in enumerate(col.values):
            j = index.get(v)
            if j is None:
                if self.handle_invalid == "error" and v is not None:
                    raise ValueError(f"unseen label {v!r}")
                if self.handle_invalid == "skip":
                    # columnar datasets can't drop rows mid-DAG, so 'skip'
                    # marks the row missing instead (masked out downstream)
                    mask[i] = False
                out[i] = unseen
            else:
                out[i] = j
        return FeatureColumn(RealNN, out, mask)


class OpIndexToString(UnaryTransformer):
    """Index -> label text (OpIndexToString.scala)."""

    input_types = (OPNumeric,)

    def __init__(self, labels: Sequence[str], unseen_name: str = "UnseenLabel",
                 uid: Optional[str] = None):
        super().__init__(operation_name="indexToString", output_type=Text,
                         uid=uid)
        self.labels = list(labels)
        self.unseen_name = unseen_name

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        vals = np.asarray(col.values)
        for i, v in enumerate(vals):
            j = int(v) if np.isfinite(v) else -1
            out[i] = (self.labels[j] if 0 <= j < len(self.labels)
                      else self.unseen_name)
        return FeatureColumn(Text, out)


class TextLenTransformer(UnaryTransformer):
    """Text/TextList -> total character length (TextLenTransformer.scala)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="textLen", output_type=RealNN, uid=uid)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.zeros(len(col), np.float64)
        for i, v in enumerate(col.values):
            if v is None:
                continue
            if isinstance(v, (tuple, list, frozenset, set)):
                out[i] = float(sum(len(t) for t in v))
            else:
                out[i] = float(len(v))
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))
