"""``transmogrify()`` — automated feature engineering dispatcher.

Reference: ``Transmogrifier`` (core/.../impl/feature/Transmogrifier.scala:92-260)
and the DSL entry ``RichFeaturesCollection.transmogrify``
(core/.../dsl/RichFeaturesCollection.scala:69): group input features by
semantic type, apply the per-type default vectorizer to each group, and
combine the resulting OPVectors into one feature vector.

Defaults mirror Transmogrifier.scala:52-90: TopK=20, MinSupport=10,
512 hash features, null tracking on, fill-with-mean/mode for numerics.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from ..features.feature import Feature
from ..types import feature_types as ft
from .date_geo import (
    DateListVectorizer, DateToUnitCircleVectorizer, GeolocationVectorizer,
)
from .map_vectorizers import transmogrify_map_group
from .vectorizers import (
    BinaryVectorizer, IntegralVectorizer, MultiPickListVectorizer,
    OneHotVectorizer, RealVectorizer, SmartTextVectorizer,
    TextHashingVectorizer, VectorsCombiner,
)

__all__ = ["transmogrify", "TransmogrifierDefaults"]


class TransmogrifierDefaults:
    TOP_K = 20
    MIN_SUPPORT = 10
    NUM_HASH_FEATURES = 512
    MAX_HASH_FEATURES = 1 << 17
    MAX_CARDINALITY = 100
    TRACK_NULLS = True
    FILL_WITH_MEAN = True
    FILL_WITH_MODE = True


# categorical text types that get a direct TopK pivot
_PIVOT_TEXT = (ft.PickList, ft.ComboBox, ft.Country, ft.State, ft.City,
               ft.PostalCode, ft.Street, ft.ID)
# free-text types that go through SmartTextVectorizer
_SMART_TEXT = (ft.Text,)


def transmogrify(
    features: Sequence[Feature],
    top_k: int = TransmogrifierDefaults.TOP_K,
    min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
    num_hash_features: int = TransmogrifierDefaults.NUM_HASH_FEATURES,
    max_cardinality: int = TransmogrifierDefaults.MAX_CARDINALITY,
    track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
) -> Feature:
    """Vectorize a heterogeneous feature set into a single OPVector feature."""
    groups: Dict[str, List[Feature]] = {}
    for f in features:
        groups.setdefault(_group_of(f.ftype), []).append(f)

    vectors: List[Feature] = []
    order = ["real", "integral", "binary", "date", "date_list", "pivot_text",
             "smart_text", "multi_pick_list", "text_list", "geolocation",
             "vector", "map"]
    for g in order:
        feats = groups.pop(g, [])
        if not feats:
            continue
        if g == "real":
            stage = RealVectorizer(track_nulls=track_nulls)
        elif g == "integral":
            stage = IntegralVectorizer(track_nulls=track_nulls)
        elif g == "binary":
            stage = BinaryVectorizer(track_nulls=track_nulls)
        elif g == "date":
            stage = DateToUnitCircleVectorizer(track_nulls=track_nulls)
        elif g == "date_list":
            # reference default pivot: SinceLast (Transmogrifier.scala:57)
            stage = DateListVectorizer(pivot="SinceLast",
                                       track_nulls=track_nulls)
        elif g == "pivot_text":
            stage = OneHotVectorizer(top_k=top_k, min_support=min_support,
                                     track_nulls=track_nulls)
        elif g == "smart_text":
            stage = SmartTextVectorizer(
                max_cardinality=max_cardinality, top_k=top_k,
                min_support=min_support, num_hash_features=num_hash_features,
                track_nulls=track_nulls)
        elif g == "multi_pick_list":
            stage = MultiPickListVectorizer(top_k=top_k, min_support=min_support,
                                            track_nulls=track_nulls)
        elif g == "text_list":
            stage = TextHashingVectorizer(num_features=num_hash_features,
                                          track_nulls=track_nulls)
        elif g == "geolocation":
            stage = GeolocationVectorizer(track_nulls=track_nulls)
        elif g == "vector":
            vectors.extend(feats)
            continue
        elif g == "map":
            vectors.extend(transmogrify_map_group(
                feats, top_k=top_k, min_support=min_support,
                num_hash_features=num_hash_features, track_nulls=track_nulls))
            continue
        stage.set_input(*feats)
        vectors.append(stage.get_output())
    if groups:
        raise TypeError(f"no default vectorizer for groups {sorted(groups)}")

    if len(vectors) == 1:
        return vectors[0]
    combiner = VectorsCombiner()
    combiner.set_input(*vectors)
    return combiner.get_output()


def _group_of(t: Type[ft.FeatureType]) -> str:
    if issubclass(t, ft.OPMap):
        return "map"
    if issubclass(t, ft.OPVector):
        return "vector"
    if issubclass(t, ft.Geolocation):
        return "geolocation"
    if issubclass(t, ft.MultiPickList):
        return "multi_pick_list"
    if issubclass(t, ft.TextList):
        return "text_list"
    if issubclass(t, ft.DateList):
        return "date_list"
    if issubclass(t, ft.Binary):
        return "binary"
    if issubclass(t, (ft.Date, ft.DateTime)):
        return "date"
    if issubclass(t, ft.Integral):
        return "integral"
    if issubclass(t, (ft.Real,)):
        return "real"
    if issubclass(t, _PIVOT_TEXT):
        return "pivot_text"
    if issubclass(t, ft.Text):
        return "smart_text"
    raise TypeError(f"cannot transmogrify feature type {t.type_name()}")
