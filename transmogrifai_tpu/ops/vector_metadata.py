"""Vector column metadata — per-slot provenance of the feature matrix.

Reference: ``OpVectorMetadata`` / ``OpVectorColumnMetadata`` /
``OpVectorColumnHistory`` (features/.../utils/spark/OpVectorMetadata.scala,
OpVectorColumnMetadata.scala, OpVectorColumnHistory.scala).  Every slot of the
assembled feature vector records which raw feature it came from, its grouping
(e.g. the pivot value or map key), the indicator value for one-hot slots, and
whether it's a null-indicator.  SanityChecker, ModelInsights and LOCO all key
off this structure, so it is designed in from the start (SURVEY §7 hard part e).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["VectorColumnMetadata", "VectorMetadata"]

OTHER_INDICATOR = "OTHER"
NULL_INDICATOR = "NullIndicatorValue"


@dataclasses.dataclass
class VectorColumnMetadata:
    """Provenance of one slot in the feature vector.

    Mirrors OpVectorColumnMetadata: parentFeatureName, parentFeatureType,
    grouping (pivot group / map key), indicatorValue (one-hot value),
    descriptorValue (e.g. 'x' / 'y' for unit-circle), index.
    """

    parent_feature: str
    parent_type: str
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None
    index: int = 0

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_INDICATOR

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_INDICATOR

    def column_name(self) -> str:
        parts = [self.parent_feature]
        if self.grouping:
            parts.append(self.grouping)
        if self.descriptor_value:
            parts.append(self.descriptor_value)
        elif self.indicator_value:
            parts.append(self.indicator_value)
        return "_".join(parts) + f"_{self.index}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "VectorColumnMetadata":
        return VectorColumnMetadata(**d)


class VectorMetadata:
    """Metadata for a whole OPVector feature: ordered slot provenance."""

    def __init__(self, name: str, columns: Sequence[VectorColumnMetadata]):
        self.name = name
        self.columns: List[VectorColumnMetadata] = list(columns)
        for i, c in enumerate(self.columns):
            c.index = i

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.column_name() for c in self.columns]

    def index_of_parent(self, parent_feature: str) -> List[int]:
        return [
            i for i, c in enumerate(self.columns) if c.parent_feature == parent_feature
        ]

    def parent_features(self) -> List[str]:
        seen: Dict[str, None] = {}
        for c in self.columns:
            seen.setdefault(c.parent_feature, None)
        return list(seen.keys())

    @staticmethod
    def flatten(name: str, parts: Sequence["VectorMetadata"]) -> "VectorMetadata":
        """Concatenate metadata of combined vectors (VectorsCombiner parity)."""
        cols: List[VectorColumnMetadata] = []
        for p in parts:
            for c in p.columns:
                cols.append(dataclasses.replace(c))
        return VectorMetadata(name, cols)

    def select(self, indices: Sequence[int]) -> "VectorMetadata":
        """Metadata after keeping only ``indices`` slots (SanityChecker drop)."""
        return VectorMetadata(
            self.name, [dataclasses.replace(self.columns[i]) for i in indices]
        )

    def to_json(self) -> dict:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: dict) -> "VectorMetadata":
        return VectorMetadata(
            d["name"], [VectorColumnMetadata.from_json(c) for c in d["columns"]]
        )

    def __repr__(self):
        return f"VectorMetadata(name={self.name!r}, size={self.size})"
