"""Default vectorizers — the building blocks of ``transmogrify()``.

Reference stages (core/.../stages/impl/feature/):
 * numeric fills + null tracking — ``RealVectorizer``/``IntegralVectorizer``
   via ``VectorizerDefaults`` (Transmogrifier defaults :52-90)
 * ``OpOneHotVectorizer``/``OneHotEstimator`` — TopK pivot with minSupport,
   OTHER and null-indicator columns (OpOneHotVectorizer.scala)
 * ``OPCollectionHashingVectorizer`` — murmur3 feature hashing
   (OPCollectionHashingVectorizer.scala:59)
 * ``SmartTextVectorizer`` — cardinality-driven strategy per text field
   (SmartTextVectorizer.scala:60,79,207-247,323)
 * ``VectorsCombiner`` — concatenates OPVectors and merges their metadata
   (VectorsCombiner.scala)

All emit float32 (N, D) matrices (device-ready; bf16 conversion happens at
model ingestion) plus a ``VectorMetadata`` recording slot provenance.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stages.base import (
    SequenceEstimator, SequenceModel, SequenceTransformer,
)
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import (
    Binary, MultiPickList, OPNumeric, OPSet, OPVector, Text, TextList,
)
from ..utils.hashing import murmur3_32
from .vector_metadata import (
    NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMetadata, VectorMetadata,
)

__all__ = [
    "RealVectorizer", "RealVectorizerModel",
    "IntegralVectorizer", "IntegralVectorizerModel",
    "BinaryVectorizer",
    "OneHotVectorizer", "OneHotVectorizerModel",
    "TextHashingVectorizer",
    "SmartTextVectorizer", "SmartTextVectorizerModel", "TextStats",
    "MultiPickListVectorizer", "MultiPickListVectorizerModel",
    "VectorsCombiner",
]


def _vec_column(arr: np.ndarray, meta: VectorMetadata) -> FeatureColumn:
    return FeatureColumn(OPVector, np.asarray(arr, dtype=np.float32), vmeta=meta)


# ---------------------------------------------------------------------------
# Drift baselines — the train-side distribution snapshot a serving-side
# DriftMonitor (serving/drift.py) compares sampled traffic against.  Each
# fitting vectorizer exports ``metadata["drift_baseline"]`` =
# {raw feature name -> baseline dict}; numeric baselines carry Welford
# moments + StreamingHistogram bins (ndarrays -> persistence externalizes
# them into arrays.npz bit-exactly), categorical baselines carry the top
# category counts.  Baselines ride on the fitted model's metadata, so they
# survive save/load and registry hot-swaps with no extra artifact.
# ---------------------------------------------------------------------------

#: histogram bin budget for numeric baselines (the PSI grid source)
_BASELINE_BINS = 32
#: stride-sample cap for the IN-CORE baseline histogram: moments stay
#: exact; the histogram only needs the distribution's shape, and an
#: unbounded np.unique over 1M-row columns would tax the headline bench
_BASELINE_SAMPLE = 65536
#: categorical baselines keep at most this many categories (rest = OTHER)
_BASELINE_CATEGORIES = 64


def _numeric_baseline(mom, hist) -> Dict[str, Any]:
    """Codec-safe numeric baseline from a WelfordMoments + histogram."""
    empty = mom.mean is None
    return {
        "kind": "numeric", "n": float(mom.n),
        "mean": 0.0 if empty else float(mom.mean),
        "m2": 0.0 if empty else float(mom.m2),
        "min": 0.0 if empty else float(mom.min),
        "max": 0.0 if empty else float(mom.max),
        "histCentroids": np.asarray(hist.centroids, np.float64),
        "histCounts": np.asarray(hist.counts, np.float64),
    }


def _categorical_baseline(values, counts, total) -> Dict[str, Any]:
    return {"kind": "categorical", "n": float(total),
            "values": [str(v) for v in values],
            "counts": np.asarray(counts, np.float64)}


def _numeric_baseline_from_values(vals: np.ndarray) -> Dict[str, Any]:
    """In-core numeric baseline: exact moments + stride-sampled histogram."""
    from ..utils.sketches import WelfordMoments
    from ..utils.streaming_histogram import StreamingHistogram

    mom = WelfordMoments().update(vals)
    stride = max(1, int(len(vals)) // _BASELINE_SAMPLE)
    hist = StreamingHistogram(_BASELINE_BINS).update(vals[::stride])
    return _numeric_baseline(mom, hist)


def _numeric_baseline_from_counts(counts: Dict[float, int]) -> Dict[str, Any]:
    """Exact numeric baseline from a value->count map (the mode fitters)."""
    from ..utils.streaming_histogram import StreamingHistogram

    if not counts:
        return _numeric_baseline_from_values(np.zeros(0, np.float64))
    v = np.asarray(list(counts.keys()), np.float64)
    c = np.asarray(list(counts.values()), np.float64)
    n = float(c.sum())
    mean = float((v * c).sum() / n)
    hist = StreamingHistogram.from_value_counts(v, c, _BASELINE_BINS)
    return {
        "kind": "numeric", "n": n, "mean": mean,
        "m2": float((c * (v - mean) ** 2).sum()),
        "min": float(v.min()), "max": float(v.max()),
        "histCentroids": np.asarray(hist.centroids, np.float64),
        "histCounts": np.asarray(hist.counts, np.float64),
    }


def _categorical_baseline_from_sketch(sk) -> Dict[str, Any]:
    """Baseline from a TopKSketch: top categories by (count, first-seen)."""
    ordered = sorted(sk.counts.items(),
                     key=lambda kv: (-kv[1][0], kv[1][1]))
    top = ordered[:_BASELINE_CATEGORIES]
    return _categorical_baseline([k for k, _ in top],
                                 [ent[0] for _, ent in top], sk.offset)


def _pivot_fit(values, top_k: int, min_support: int):
    """(vocab, baseline) in ONE vectorized ``np.unique`` pass.

    The vocab half replaces the per-row Python ``Counter`` loop (the hot
    part of the OneHot/MultiPickList fit at scale) while reproducing
    ``Counter.most_common(top_k)`` EXACTLY, including its tie order: keys
    tie-break by insertion order = first occurrence, so rank by
    ``(-count, first_index)``.  Falls back to the Counter loop for values
    ``np.unique`` cannot sort (mixed/unhashable-by-comparison cells).
    The baseline half reuses the same pass for the drift snapshot.
    """
    arr = np.asarray(list(values) if not isinstance(values, np.ndarray)
                     else values, dtype=object)
    total = int(arr.size)
    if total == 0:
        return [], _categorical_baseline([], [], 0)
    try:
        uniq, first, cnt = np.unique(arr, return_index=True,
                                     return_counts=True)
    except TypeError:  # non-comparable mix: keep the legacy loop semantics
        counts = Counter(arr.tolist())
        vocab = [v for v, n in counts.most_common(top_k) if n >= min_support]
        top = counts.most_common(_BASELINE_CATEGORIES)
        return vocab, _categorical_baseline(
            [v for v, _ in top], [n for _, n in top], total)
    order = np.lexsort((first, -cnt))
    vocab = [uniq[i] for i in order[:top_k] if cnt[i] >= min_support]
    keep = order[:_BASELINE_CATEGORIES]
    return vocab, _categorical_baseline(uniq[keep], cnt[keep], total)


def _pivot_vocab(values, top_k: int, min_support: int) -> List:
    """TopK pivot vocabulary (see ``_pivot_fit`` for the semantics)."""
    return _pivot_fit(values, top_k, min_support)[0]


# ---------------------------------------------------------------------------
# Numerics
# ---------------------------------------------------------------------------

class RealVectorizer(SequenceEstimator):
    """Fill missing reals (mean or constant) + optional null-indicator slots.

    Transmogrifier default for Real/Percent/Currency: FillWithMean + null
    tracking (Transmogrifier.scala:52-90).
    """

    input_types = (OPNumeric,)
    # Welford-merged means are order-insensitive up to float noise
    streaming_order_insensitive = True

    def __init__(self, fill_with_mean: bool = True, fill_value: float = 0.0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecReal", output_type=OPVector, uid=uid)
        self.fill_with_mean = fill_with_mean
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        fills = []
        baseline = {}
        for f, c in zip(self.input_features, cols):
            vals = np.asarray(c.values, dtype=np.float64)
            m = np.asarray(c.mask)
            present = np.nan_to_num(vals)[m]
            if self.fill_with_mean:
                fills.append(float(present.mean()) if m.any()
                             else self.fill_value)
            else:
                fills.append(float(self.fill_value))
            baseline[f.name] = _numeric_baseline_from_values(present)
        self.metadata["drift_baseline"] = baseline
        return RealVectorizerModel(fills=fills, track_nulls=self.track_nulls)

    # -- streaming fit: Welford moments + histogram bins per column ---------
    # Chunked means match the in-core fit to ~1e-12 relative (documented:
    # chunked float64 summation order vs numpy's pairwise sum).  The
    # histogram feeds only the drift baseline, never the fill.

    supports_streaming_fit = True

    def begin_fit(self):
        from ..utils.sketches import WelfordMoments
        from ..utils.streaming_histogram import StreamingHistogram

        return [{"mom": WelfordMoments(),
                 "hist": StreamingHistogram(_BASELINE_BINS)}
                for _ in self.input_features]

    def update_chunk(self, state, data, *cols):
        for st, c in zip(state, cols):
            vals = np.nan_to_num(np.asarray(c.values, dtype=np.float64))
            present = vals[np.asarray(c.mask)]
            st["mom"].update(present)
            st["hist"].update(present)
        return state

    def merge_states(self, a, b):
        return [{"mom": sa["mom"].merge(sb["mom"]),
                 "hist": sa["hist"].merge(sb["hist"])}
                for sa, sb in zip(a, b)]

    def finish_fit(self, state):
        fills = [float(st["mom"].mean)
                 if self.fill_with_mean and st["mom"].n > 0
                 else float(self.fill_value) for st in state]
        self.metadata["drift_baseline"] = {
            f.name: _numeric_baseline(st["mom"], st["hist"])
            for f, st in zip(self.input_features, state)}
        return RealVectorizerModel(fills=fills, track_nulls=self.track_nulls)


class RealVectorizerModel(SequenceModel):
    input_types = (OPNumeric,)

    def __init__(self, fills: List[float], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecReal", output_type=OPVector, uid=uid)
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        width = len(cols) * (2 if self.track_nulls else 1)
        # Build through a small TRANSPOSED group buffer: writing column j of
        # a C-order (n, width) matrix directly strides `width` floats per
        # element — 500 wide columns at 1M rows turn into all-cache-miss
        # writes (measured 67 s host time at the 1M×500 bench).  Contiguous
        # buffer-row writes + grouped transpose flushes (destination runs of
        # GROUP floats per row) are ~10x faster, and the buffer bounds the
        # extra peak memory to ~128 MB instead of a full second matrix.
        out = np.empty((n, width), dtype=np.float32)
        group = int(np.clip((128 << 20) // max(n * 4, 1), 1, width))
        buf = np.empty((group, n), dtype=np.float32)
        meta = []
        j = 0
        flushed = 0

        def flush(upto):
            nonlocal flushed
            if upto > flushed:
                out[:, flushed:upto] = buf[: upto - flushed].T
                flushed = upto

        def put(row_vals):
            nonlocal j
            if j - flushed == group:
                flush(j)
            np.copyto(buf[j - flushed], row_vals)
            j += 1

        for f, fill, c in zip(self.input_features, self.fills, cols):
            vals = np.asarray(c.values, dtype=np.float32)
            m = np.asarray(c.mask)
            row = np.where(m, vals, np.float32(fill))
            # clamp non-finite survivors (producers that don't fold isfinite
            # into the mask, or float32-cast overflow): NaN -> 0, inf -> max
            np.nan_to_num(row, copy=False)
            put(row)
            meta.append(VectorColumnMetadata(f.name, f.ftype.type_name()))
            if self.track_nulls:
                put(~m)
                meta.append(VectorColumnMetadata(
                    f.name, f.ftype.type_name(), indicator_value=NULL_INDICATOR))
        flush(j)
        return _vec_column(out, VectorMetadata(self.get_output().name if self._output_feature else "real_vec", meta))


class IntegralVectorizer(SequenceEstimator):
    """Fill missing integrals with mode + null tracking (Transmogrifier default)."""

    input_types = (OPNumeric,)
    # merged mode counts are exact; ties break by smallest value, not order
    streaming_order_insensitive = True

    def __init__(self, fill_with_mode: bool = True, fill_value: int = 0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecIntegral", output_type=OPVector, uid=uid)
        self.fill_with_mode = fill_with_mode
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        fills = []
        baseline = {}
        for f, c in zip(self.input_features, cols):
            vals = np.asarray(c.values)[np.asarray(c.mask)]
            counts: Dict[float, int] = {}
            if len(vals):
                uniq, cnt = np.unique(vals, return_counts=True)
                counts = {float(v): int(n) for v, n in zip(uniq, cnt)}
            if self.fill_with_mode and counts:
                fills.append(float(uniq[np.argmax(cnt)]))
            else:
                fills.append(float(self.fill_value))
            baseline[f.name] = _numeric_baseline_from_counts(counts)
        self.metadata["drift_baseline"] = baseline
        return RealVectorizerModel(fills=fills, track_nulls=self.track_nulls)

    # -- streaming fit: mergeable value counts per column (mode fill) -------
    # EXACT vs in-core: the in-core argmax over ascending-sorted uniques
    # picks the smallest value among tied modes, replicated in finish_fit.

    supports_streaming_fit = True

    def begin_fit(self):
        return [dict() for _ in self.input_features]

    def update_chunk(self, state, data, *cols):
        for counts, c in zip(state, cols):
            vals = np.asarray(c.values)[np.asarray(c.mask)]
            if len(vals):
                uniq, cnt = np.unique(vals, return_counts=True)
                for v, n in zip(uniq, cnt):
                    counts[float(v)] = counts.get(float(v), 0) + int(n)
        return state

    def merge_states(self, a, b):
        for ca, cb in zip(a, b):
            for v, n in cb.items():
                ca[v] = ca.get(v, 0) + n
        return a

    def finish_fit(self, state):
        fills = []
        for counts in state:
            if self.fill_with_mode and counts:
                best = max(counts.items(), key=lambda kv: (kv[1], -kv[0]))
                fills.append(float(best[0]))
            else:
                fills.append(float(self.fill_value))
        self.metadata["drift_baseline"] = {
            f.name: _numeric_baseline_from_counts(counts)
            for f, counts in zip(self.input_features, state)}
        return RealVectorizerModel(fills=fills, track_nulls=self.track_nulls)


class BinaryVectorizer(SequenceTransformer):
    """Binary -> {0,1} with fill + null tracking (stateless)."""

    input_types = (OPNumeric,)

    def __init__(self, fill_value: bool = False, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecBinary", output_type=OPVector, uid=uid)
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        parts, meta = [], []
        for f, c in zip(self.input_features, cols):
            vals = np.nan_to_num(np.asarray(c.values, dtype=np.float64))
            m = np.asarray(c.mask)
            parts.append(np.where(m, vals, float(self.fill_value)))
            meta.append(VectorColumnMetadata(f.name, f.ftype.type_name()))
            if self.track_nulls:
                parts.append(~m)
                meta.append(VectorColumnMetadata(
                    f.name, f.ftype.type_name(), indicator_value=NULL_INDICATOR))
        return _vec_column(np.stack(parts, axis=1),
                           VectorMetadata("binary_vec", meta))


# ---------------------------------------------------------------------------
# Categorical pivot (one-hot)
# ---------------------------------------------------------------------------

class OneHotVectorizer(SequenceEstimator):
    """TopK pivot of categorical text with OTHER + null indicator columns.

    Reference OpOneHotVectorizer.scala; defaults TopK=20, minSupport=10
    (Transmogrifier.scala:55-60).
    """

    input_types = (Text,)

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, unseen_to_other: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="pivotText", output_type=OPVector, uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        self.unseen_to_other = unseen_to_other

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        vocabs: List[List[str]] = []
        baseline = {}
        for f, c in zip(self.input_features, cols):
            # vectorized count (one np.unique) instead of the per-row
            # Counter loop; _pivot_fit reproduces most_common exactly and
            # yields the drift baseline from the same pass
            vals = c.values[np.not_equal(c.values, None)]
            vocab, base = _pivot_fit(vals, self.top_k, self.min_support)
            vocabs.append(vocab)
            baseline[f.name] = base
        self.metadata["drift_baseline"] = baseline
        return OneHotVectorizerModel(
            vocabs=vocabs, track_nulls=self.track_nulls,
            unseen_to_other=self.unseen_to_other)

    # -- streaming fit: mergeable top-k counting per column -----------------

    supports_streaming_fit = True

    def begin_fit(self):
        from ..utils.sketches import TopKSketch

        return [TopKSketch() for _ in self.input_features]

    def update_chunk(self, state, data, *cols):
        for sk, c in zip(state, cols):
            sk.add_chunk(c.values[np.not_equal(c.values, None)])
        return state

    def merge_states(self, a, b):
        return [sa.merge(sb) for sa, sb in zip(a, b)]

    def finish_fit(self, state):
        vocabs = [sk.top_k(self.top_k, self.min_support) for sk in state]
        self.metadata["drift_baseline"] = {
            f.name: _categorical_baseline_from_sketch(sk)
            for f, sk in zip(self.input_features, state)}
        return OneHotVectorizerModel(
            vocabs=vocabs, track_nulls=self.track_nulls,
            unseen_to_other=self.unseen_to_other)


class OneHotVectorizerModel(SequenceModel):
    input_types = (Text,)

    def __init__(self, vocabs: List[List[str]], track_nulls: bool = True,
                 unseen_to_other: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="pivotText", output_type=OPVector, uid=uid)
        self.vocabs = vocabs
        self.track_nulls = track_nulls
        self.unseen_to_other = unseen_to_other

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts, meta = [], []
        for f, vocab, c in zip(self.input_features, self.vocabs, cols):
            index = {v: i for i, v in enumerate(vocab)}
            k = len(vocab)
            width = k + (1 if self.unseen_to_other else 0) + (1 if self.track_nulls else 0)
            block = np.zeros((n, width), dtype=np.float32)
            for row, v in enumerate(c.values):
                if v is None:
                    if self.track_nulls:
                        block[row, width - 1] = 1.0
                elif v in index:
                    block[row, index[v]] = 1.0
                elif self.unseen_to_other:
                    block[row, k] = 1.0
            parts.append(block)
            tname = f.ftype.type_name()
            for v in vocab:
                meta.append(VectorColumnMetadata(
                    f.name, tname, grouping=f.name, indicator_value=v))
            if self.unseen_to_other:
                meta.append(VectorColumnMetadata(
                    f.name, tname, grouping=f.name, indicator_value=OTHER_INDICATOR))
            if self.track_nulls:
                meta.append(VectorColumnMetadata(
                    f.name, tname, grouping=f.name, indicator_value=NULL_INDICATOR))
        out = np.concatenate(parts, axis=1) if parts else np.zeros((n, 0), np.float32)
        return _vec_column(out, VectorMetadata("onehot_vec", meta))


class MultiPickListVectorizer(SequenceEstimator):
    """TopK multi-hot pivot of MultiPickList sets (OpSetVectorizer parity)."""

    input_types = (OPSet,)

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="pivotSet", output_type=OPVector, uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        vocabs = []
        baseline = {}
        for f, c in zip(self.input_features, cols):
            # multi-valued cells: flatten once, then one vectorized
            # np.unique — the flattened order equals Counter.update(s)'s
            # insertion order, so ties still break identically
            flat = [v for s in c.values for v in s]
            vocab, base = _pivot_fit(flat, self.top_k, self.min_support)
            vocabs.append(vocab)
            baseline[f.name] = base
        self.metadata["drift_baseline"] = baseline
        return MultiPickListVectorizerModel(vocabs=vocabs, track_nulls=self.track_nulls)

    # -- streaming fit: mergeable top-k over flattened set elements ---------

    supports_streaming_fit = True

    def begin_fit(self):
        from ..utils.sketches import TopKSketch

        return [TopKSketch() for _ in self.input_features]

    def update_chunk(self, state, data, *cols):
        for sk, c in zip(state, cols):
            sk.add_chunk([v for s in c.values for v in s])
        return state

    def merge_states(self, a, b):
        return [sa.merge(sb) for sa, sb in zip(a, b)]

    def finish_fit(self, state):
        vocabs = [sk.top_k(self.top_k, self.min_support) for sk in state]
        self.metadata["drift_baseline"] = {
            f.name: _categorical_baseline_from_sketch(sk)
            for f, sk in zip(self.input_features, state)}
        return MultiPickListVectorizerModel(vocabs=vocabs,
                                            track_nulls=self.track_nulls)


class MultiPickListVectorizerModel(SequenceModel):
    input_types = (OPSet,)

    def __init__(self, vocabs: List[List[str]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="pivotSet", output_type=OPVector, uid=uid)
        self.vocabs = vocabs
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts, meta = [], []
        for f, vocab, c in zip(self.input_features, self.vocabs, cols):
            index = {v: i for i, v in enumerate(vocab)}
            k = len(vocab)
            width = k + 1 + (1 if self.track_nulls else 0)
            block = np.zeros((n, width), dtype=np.float32)
            for row, s in enumerate(c.values):
                if not s:
                    if self.track_nulls:
                        block[row, width - 1] = 1.0
                    continue
                hit = False
                for v in s:
                    if v in index:
                        block[row, index[v]] = 1.0
                        hit = True
                if not hit:
                    block[row, k] = 1.0
            parts.append(block)
            tname = f.ftype.type_name()
            for v in vocab:
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=v))
            meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                             indicator_value=OTHER_INDICATOR))
            if self.track_nulls:
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1),
                           VectorMetadata("set_vec", meta))


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------

def _tokenize(v: Optional[str]) -> List[str]:
    if v is None:
        return []
    return [t for t in _TOKEN_SPLIT.split(v.lower()) if t]


import re
_TOKEN_SPLIT = re.compile(r"[^\w']+", re.UNICODE)


def _row_tokens(v) -> List[str]:
    """Tokens for one cell: strings are word-tokenized; collection cells
    (lists/sets of arbitrary values, e.g. DateList epoch ints) hash their
    elements' string forms."""
    if v is None:
        return []
    if isinstance(v, str):
        return _tokenize(v)
    return [str(t) for t in v]


def _hash_rows(values, block: np.ndarray, offset: int, nf: int, seed: int,
               binary_freq: bool = False) -> np.ndarray:
    """Scatter token counts of one column into ``block[:, offset:offset+nf]``;
    returns a bool array marking rows with no tokens (null rows).
    Shared by TextHashingVectorizer and SmartTextVectorizerModel."""
    cache: Dict[str, int] = {}
    empty = np.zeros(len(values), dtype=bool)
    for row, v in enumerate(values):
        toks = _row_tokens(v)
        if not toks:
            empty[row] = True
            continue
        for t in toks:
            b = cache.get(t)
            if b is None:
                b = murmur3_32(t, seed) % nf
                cache[t] = b
            if binary_freq:
                block[row, offset + b] = 1.0
            else:
                block[row, offset + b] += 1.0
    return empty


class TextHashingVectorizer(SequenceTransformer):
    """Murmur3 feature hashing of tokenized text (stateless).

    Reference OPCollectionHashingVectorizer / hashed text path of
    SmartTextVectorizer; default 512 buckets (Transmogrifier.scala:55).
    ``shared_hash_space``: one bucket space for all inputs vs per-feature
    (HashSpaceStrategy parity).
    """

    def __init__(self, num_features: int = 512, binary_freq: bool = False,
                 shared_hash_space: bool = False, track_nulls: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name="textHash", output_type=OPVector, uid=uid)
        self.num_features = num_features
        self.binary_freq = binary_freq
        self.shared_hash_space = shared_hash_space
        self.track_nulls = track_nulls
        self.seed = seed

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        nf = self.num_features
        n_spaces = 1 if self.shared_hash_space else len(cols)
        hashed = np.zeros((n, n_spaces * nf), dtype=np.float32)
        nulls = np.zeros((n, len(cols)), dtype=np.float32)
        for ci, c in enumerate(cols):
            offset = 0 if self.shared_hash_space else ci * nf
            empty = _hash_rows(c.values, hashed, offset, nf, self.seed,
                               self.binary_freq)
            nulls[:, ci] = empty
        meta: List[VectorColumnMetadata] = []
        if self.shared_hash_space:
            pf = ",".join(f.name for f in self.input_features)
            for b in range(nf):
                meta.append(VectorColumnMetadata(pf, "Text", grouping=None,
                                                 descriptor_value=f"hash_{b}"))
        else:
            for f in self.input_features:
                for b in range(nf):
                    meta.append(VectorColumnMetadata(f.name, f.ftype.type_name(),
                                                     descriptor_value=f"hash_{b}"))
        parts = [hashed]
        if self.track_nulls:
            parts.append(nulls)
            for f in self.input_features:
                meta.append(VectorColumnMetadata(f.name, f.ftype.type_name(),
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1),
                           VectorMetadata("hash_vec", meta))


# ---------------------------------------------------------------------------
# SmartTextVectorizer
# ---------------------------------------------------------------------------

class TextStats:
    """Streaming text statistics monoid (SmartTextVectorizer.scala:207-247)."""

    def __init__(self, max_card: int = 100):
        self.max_card = max_card
        self.value_counts: Counter = Counter()
        self.length_counts: Counter = Counter()
        self.n = 0
        self.n_null = 0
        self.saturated = False

    def update(self, v: Optional[str]):
        self.n += 1
        if v is None:
            self.n_null += 1
            return
        self.length_counts[len(v)] += 1
        if not self.saturated:
            self.value_counts[v] += 1
            if len(self.value_counts) > self.max_card:
                self.saturated = True

    @property
    def cardinality(self) -> int:
        return len(self.value_counts)

    def merge(self, other: "TextStats") -> "TextStats":
        out = TextStats(self.max_card)
        out.value_counts = self.value_counts + other.value_counts
        out.length_counts = self.length_counts + other.length_counts
        out.n = self.n + other.n
        out.n_null = self.n_null + other.n_null
        out.saturated = (
            self.saturated or other.saturated
            or len(out.value_counts) > out.max_card
        )
        return out

    # -- checkpoint codec hooks (workflow/checkpoint.py) --------------------

    def to_state(self) -> dict:
        """Counter insertion order is the ``most_common`` tie order, so
        keys/counts persist as parallel ordered lists."""
        return {"max_card": self.max_card,
                "values": list(self.value_counts.keys()),
                "value_ns": list(self.value_counts.values()),
                "lengths": list(self.length_counts.keys()),
                "length_ns": list(self.length_counts.values()),
                "n": self.n, "n_null": self.n_null,
                "saturated": self.saturated}

    @classmethod
    def from_state(cls, state: dict) -> "TextStats":
        out = cls(int(state["max_card"]))
        out.value_counts = Counter(dict(zip(state["values"],
                                            state["value_ns"])))
        out.length_counts = Counter(dict(zip(
            (int(k) for k in state["lengths"]), state["length_ns"])))
        out.n = int(state["n"])
        out.n_null = int(state["n_null"])
        out.saturated = bool(state["saturated"])
        return out


class SmartTextVectorizer(SequenceEstimator):
    """Cardinality-driven text strategy: pivot / hash / ignore per field.

    Reference SmartTextVectorizer.scala:60,79,323 — computes TextStats per
    field then chooses: categorical pivot when cardinality <= max_cardinality,
    murmur3 hashing otherwise, ignore when the field is effectively empty.
    """

    input_types = (Text,)

    PIVOT, HASH, IGNORE = "pivot", "hash", "ignore"

    def __init__(self, max_cardinality: int = 100, top_k: int = 20,
                 min_support: int = 10, num_hash_features: int = 512,
                 auto_detect_languages: bool = False,
                 min_fill_rate: float = 0.001, track_nulls: bool = True,
                 track_text_len: bool = False, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtVec", output_type=OPVector, uid=uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hash_features = num_hash_features
        self.auto_detect_languages = auto_detect_languages
        self.min_fill_rate = min_fill_rate
        self.track_nulls = track_nulls
        self.track_text_len = track_text_len
        self.seed = seed

    def _decide(self, stats_list: List[TextStats]):
        """Strategy + vocab per field from fitted TextStats (shared by the
        in-core fit and the streaming finish — TextStats is already a
        mergeable monoid, SmartTextVectorizer.scala:207-247)."""
        strategies, vocabs = [], []
        baseline = {}
        for f, stats in zip(self.input_features, stats_list):
            fill = (stats.n - stats.n_null) / max(stats.n, 1)
            if fill < self.min_fill_rate:
                strategies.append(self.IGNORE)
                vocabs.append([])
            elif not stats.saturated and stats.cardinality <= self.max_cardinality:
                strategies.append(self.PIVOT)
                vocabs.append([
                    v for v, cnt in stats.value_counts.most_common(self.top_k)
                    if cnt >= self.min_support
                ])
            else:
                strategies.append(self.HASH)
                vocabs.append([])
            if not stats.saturated and stats.value_counts:
                # low-cardinality fields get a categorical drift baseline;
                # hashed/saturated text has no bounded category space
                top = stats.value_counts.most_common(_BASELINE_CATEGORIES)
                baseline[f.name] = _categorical_baseline(
                    [v for v, _ in top], [cnt for _, cnt in top],
                    stats.n - stats.n_null)
        self.metadata["text_strategies"] = dict(
            zip([f.name for f in self.input_features], strategies))
        self.metadata["drift_baseline"] = baseline
        return SmartTextVectorizerModel(
            strategies=strategies, vocabs=vocabs,
            num_hash_features=self.num_hash_features,
            track_nulls=self.track_nulls, track_text_len=self.track_text_len,
            seed=self.seed)

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        stats_list = []
        for c in cols:
            stats = TextStats(self.max_cardinality)
            for v in c.values:
                stats.update(v)
            stats_list.append(stats)
        return self._decide(stats_list)

    # -- streaming fit: per-chunk TextStats merged left-to-right ------------
    # Exact vs in-core: saturation/decision logic only consults complete
    # counts (any chunk that saturates forces HASH in both paths), and
    # Counter.__add__ preserves global first-occurrence tie order.

    supports_streaming_fit = True

    def begin_fit(self):
        return [TextStats(self.max_cardinality) for _ in self.input_features]

    def update_chunk(self, state, data, *cols):
        new = []
        for stats, c in zip(state, cols):
            chunk_stats = TextStats(self.max_cardinality)
            for v in c.values:
                chunk_stats.update(v)
            new.append(stats.merge(chunk_stats))
        return new

    def merge_states(self, a, b):
        return [sa.merge(sb) for sa, sb in zip(a, b)]

    def finish_fit(self, state):
        return self._decide(state)


class SmartTextVectorizerModel(SequenceModel):
    input_types = (Text,)

    def __init__(self, strategies: List[str], vocabs: List[List[str]],
                 num_hash_features: int = 512, track_nulls: bool = True,
                 track_text_len: bool = False, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(operation_name="smartTxtVec", output_type=OPVector, uid=uid)
        self.strategies = strategies
        self.vocabs = vocabs
        self.num_hash_features = num_hash_features
        self.track_nulls = track_nulls
        self.track_text_len = track_text_len
        self.seed = seed

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        n = len(cols[0])
        parts: List[np.ndarray] = []
        meta: List[VectorColumnMetadata] = []
        nf = self.num_hash_features
        for f, strat, vocab, c in zip(
            self.input_features, self.strategies, self.vocabs, cols
        ):
            tname = f.ftype.type_name()
            if strat == SmartTextVectorizer.IGNORE:
                pass
            elif strat == SmartTextVectorizer.PIVOT:
                index = {v: i for i, v in enumerate(vocab)}
                k = len(vocab)
                block = np.zeros((n, k + 1), dtype=np.float32)
                for row, v in enumerate(c.values):
                    if v is None:
                        continue
                    j = index.get(v)
                    if j is None:
                        block[row, k] = 1.0
                    else:
                        block[row, j] = 1.0
                parts.append(block)
                for v in vocab:
                    meta.append(VectorColumnMetadata(f.name, tname,
                                                     grouping=f.name,
                                                     indicator_value=v))
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=OTHER_INDICATOR))
            else:  # HASH
                block = np.zeros((n, nf), dtype=np.float32)
                _hash_rows(c.values, block, 0, nf, self.seed)
                parts.append(block)
                for b in range(nf):
                    meta.append(VectorColumnMetadata(f.name, tname,
                                                     descriptor_value=f"hash_{b}"))
            if self.track_text_len:
                lens = np.array([
                    0.0 if v is None else float(len(v)) for v in c.values
                ], dtype=np.float32)[:, None]
                parts.append(lens)
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value="textLen"))
            if self.track_nulls:
                nulls = np.array([v is None for v in c.values],
                                 dtype=np.float32)[:, None]
                parts.append(nulls)
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        out = (np.concatenate(parts, axis=1)
               if parts else np.zeros((n, 0), np.float32))
        return _vec_column(out, VectorMetadata("smart_text_vec", meta))


# ---------------------------------------------------------------------------
# Combiner
# ---------------------------------------------------------------------------

class VectorsCombiner(SequenceTransformer):
    """Concatenate OPVector inputs + merge metadata (VectorsCombiner.scala)."""

    input_types = (OPVector,)

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="combineVecs", output_type=OPVector, uid=uid)

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        mats = [np.asarray(c.values, dtype=np.float32) for c in cols]
        metas = []
        for c, f in zip(cols, self.input_features):
            if c.vmeta is not None:
                metas.append(c.vmeta)
            else:
                metas.append(VectorMetadata(f.name, [
                    VectorColumnMetadata(f.name, f.ftype.type_name(),
                                         descriptor_value=f"slot_{i}")
                    for i in range(mats[len(metas)].shape[1])
                ]))
        out_name = self._output_feature.name if self._output_feature else "features"
        vm = VectorMetadata.flatten(out_name, metas)
        self.metadata["vector_metadata"] = vm.to_json()
        return _vec_column(np.concatenate(mats, axis=1), vm)
