"""Mesh / sharding substrate (SURVEY §2.12, §5.8 — Spark → JAX mapping)."""
from .elastic import (
    ElasticContext, ElasticCounters, classify_sweep_error, is_device_loss,
    shrink_mesh,
)
from .ingest import ShardedMatrixWriter, stream_to_mesh
from .mesh import (
    auto_grid_axis, data_sharding, feature_sharding, fold_weight_sharding,
    grid_sharding, has_grid_axis, make_mesh, make_sweep_mesh,
    matrix_sharding, pad_to_multiple, replicated, shard_dataset,
    shard_sweep_inputs, sweep_matrix_sharding,
)
from .sharded import (
    TrainStepState, colstats_corr_sharded, colstats_psum,
    fit_logreg_newton_psum, fit_logreg_sharded, full_train_step,
    grow_forest_sharded, histogram_psum, make_train_step,
)

__all__ = [
    "make_mesh", "make_sweep_mesh", "auto_grid_axis", "has_grid_axis",
    "data_sharding", "feature_sharding", "matrix_sharding",
    "sweep_matrix_sharding", "grid_sharding", "fold_weight_sharding",
    "replicated", "shard_dataset", "pad_to_multiple", "shard_sweep_inputs",
    "TrainStepState", "full_train_step", "make_train_step",
    "fit_logreg_sharded", "grow_forest_sharded", "colstats_corr_sharded",
    "colstats_psum", "fit_logreg_newton_psum", "histogram_psum",
    "ShardedMatrixWriter", "stream_to_mesh",
    "ElasticContext", "ElasticCounters", "classify_sweep_error",
    "is_device_loss", "shrink_mesh",
]
