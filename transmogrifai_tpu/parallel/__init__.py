"""Mesh / sharding substrate (SURVEY §2.12, §5.8 — Spark → JAX mapping)."""
from .mesh import (
    data_sharding, feature_sharding, make_mesh, matrix_sharding,
    pad_to_multiple, replicated, shard_dataset,
)
from .sharded import (
    TrainStepState, colstats_corr_sharded, fit_logreg_sharded,
    full_train_step, grow_forest_sharded, make_train_step,
)

__all__ = [
    "make_mesh", "data_sharding", "feature_sharding", "matrix_sharding",
    "replicated", "shard_dataset", "pad_to_multiple",
    "TrainStepState", "full_train_step", "make_train_step",
    "fit_logreg_sharded", "grow_forest_sharded", "colstats_corr_sharded",
]
