"""Elastic sweep execution — survive device loss, degrade, keep finishing.

Real TPU fleets are preemptible and resize under you (cf. the TPU
serving/fine-tuning comparison in PAPERS.md): a chip drops mid-sweep, the
backend restarts, or a preempted pod comes back smaller.  Before this
module the pod-scale selector sweep (parallel/mesh.py + selector/
validators.py) answered every one of those with an aborted train — the
only recovery was bench.py's whole-process re-exec.  This module holds
the pieces that turn "restartable" into "finishes anyway":

* :func:`is_device_loss` / :func:`classify_sweep_error` — the shared
  classifier for backend/XLA runtime errors, promoted out of bench.py's
  ``_is_backend_unavailable`` taxonomy so every sweep-unit exception
  handler routes through ONE list of needles (the TM046 lint pins this:
  a broad ``except Exception`` around sweep-unit execution that does not
  consult the classifier is a static error).
* :class:`ElasticCounters` — retries / mesh shrinks / quarantined units /
  watchdog fires / device losses, mirrored into the global
  ``utils.profiling`` run counters so bench JSON and selector metadata
  report the same numbers.
* :class:`ElasticContext` — the per-sweep policy object the
  ``SweepWorkQueue`` consults: bounded per-unit retry on device loss
  (shrinking the mesh between attempts, ultimately to the single-device
  CPU path), the opt-in straggler watchdog (per-unit deadlines at
  ``factor x CostModel.predict``, escalating timeout -> degraded re-run
  -> quarantine), and the checkpoint flush that makes completed work
  durable before a risky retry.
* :func:`shrink_mesh` — rebuild a smaller ("data", "grid") sweep mesh
  from the devices that still answer; ``None`` means "no mesh left, fit
  single-device".

Testability: ``utils.faults`` gained the ``device_loss`` action and the
``unit.slow`` / ``device.loss`` injection points (fired at the top of
every sweep-unit attempt), so the whole escalation matrix is
seed-deterministically exercised in tests/test_elastic.py without ever
needing a chip to actually die.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "is_device_loss", "classify_sweep_error", "DEVICE_LOSS_NEEDLES",
    "ElasticCounters", "ElasticContext", "shrink_mesh",
    "run_with_deadline",
]

#: message fragments that say the accelerator BACKEND is missing/broken —
#: as opposed to a workload failure (a diverging candidate, a shape
#: error).  Superset of bench.py's ``_is_backend_unavailable`` needles
#: (that function now delegates here) plus the runtime device-loss shapes
#: XLA raises mid-execution and the fault harness's injected form.
DEVICE_LOSS_NEEDLES = (
    "Unable to initialize backend",
    "backend setup/compile error",
    "No visible TPU",
    "failed to connect to all addresses",
    "UNAVAILABLE: TPU",
    "DEVICE_LOST",
    "device is lost",
    "Device or resource busy",
    "injected device loss",
)


def is_device_loss(e: BaseException) -> bool:
    """True when ``e`` says a device/backend died — the recoverable-by-
    degrading class — rather than the workload itself failing."""
    from ..utils.faults import DeviceLossError

    if isinstance(e, DeviceLossError):
        return True
    msg = f"{type(e).__name__}: {e}"
    return any(s in msg for s in DEVICE_LOSS_NEEDLES)


def classify_sweep_error(e: BaseException) -> str:
    """``"device_loss"`` | ``"workload"`` — the routing decision every
    sweep-unit exception handler must make (lint rule TM046)."""
    return "device_loss" if is_device_loss(e) else "workload"


def surviving_devices():
    """Devices that still answer, or ``[]`` when the backend itself is
    gone (at which point the caller falls back to single-device CPU —
    jax re-inits lazily on the next host-path fit)."""
    try:
        import jax

        return list(jax.devices())
    except Exception:
        return []


def shrink_mesh(mesh, queue_width: int = 1):
    """The next smaller ("data", "grid") sweep mesh from the surviving
    devices, or ``None`` when one (or zero) device remains — the signal
    to drop to the single-device fit path.

    The returned mesh is pure data-parallel (grid axis 1): after a loss
    the grid groups are stripped anyway (their compiled programs target
    the dead mesh), so the degraded mode is sequential mesh-sharded fits.
    """
    from .mesh import make_sweep_mesh

    prev = 1
    if mesh is not None:
        prev = 1
        for name in mesh.axis_names:
            prev *= int(mesh.shape[name])
    devs = surviving_devices()
    n = min(len(devs), max(prev // 2, 1))
    # largest power of two <= n keeps the data axis tiling trivial
    p = 1
    while p * 2 <= n:
        p *= 2
    if p <= 1:
        return None
    return make_sweep_mesh(queue_width, n_devices=p, grid_parallelism=1)


def mesh_device_count(mesh) -> int:
    """Devices a mesh spans (1 for ``None`` — the single-chip path)."""
    if mesh is None:
        return 1
    n = 1
    for name in mesh.axis_names:
        n *= int(mesh.shape[name])
    return n


@dataclass
class ElasticCounters:
    """The elastic-execution scoreboard for one sweep.

    Mirrored increment-by-increment into the global profiling counters
    (``utils.profiling.count_elastic``) so ``benchmarks/*_latest.json``
    and ``model_selector_summary`` metadata agree without plumbing.
    """

    retries: int = 0            # unit re-runs (device loss or watchdog)
    mesh_shrinks: int = 0       # mesh rebuilt smaller (incl. resume-time)
    mesh_repacks: int = 0       # resume re-batched onto a DIFFERENT mesh
    quarantined: int = 0        # units given up on after the retry budget
    watchdog_fires: int = 0     # per-unit deadline overruns
    device_losses: int = 0      # classified device-loss exceptions seen

    def count(self, kind: str, n: int = 1) -> None:
        setattr(self, kind, getattr(self, kind) + n)
        from ..obs.flight import record_event
        from ..utils.profiling import count_elastic

        count_elastic(kind, n)
        # every elastic transition is a flight-recorder event — counting
        # at the single shared site keeps the causal order (loss →
        # shrink → retry → quarantine) exactly as the ladder executed it
        record_event(f"elastic.{kind}")

    def to_json(self) -> Dict[str, int]:
        return {"retries": self.retries,
                "meshShrinks": self.mesh_shrinks,
                "meshRepacks": self.mesh_repacks,
                "quarantined": self.quarantined,
                "watchdogFires": self.watchdog_fires,
                "deviceLosses": self.device_losses}


class ElasticContext:
    """Per-sweep elastic policy, consulted by ``SweepWorkQueue``.

    ``shrink`` is the owner's degrade hook (the ModelSelector rebuilds a
    smaller mesh from surviving devices and re-points its live ``mesh``
    attribute — the unit fitters read it per fit, so the NEXT attempt
    lands on the shrunk mesh with no queue surgery); it returns True when
    something actually changed.  ``unit_deadline_s`` arms the straggler
    watchdog (None = off; the ModelSelector only arms it when the cost
    model's tier is warm — a cold tier would produce garbage deadlines).
    """

    def __init__(self,
                 shrink: Optional[Callable[[], bool]] = None,
                 max_unit_retries: int = 2,
                 unit_deadline_s: Optional[float] = None,
                 max_watchdog_retries: int = 1,
                 counters: Optional[ElasticCounters] = None):
        self.shrink_cb = shrink
        self.max_unit_retries = int(max_unit_retries)
        self.unit_deadline_s = unit_deadline_s
        self.max_watchdog_retries = int(max_watchdog_retries)
        self.counters = counters or ElasticCounters()
        #: set by run_all so a risky retry can flush completed units first
        self.checkpoint: Any = None
        #: flips True after a shrink: remaining grid-group blocks target
        #: the dead mesh and must be stripped to sequential fits
        self.groups_invalid = False
        #: watchdog-abandoned worker threads (an in-flight XLA program
        #: cannot be interrupted); drained at sweep end so a finishing
        #: straggler never runs into interpreter teardown
        self.abandoned: list = []

    # -- shared classifier ---------------------------------------------------

    @staticmethod
    def classify(e: BaseException) -> bool:
        return is_device_loss(e)

    # -- plumbing ------------------------------------------------------------

    def _shrink_once(self) -> bool:
        if self.shrink_cb is None:
            return False
        try:
            changed = bool(self.shrink_cb())
        except Exception:   # a failing degrade hook must not mask the loss
            changed = False
        if changed:
            self.counters.count("mesh_shrinks")
            self.groups_invalid = True
        return changed

    def _flush_checkpoint(self) -> None:
        ck = self.checkpoint
        if ck is not None:
            try:
                ck.flush()
            except Exception:   # durability is best-effort mid-recovery
                pass

    # -- escalation hooks ----------------------------------------------------

    def on_device_loss(self, unit_index: int, err: BaseException,
                       attempt: int) -> bool:
        """A classified device loss inside unit ``unit_index`` on retry
        ``attempt``.  True = shrink happened (or was attempted) and the
        unit should re-run; False = budget exhausted, quarantine it."""
        self.counters.count("device_losses")
        self._flush_checkpoint()
        if attempt >= self.max_unit_retries:
            self.counters.count("quarantined")
            return False
        self._shrink_once()
        self.counters.count("retries")
        return True

    def on_group_device_loss(self, err: BaseException) -> None:
        """A device loss inside a batched grid-group program: shrink and
        let the queue strip the group to sequential fits (which then land
        on the shrunk mesh).  The strip IS the retry — every member
        re-runs — so it lands on the retry counter like a unit re-run."""
        self.counters.count("device_losses")
        self._flush_checkpoint()
        self._shrink_once()
        self.counters.count("retries")

    def on_watchdog_timeout(self, unit_index: int, attempt: int) -> bool:
        """Unit ``unit_index`` blew its deadline.  True = degrade and
        re-run (the deadline doubles per attempt); False = quarantine."""
        self.counters.count("watchdog_fires")
        self._flush_checkpoint()
        if attempt >= self.max_watchdog_retries:
            self.counters.count("quarantined")
            return False
        self._shrink_once()
        self.counters.count("retries")
        return True

    def drain(self, per_thread_timeout_s: float = 30.0) -> int:
        """Join watchdog-abandoned workers (bounded per thread) at sweep
        end: their results are already discarded, but letting them run
        into interpreter teardown crashes the XLA runtime.  A thread
        still alive past the cap is left as a daemon (a truly hung
        program must not hang the sweep's exit too).  Returns how many
        were still alive when drain started."""
        alive = [t for t in self.abandoned if t.is_alive()]
        for t in alive:
            t.join(per_thread_timeout_s)
        self.abandoned = [t for t in self.abandoned if t.is_alive()]
        return len(alive)

    def note_resumed_mesh(self, saved_mesh: Optional[Dict[str, Any]],
                          current_mesh: Optional[Dict[str, Any]]) -> None:
        """A checkpoint written under ``saved_mesh`` resumed under
        ``current_mesh`` (advisory records, ``checkpoint.mesh_record``).
        Counts the re-pack, and a shrink when the device count dropped —
        the ELASTIC_SMOKE gate asserts this is visible in the JSON."""
        if saved_mesh == current_mesh:
            return
        self.counters.count("mesh_repacks")
        saved_n = int((saved_mesh or {}).get("devices", 1))
        cur_n = int((current_mesh or {}).get("devices", 1))
        if cur_n < saved_n:
            self.counters.count("mesh_shrinks")


def run_with_deadline(fn: Callable[[], Any], deadline_s: float,
                      abandoned: Optional[list] = None) -> Tuple[Any, bool]:
    """Run ``fn`` in a daemon worker with a join deadline.

    Returns ``(value, timed_out)``.  On timeout the worker keeps running
    (an in-flight XLA program cannot be interrupted) but the sweep moves
    on — the abandoned thread's result is discarded, and the thread is
    appended to ``abandoned`` so the sweep can :meth:`ElasticContext.
    drain` it before exiting.  Exceptions raised by ``fn`` re-raise
    here, so the caller's device-loss routing sees them exactly as in
    the undecorated path.
    """
    box: Dict[str, Any] = {}

    def work():
        try:
            box["val"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised to caller
            box["err"] = e

    t = threading.Thread(target=work, name="sweep-unit-watchdog",
                         daemon=True)
    t.start()
    t.join(max(float(deadline_s), 1e-3))
    if t.is_alive():
        if abandoned is not None:
            abandoned.append(t)
        return None, True
    if "err" in box:
        raise box["err"]
    return box.get("val"), False
