"""Streaming ingest into mesh-sharded device buffers.

ROADMAP item 1's memory half: the out-of-core driver (workflow/
streaming.py) already streams bounded chunks, but the packed (N, D)
feature matrix still materialized as ONE host buffer before any sharded
fit could begin — at 10M+ rows the host copy, not HBM, was the binding
constraint.  This module closes the gap: row chunks are accumulated ONLY
up to one data-shard slice, each completed slice is ``device_put`` to its
shard's devices immediately and the host buffer is reused, and the final
global array is assembled zero-copy from the per-device buffers with
``jax.make_array_from_single_device_arrays``.  Peak host residency for
the matrix is one shard (N/ndata rows) plus one in-flight chunk, never
the full (N, D) — measured in examples/bench_multichip.py.

Rows zero-pad to tile the data axis; callers carry the true row count and
zero weights for the tail (the standard ``pad_to_multiple`` contract —
pad rows are inert in every weighted reduction).
"""
from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["ShardedMatrixWriter", "ShardedMatrix", "BlockSpillMatrix",
           "stream_to_mesh"]


class BlockSpillMatrix:
    """Per-block views over a disk-spilled row matrix — what the block
    plane's streaming driver folds instead of one resident shard.

    The writer's block-spill mode appends fixed-size row blocks to ONE
    sequential spill file and hands back this handle; ``iter_blocks``
    re-reads the blocks one at a time (peak host residency: one block),
    in the same order every pass — the bit-exact fold-order property the
    blocked kernels' parity/resume gates lean on.  ``close`` (idempotent)
    unlinks the spill file; abandoning the handle leaks a temp file until
    process exit, so callers pair it with try/finally like the writer.
    """

    def __init__(self, path: Optional[str], rows: int, cols: int,
                 block_bounds: List[Tuple[int, int]], dtype):
        self.path = path
        self.rows = int(rows)
        self.cols = int(cols)
        #: [start, stop) row bounds of each spilled block, in file order
        self.block_bounds = list(block_bounds)
        self.dtype = np.dtype(dtype)
        self._closed = False

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.rows, self.cols)

    @property
    def n_blocks(self) -> int:
        return len(self.block_bounds)

    def iter_blocks(self, start_block: int = 0) -> Iterator[np.ndarray]:
        """Yield each (block_rows_i, cols) block, re-read sequentially
        from the spill file — never more than one block resident.
        ``start_block`` seeks straight to that block (stripe resume skips
        already-folded blocks without re-reading their bytes)."""
        if self._closed:
            raise ValueError("iter_blocks() on a closed BlockSpillMatrix")
        if not self.block_bounds or start_block >= len(self.block_bounds):
            return
        row_bytes = self.cols * self.dtype.itemsize
        first = self.block_bounds[start_block]
        with open(self.path, "rb") as fh:
            if first[0] > 0:
                fh.seek(first[0] * row_bytes)
            for start, stop in self.block_bounds[start_block:]:
                n = stop - start
                buf = fh.read(n * row_bytes)
                if len(buf) != n * row_bytes:
                    raise IOError(
                        f"block spill file truncated at rows "
                        f"[{start}, {stop}) of {self.path}")
                yield np.frombuffer(buf, self.dtype).reshape(n, self.cols)

    def read_all(self) -> np.ndarray:
        """Materialize the WHOLE local matrix — the resident fallback
        (kill-switch / debugging), deliberately not the streaming path."""
        if not self.block_bounds:
            return np.zeros((0, self.cols), self.dtype)
        return np.concatenate(list(self.iter_blocks()), axis=0)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.path is not None:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class ShardedMatrix:
    """A row-sharded device matrix posing as a host array of its TRUE row
    count.

    The streaming driver hands the packed feature matrix to the rest of
    the pipeline as a dataset column; this wrapper keeps the device
    residency (``.x_dev`` — the mesh-padded, row-sharded ``jax.Array``)
    while reporting the unpadded shape to shape-only consumers and
    materializing a trimmed host copy for ``np.asarray`` consumers.  The
    mesh sweep (ModelSelector with a sweep mesh) unwraps ``x_dev``
    directly and pads labels/weights instead, so the matrix never makes a
    host round trip on the sharded path.
    """

    def __init__(self, x_dev, rows: int):
        self.x_dev = x_dev
        self._rows = int(rows)

    @property
    def shape(self):
        return (self._rows,) + tuple(self.x_dev.shape[1:])

    @property
    def ndim(self) -> int:
        return self.x_dev.ndim

    @property
    def dtype(self):
        return self.x_dev.dtype

    @property
    def size(self) -> int:
        n = self._rows
        for s in self.x_dev.shape[1:]:
            n *= int(s)
        return n

    def __len__(self) -> int:
        return self._rows

    def __array__(self, dtype=None, copy=None):
        host = np.asarray(self.x_dev)[:self._rows]
        return host.astype(dtype) if dtype is not None else host


class ShardedMatrixWriter:
    """Append row chunks; get back a row-sharded global device array.

    The writer targets a (data, ...) mesh's row sharding
    (``sweep_matrix_sharding`` for 2-D values, ``data_sharding`` for
    1-D): rows land in the data-shard slice covering their global
    position, each slice uploads as soon as it fills, and ``finish()``
    stitches the committed per-device buffers into one global
    ``jax.Array``.  Appends must be in row order (the streaming driver's
    chunks are).
    """

    def __init__(self, mesh, total_rows: int, cols: Optional[int],
                 dtype=np.float32, block_rows: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        # -- block-spill mode (the 10M-row pod data plane) ------------------
        # ``block_rows`` set => rows accumulate into fixed-size blocks
        # appended to ONE sequential spill file; ``finish`` returns a
        # BlockSpillMatrix of per-block views instead of a device array.
        # Host-local by construction (the pod's host sharding already
        # scoped ``total_rows`` to this host's range), so no mesh is
        # needed; a host owning ZERO rows is legal (empty handle).
        self.block_rows = None if block_rows is None else int(block_rows)
        if self.block_rows is not None:
            if self.block_rows < 1:
                raise ValueError(
                    f"block_rows must be >= 1, got {block_rows}")
            if cols is None:
                raise ValueError("block-spill mode needs a column count")
            self.mesh = mesh
            self.rows = int(total_rows)
            self.cols = int(cols)
            self.dtype = np.dtype(dtype)
            self.span = (0, self.rows)
            self.local_rows = self.rows
            self._spill_dir = spill_dir
            self._spill_path: Optional[str] = None
            self._spill_fh = None
            self._blk_bounds: List[Tuple[int, int]] = []
            self._buf = np.zeros((self.block_rows, self.cols), self.dtype)
            self._fill = 0
            self._done_rows = 0
            self._committed = {}
            self._closed = False
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.ndata = mesh.shape[mesh.axis_names[0]]
        self.rows = int(total_rows)
        self.cols = cols                       # None -> 1-D vector
        self.dtype = np.dtype(dtype)
        pad = (-self.rows) % self.ndata
        self.padded_rows = self.rows + pad
        self.shard_rows = self.padded_rows // self.ndata
        self.n_pad = pad
        spec = (P(mesh.axis_names[0], None) if cols is not None
                else P(mesh.axis_names[0]))
        self.sharding = NamedSharding(mesh, spec)
        shape = ((self.padded_rows, cols) if cols is not None
                 else (self.padded_rows,))
        self.global_shape = shape
        # device -> global row slice start, from the sharding itself (the
        # authoritative layout — replicated grid/model lanes map to the
        # same row range and receive the same host buffer).  On a
        # MULTI-PROCESS mesh this map covers only the ADDRESSABLE
        # devices: the writer fills exactly this process's shards and
        # the global array assembles from every process's contributions
        # — no host ever materializes more than its own span.
        self._dev_start = {
            dev: (idx[0].start or 0)
            for dev, idx in self.sharding.addressable_devices_indices_map(
                shape).items()}
        #: global row starts of the shards THIS process owns, in order
        self._starts = sorted(set(self._dev_start.values()))
        self.process_local = len(self._starts) < self.ndata
        if self.process_local:
            span = self._starts[-1] + self.shard_rows - self._starts[0]
            if span != self.shard_rows * len(self._starts):
                raise ValueError(
                    "ShardedMatrixWriter requires this process's shards "
                    "to cover one contiguous row span (process-major "
                    "device order)")
        #: [local_span_start, local_span_stop) in global rows
        self.span = (self._starts[0], self._starts[-1] + self.shard_rows)
        #: REAL rows this process is expected to append (its span minus
        #: any global pad tail living in its last shard)
        self.local_rows = max(min(self.rows, self.span[1]) - self.span[0],
                              0)
        self._buf = np.zeros(
            (self.shard_rows, cols) if cols is not None
            else (self.shard_rows,), self.dtype)
        self._shard_i = 0                      # index into self._starts
        self._fill = 0
        self._committed = {}                   # device -> device buffer
        self._closed = False
        self._jax = jax

    @property
    def offset(self) -> int:
        """GLOBAL row position of the next appended row."""
        if self.block_rows is not None:
            return self._done_rows + self._fill
        return (self.span[0] + self._shard_i * self.shard_rows
                + self._fill)

    def _spill_block(self) -> None:
        """Append the filled rows of the block buffer to the spill file
        and reuse the buffer — peak host residency stays one block."""
        if self._fill == 0:
            return
        if self._spill_fh is None:
            fd, self._spill_path = tempfile.mkstemp(
                prefix="tmog_blockspill_", suffix=".bin",
                dir=self._spill_dir)
            self._spill_fh = os.fdopen(fd, "wb")
        self._spill_fh.write(self._buf[:self._fill].tobytes())
        self._blk_bounds.append((self._done_rows,
                                 self._done_rows + self._fill))
        self._done_rows += self._fill
        self._fill = 0

    def _flush_shard(self) -> None:
        start = self._starts[self._shard_i]
        for dev, s in self._dev_start.items():
            if s == start:
                self._committed[dev] = self._jax.device_put(self._buf, dev)
        self._shard_i += 1
        self._fill = 0
        if self._shard_i < len(self._starts):
            # fresh buffer: the committed device array must not alias the
            # host memory the next shard overwrites
            self._buf = np.zeros_like(self._buf)

    def append(self, chunk: np.ndarray) -> None:
        """Append this process's next rows (global order within its
        span)."""
        arr = np.asarray(chunk, self.dtype)
        k = arr.shape[0]
        if self._closed:
            raise ValueError("append() on a closed ShardedMatrixWriter")
        if self.offset + k > min(self.rows, self.span[1]):
            raise ValueError(
                f"append past this process's rows "
                f"(span {self.span}, total_rows={self.rows}; offset "
                f"{self.offset} + chunk {k})")
        cap = (self.block_rows if self.block_rows is not None
               else self.shard_rows)
        pos = 0
        while pos < k:
            room = cap - self._fill
            take = min(room, k - pos)
            self._buf[self._fill:self._fill + take] = arr[pos:pos + take]
            self._fill += take
            pos += take
            if self._fill == cap:
                if self.block_rows is not None:
                    self._spill_block()
                else:
                    self._flush_shard()

    def close(self) -> None:
        """Release the per-shard DEVICE buffers and the reusable host
        slice without finishing — the abort path.  An ingest that dies
        mid-shard would otherwise strand every committed shard on device
        (plus one host slice) for as long as the writer object lives;
        callers wrap the append loop in ``try/finally: close()``
        (mirrors the ``_BlockStore`` spill cleanup from the streaming
        driver).  In block-spill mode this also closes AND unlinks the
        partial spill file — an abort mid-block must not strand disk.
        Idempotent; a no-op after ``finish()``."""
        self._committed = {}
        self._buf = None
        self._closed = True
        if self.block_rows is not None:
            if self._spill_fh is not None:
                try:
                    self._spill_fh.close()
                except OSError:  # pragma: no cover
                    pass
                self._spill_fh = None
            if self._spill_path is not None:
                try:
                    os.unlink(self._spill_path)
                except OSError:
                    pass
                self._spill_path = None
            self._blk_bounds = []

    def finish(self):
        """The global row-sharded array (pad rows zero-filled).

        Every process contributes ONLY its addressable per-device
        buffers; ``jax.make_array_from_single_device_arrays`` stitches
        them into one global array (the multi-process assembly path —
        each process names the same global shape + sharding and its own
        shards, the documented cross-host contract)."""
        if self._closed:
            raise ValueError("finish() on a closed ShardedMatrixWriter")
        if self.block_rows is not None:
            if self.offset != self.rows:
                raise ValueError(
                    f"finish() at offset {self.offset}, expected "
                    f"{self.rows} rows (block-spill mode)")
            self._spill_block()           # short tail block, if any
            if self._spill_fh is not None:
                self._spill_fh.flush()
                os.fsync(self._spill_fh.fileno())
                self._spill_fh.close()
                self._spill_fh = None
            out = BlockSpillMatrix(self._spill_path, self.rows, self.cols,
                                   self._blk_bounds, self.dtype)
            # the handle owns the spill file now: a later close() on the
            # writer (the stream_to_mesh finally) must not unlink it
            self._spill_path = None
            self._blk_bounds = []
            self._buf = None
            self._closed = True
            return out
        expected = self.span[0] + self.local_rows
        if self.offset != expected:
            raise ValueError(
                f"finish() at offset {self.offset}, expected "
                f"{expected} rows (span {self.span}, "
                f"total_rows={self.rows})")
        if self._shard_i < len(self._starts):
            # zero-fill the pad tail of the last local shard(s)
            self._buf[self._fill:] = 0
            self._fill = self.shard_rows
            self._flush_shard()
            while self._shard_i < len(self._starts):
                self._buf[:] = 0
                self._fill = self.shard_rows
                self._flush_shard()
        devs = list(self.sharding.addressable_devices_indices_map(
            self.global_shape))
        arrays = [self._committed[d] for d in devs]
        out = self._jax.make_array_from_single_device_arrays(
            self.global_shape, self.sharding, arrays)
        self._committed = {}
        self._buf = None
        self._closed = True
        self._check_pad_tail(out)
        return out

    def _check_pad_tail(self, out) -> None:
        """TM024 runtime contract (TMOG_CHECK=1): the mesh-pad tail of
        the stitched global array must be EXACTLY zero — a non-zero pad
        row would survive every downstream weighted reduction as a
        pad-variance leak.  One small tail fetch, paid only in check
        mode."""
        from ..analysis.contracts import checks_enabled

        if not self.n_pad or not checks_enabled():
            return
        if self.process_local:
            # a cross-process host fetch of the tail is not addressable
            # from here; the zero-fill above is the same code path the
            # single-process check covers
            return
        import numpy as _np

        tail = _np.asarray(out[self.rows:])
        if tail.size and not (tail == 0).all():
            from ..analysis.diagnostics import ContractViolation, Diagnostic

            raise ContractViolation(Diagnostic(
                rule="TM024",
                message=(f"ShardedMatrixWriter pad tail is non-zero "
                         f"({self.n_pad} pad row(s)); sharded reductions "
                         f"over this buffer are not pad-invariant")))


def stream_to_mesh(chunks: Iterable[np.ndarray], mesh, total_rows: int,
                   cols: int, dtype=np.float32) -> Tuple[object, np.ndarray]:
    """Feed an iterator of (k, cols) row chunks straight into per-shard
    device buffers.  Returns ``(X_dev, valid)`` — the row-sharded global
    matrix and the host (padded_rows,) 0/1 validity vector callers fold
    into their sample weights so pad rows stay inert."""
    w = ShardedMatrixWriter(mesh, total_rows, cols, dtype)
    try:
        for chunk in chunks:
            w.append(chunk)
        X_dev = w.finish()
    finally:
        w.close()   # no-op after finish(); releases buffers on abort
    valid = np.zeros(w.padded_rows, np.float32)
    valid[:total_rows] = 1.0
    return X_dev, valid
