"""Device-mesh utilities — the distributed substrate of the framework.

Reference mapping (SURVEY §2.12, §5.8): the reference's distributed backend is
Apache Spark — RDD row partitions across executors, driver-coordinated
``treeAggregate`` reductions inside MLlib (SanityChecker.scala:407-470,
FeatureDistribution.scala:187), JVM-thread parallel model fits
(OpCrossValidation.scala:113-138) and Rabit allreduce inside XGBoost's C++
core.  The TPU-native equivalent built here is single-controller JAX:

 * rows (Spark partitions)        -> ``data`` mesh axis (batch sharding)
 * feature-dim / wide vectors     -> ``model`` mesh axis (the tabular
                                     analogue of tensor parallelism)
 * treeAggregate / Rabit allreduce-> XLA collectives (psum/all_gather) that
                                     GSPMD inserts from sharding annotations,
                                     riding ICI within a slice and DCN across
 * driver thread-pool over grid   -> vmap/stacked fits over the mesh

Nothing in this module issues explicit collectives: trainers are written as
whole-array programs and the partitioner derives the communication, which is
exactly the "pick a mesh, annotate shardings, let XLA insert collectives"
recipe.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh", "make_sweep_mesh", "auto_grid_axis", "has_grid_axis",
    "data_sharding", "feature_sharding", "matrix_sharding",
    "sweep_matrix_sharding", "grid_sharding", "fold_weight_sharding",
    "chain_sharding", "replicated", "shard_dataset", "pad_to_multiple",
    "shard_sweep_inputs", "shard_map_compat", "next_shard_pad",
    "pod_default_devices", "global_mesh",
]


def pod_default_devices():
    """The device set mesh construction defaults to: under an active
    multi-process pod, the LOCALLY ADDRESSABLE devices (each process's
    sweep/fit machinery replicates deterministically on its own slice —
    the host-level pod protocol, distributed/podstream.py); otherwise
    every device jax can see.  Cross-process GLOBAL meshes (the
    ShardedMatrixWriter process-local ingest path) are built explicitly
    via :func:`global_mesh`."""
    import jax as _jax

    from ..distributed.runtime import current_pod

    if current_pod().active:
        return list(_jax.local_devices())
    return list(_jax.devices())


def global_mesh(axis_name: str = "data") -> Mesh:
    """A 1-D mesh over EVERY device of the pod (all processes), in
    process-major order — row shards land contiguously per process, which
    is exactly the layout host-sharded ingest fills.  In a single
    process this is just a 1-D mesh over the local devices."""
    import jax as _jax

    return Mesh(np.asarray(_jax.devices()), (axis_name,))


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Tuple[str, str] = ("data", "model"),
              model_parallelism: Optional[int] = None,
              queue_width: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a 2-D mesh over the available devices.

    The default is the (data, model) mesh: ``model_parallelism`` defaults
    to 1 (pure data parallel) unless the device count is not a
    power-of-two multiple of it.  Tabular workloads are row-dominated; the
    model axis exists for wide-feature sharding of histogram builds and
    (D,D) normal-equation work.

    ``axis_names=("data", "grid")`` builds the SWEEP mesh instead: the
    second axis packs hyperparameter-grid candidates (vmapped same-family
    batches, selector.grid_groups) rather than feature columns.
    ``model_parallelism`` then names the grid-axis size; when omitted it
    is auto-selected from ``queue_width`` — the number of schedulable
    sweep units — via :func:`auto_grid_axis`.
    """
    devs = list(devices) if devices is not None else pod_default_devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    devs = devs[:n]
    mp = model_parallelism
    if mp is None:
        mp = (auto_grid_axis(n, queue_width)
              if axis_names[1] == "grid" and queue_width is not None else 1)
    if n % mp != 0:
        raise ValueError(
            f"n_devices={n} not divisible by "
            f"{axis_names[1]}_parallelism={mp}")
    arr = np.asarray(devs).reshape(n // mp, mp)
    return Mesh(arr, axis_names)


def auto_grid_axis(n_devices: int, queue_width: Optional[int]) -> int:
    """Grid-axis size for a (data, grid) sweep mesh.

    Rows dominate tabular sweep cost, so the data axis keeps at least
    half the devices; the grid axis takes power-of-two lanes up to the
    queue width (lanes beyond the candidate count would only hold
    padding candidates).  Deterministic in (n_devices, queue_width).
    """
    if not queue_width or queue_width <= 1 or n_devices <= 1:
        return 1
    g = 1
    while (g * 2 <= max(n_devices // 2, 1) and g * 2 <= queue_width
           and n_devices % (g * 2) == 0):
        g *= 2
    return g


def make_sweep_mesh(queue_width: int, n_devices: Optional[int] = None,
                    grid_parallelism: Optional[int] = None) -> Mesh:
    """The ("data", "grid") mesh for a selector sweep of ``queue_width``
    schedulable units (SweepWorkQueue) — shape auto-selected unless
    ``grid_parallelism`` pins the grid axis."""
    return make_mesh(n_devices, axis_names=("data", "grid"),
                     model_parallelism=grid_parallelism,
                     queue_width=queue_width)


def has_grid_axis(mesh) -> bool:
    """True for a sweep mesh (second axis packs grid candidates)."""
    return mesh is not None and "grid" in getattr(mesh, "axis_names", ())


def shard_map_compat(fn, mesh, in_specs, out_specs, check: bool = False):
    """``shard_map`` across jax versions: >= 0.6 exports it top-level with
    ``check_vma``; the 0.4.x line ships ``jax.experimental.shard_map``
    with ``check_rep``.  Semantics are identical for these kernels."""
    try:
        from jax import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis — a (N,) label/weight vector."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """A (D,) or (D, D) object sharded over the model axis."""
    return NamedSharding(mesh, P(mesh.axis_names[1]))


def matrix_sharding(mesh: Mesh) -> NamedSharding:
    """The (N, D) feature matrix: rows over data axis, columns over model."""
    return NamedSharding(mesh, P(mesh.axis_names[0], mesh.axis_names[1]))


def sweep_matrix_sharding(mesh: Mesh) -> NamedSharding:
    """The (N, D) matrix on a SWEEP mesh: rows over the data axis, columns
    replicated (the grid axis packs candidates, not features)."""
    return NamedSharding(mesh, P(mesh.axis_names[0], None))


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """A per-candidate (C, ...) batch sharded over the grid axis."""
    return NamedSharding(mesh, P(mesh.axis_names[1]))


def fold_weight_sharding(mesh: Mesh) -> NamedSharding:
    """A stacked (F, N) fold-weight matrix: folds replicated, rows over
    the data axis (matches the row sharding of the matrix it masks)."""
    return NamedSharding(mesh, P(None, mesh.axis_names[0]))


def chain_sharding(mesh: Mesh) -> NamedSharding:
    """A per-chain (S, N) row-state matrix (boosting margins, chain
    weights) on a SWEEP mesh: chains over the grid axis, rows over the
    data axis — the tree grid groups' placement."""
    return NamedSharding(mesh, P(mesh.axis_names[1], mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def next_shard_pad(mesh: Mesh, n_rows: int) -> int:
    """Rows to append so ``n_rows`` lands exactly on the NEXT data-axis
    tile boundary — guaranteeing the internal ``pad_to_multiple`` amount
    CHANGES, which is what the TM024 pad-invariance contract
    (``analysis/contracts.check_pad_invariance``) perturbs: results must
    not move when the padding does."""
    ndata = int(mesh.shape[mesh.axis_names[0]])
    rem = n_rows % ndata
    return (ndata - rem) if rem else ndata


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0,
                    fill=0.0) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple so it tiles evenly over a mesh axis.

    Static-shape substitute for Spark's arbitrary row partitioning; returns
    (padded, n_pad).  Callers carry a weight mask so padding rows are inert
    in every reduction.
    """
    size = arr.shape[axis]
    target = int(math.ceil(size / multiple)) * multiple if size else multiple
    n_pad = target - size
    if n_pad == 0:
        return arr, 0
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, n_pad)
    return np.pad(arr, widths, constant_values=fill), n_pad


def shard_dataset(X: np.ndarray, y: Optional[np.ndarray], mesh: Mesh,
                  w: Optional[np.ndarray] = None):
    """Place (X, y, w) onto the mesh: rows×cols sharded X, row-sharded y/w.

    Rows are zero-padded to tile the data axis and masked out via ``w``;
    columns are zero-padded to tile the model axis (inert: zero columns
    contribute nothing to matmuls and get zero weights back).
    Returns (X_dev, y_dev, w_dev) committed device arrays.
    """
    from ..models.trees import _dev_memo_sharded

    ndata = mesh.shape[mesh.axis_names[0]]
    grid_mesh = has_grid_axis(mesh)
    # a sweep mesh's second axis packs candidates, never feature columns
    nmodel = 1 if grid_mesh else mesh.shape[mesh.axis_names[1]]
    n_rows = X.shape[0]
    if w is None:
        w = np.ones(n_rows, np.float32)
    X, _ = pad_to_multiple(np.asarray(X, np.float32), ndata, axis=0)
    X, _ = pad_to_multiple(X, nmodel, axis=1)
    w, _ = pad_to_multiple(np.asarray(w, np.float32), ndata, axis=0)
    # content-memoized: the selector sweep re-shards the same fold matrices
    # for every grid candidate, and each redundant sharded upload costs
    # seconds of tunnel transfer
    xs = sweep_matrix_sharding(mesh) if grid_mesh else matrix_sharding(mesh)
    X_dev = _dev_memo_sharded(X, xs, "shard_X")
    w_dev = _dev_memo_sharded(w, data_sharding(mesh), "shard_w")
    y_dev = None
    if y is not None:
        y_pad, _ = pad_to_multiple(np.asarray(y, np.float32), ndata, axis=0)
        y_dev = _dev_memo_sharded(y_pad, data_sharding(mesh), "shard_y")
    return X_dev, y_dev, w_dev


def shard_sweep_inputs(X: np.ndarray, y: np.ndarray, mesh: Mesh,
                       fold_weights: Optional[np.ndarray] = None):
    """Commit a sweep's shared inputs onto a (data, grid) mesh.

    Rows zero-pad to tile the data axis; the pad rows carry ZERO weight in
    every stacked fold row, which makes them inert through the weighted
    column stats, the Newton/majorization Gram products and the histogram
    builds — sharded sweep results are invariant to the pad amount
    (property-tested in tests/test_parallel_mesh.py).

    Returns ``(X_dev, y_dev, W_dev)`` where ``W_dev`` is the (F, N_pad)
    stacked fold-weight matrix (None when ``fold_weights`` is None).
    """
    from ..models.trees import _dev_memo_sharded

    ndata = mesh.shape[mesh.axis_names[0]]
    Xp, _ = pad_to_multiple(np.asarray(X, np.float32), ndata, axis=0)
    yp, _ = pad_to_multiple(
        np.nan_to_num(np.asarray(y, np.float32)), ndata, axis=0)
    X_dev = _dev_memo_sharded(Xp, sweep_matrix_sharding(mesh), "sweep_X")
    y_dev = _dev_memo_sharded(yp, data_sharding(mesh), "sweep_y")
    W_dev = None
    if fold_weights is not None:
        Wp, _ = pad_to_multiple(
            np.ascontiguousarray(np.asarray(fold_weights, np.float32)),
            ndata, axis=1)
        W_dev = _dev_memo_sharded(Wp, fold_weight_sharding(mesh), "sweep_W")
    return X_dev, y_dev, W_dev
