"""Device-mesh utilities — the distributed substrate of the framework.

Reference mapping (SURVEY §2.12, §5.8): the reference's distributed backend is
Apache Spark — RDD row partitions across executors, driver-coordinated
``treeAggregate`` reductions inside MLlib (SanityChecker.scala:407-470,
FeatureDistribution.scala:187), JVM-thread parallel model fits
(OpCrossValidation.scala:113-138) and Rabit allreduce inside XGBoost's C++
core.  The TPU-native equivalent built here is single-controller JAX:

 * rows (Spark partitions)        -> ``data`` mesh axis (batch sharding)
 * feature-dim / wide vectors     -> ``model`` mesh axis (the tabular
                                     analogue of tensor parallelism)
 * treeAggregate / Rabit allreduce-> XLA collectives (psum/all_gather) that
                                     GSPMD inserts from sharding annotations,
                                     riding ICI within a slice and DCN across
 * driver thread-pool over grid   -> vmap/stacked fits over the mesh

Nothing in this module issues explicit collectives: trainers are written as
whole-array programs and the partitioner derives the communication, which is
exactly the "pick a mesh, annotate shardings, let XLA insert collectives"
recipe.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh", "data_sharding", "feature_sharding", "matrix_sharding",
    "replicated", "shard_dataset", "pad_to_multiple",
]


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Tuple[str, str] = ("data", "model"),
              model_parallelism: Optional[int] = None) -> Mesh:
    """Build a 2-D (data, model) mesh over the available devices.

    ``model_parallelism`` defaults to 1 (pure data parallel) unless the
    device count is not a power-of-two multiple of it.  Tabular workloads
    are row-dominated; the model axis exists for wide-feature sharding of
    histogram builds and (D,D) normal-equation work.
    """
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    devs = devs[:n]
    mp = model_parallelism or 1
    if n % mp != 0:
        raise ValueError(f"n_devices={n} not divisible by model_parallelism={mp}")
    arr = np.asarray(devs).reshape(n // mp, mp)
    return Mesh(arr, axis_names)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis — a (N,) label/weight vector."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """A (D,) or (D, D) object sharded over the model axis."""
    return NamedSharding(mesh, P(mesh.axis_names[1]))


def matrix_sharding(mesh: Mesh) -> NamedSharding:
    """The (N, D) feature matrix: rows over data axis, columns over model."""
    return NamedSharding(mesh, P(mesh.axis_names[0], mesh.axis_names[1]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0,
                    fill=0.0) -> Tuple[np.ndarray, int]:
    """Pad ``axis`` up to a multiple so it tiles evenly over a mesh axis.

    Static-shape substitute for Spark's arbitrary row partitioning; returns
    (padded, n_pad).  Callers carry a weight mask so padding rows are inert
    in every reduction.
    """
    size = arr.shape[axis]
    target = int(math.ceil(size / multiple)) * multiple if size else multiple
    n_pad = target - size
    if n_pad == 0:
        return arr, 0
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, n_pad)
    return np.pad(arr, widths, constant_values=fill), n_pad


def shard_dataset(X: np.ndarray, y: Optional[np.ndarray], mesh: Mesh,
                  w: Optional[np.ndarray] = None):
    """Place (X, y, w) onto the mesh: rows×cols sharded X, row-sharded y/w.

    Rows are zero-padded to tile the data axis and masked out via ``w``;
    columns are zero-padded to tile the model axis (inert: zero columns
    contribute nothing to matmuls and get zero weights back).
    Returns (X_dev, y_dev, w_dev) committed device arrays.
    """
    from ..models.trees import _dev_memo_sharded

    ndata = mesh.shape[mesh.axis_names[0]]
    nmodel = mesh.shape[mesh.axis_names[1]]
    n_rows = X.shape[0]
    if w is None:
        w = np.ones(n_rows, np.float32)
    X, _ = pad_to_multiple(np.asarray(X, np.float32), ndata, axis=0)
    X, _ = pad_to_multiple(X, nmodel, axis=1)
    w, _ = pad_to_multiple(np.asarray(w, np.float32), ndata, axis=0)
    # content-memoized: the selector sweep re-shards the same fold matrices
    # for every grid candidate, and each redundant sharded upload costs
    # seconds of tunnel transfer
    X_dev = _dev_memo_sharded(X, matrix_sharding(mesh), "shard_X")
    w_dev = _dev_memo_sharded(w, data_sharding(mesh), "shard_w")
    y_dev = None
    if y is not None:
        y_pad, _ = pad_to_multiple(np.asarray(y, np.float32), ndata, axis=0)
        y_dev = _dev_memo_sharded(y_pad, data_sharding(mesh), "shard_y")
    return X_dev, y_dev, w_dev
