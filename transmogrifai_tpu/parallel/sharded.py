"""Mesh-sharded training programs.

These are the multi-chip paths of the XLA trainers in ``models/``: identical
math, but inputs committed to a (data, model) mesh so GSPMD partitions the
matmuls/scatters and inserts the ICI collectives that replace Spark's
``treeAggregate`` (SanityChecker.scala:407-470) and XGBoost's Rabit
allreduce (SURVEY §2.11-2.12).

``full_train_step`` is the single compiled program the driver dry-runs on an
N-virtual-device mesh: one AutoML macro-step =
  column stats (SanityChecker pass)            — psum over data axis
  Newton-IRLS logistic-regression update       — (D,N)@(N,D) sharded matmul
  one histogram GBDT level (hist+split+route)  — sharded scatter-add + argmax
all under one jit, with explicit sharding constraints on the carried state.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (
    data_sharding, make_mesh, matrix_sharding, replicated, shard_dataset,
)

__all__ = ["TrainStepState", "full_train_step", "make_train_step",
           "fit_logreg_sharded"]


class TrainStepState(NamedTuple):
    """Carried state for one AutoML macro-step (all replicated)."""
    beta: jnp.ndarray       # (D+1,) logreg coefficients + intercept
    col_mean: jnp.ndarray   # (D,)
    col_var: jnp.ndarray    # (D,)
    tree_feat: jnp.ndarray  # (n_nodes,) int32 — split feature per node
    tree_thresh: jnp.ndarray  # (n_nodes,) int32


def _colstats(X, w):
    wsum = jnp.maximum(w.sum(), 1.0)
    mean = (w @ X) / wsum
    var = (w @ (X * X)) / wsum - mean ** 2
    return mean, var


def _newton_step(X, y, w, beta, l2=1e-3):
    from ..models.linear import _damped_solve, _finite_or

    n, d = X.shape
    wsum = jnp.maximum(w.sum(), 1.0)
    z = X @ beta[:d] + beta[d]
    p = jax.nn.sigmoid(z)
    g_z = w * (p - y) / wsum
    s = jnp.maximum(w * p * (1 - p) / wsum, 1e-10)
    Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    grad = Xa.T @ g_z
    grad = grad.at[:d].add(l2 * beta[:d])
    H = (Xa * s[:, None]).T @ Xa
    H = H.at[jnp.arange(d), jnp.arange(d)].add(l2)
    return _finite_or(beta - _damped_solve(H, grad), beta)


def _tree_level(binned, g, h, w, node, n_nodes, n_bins, lam=1.0):
    n, d = binned.shape
    chans = jnp.stack([g * w, h * w, w], axis=1)          # (N, 3)
    flat_idx = (node[:, None] * (d * n_bins)
                + jnp.arange(d)[None, :] * n_bins + binned)
    hist = jnp.zeros((n_nodes * d * n_bins, 3), jnp.float32)
    hist = hist.at[flat_idx].add(chans[:, None, :])
    hist = hist.reshape(n_nodes, d, n_bins, 3)
    GL = jnp.cumsum(hist[..., 0], axis=2)
    HL = jnp.cumsum(hist[..., 1], axis=2)
    Gt, Ht = GL[:, :1, -1:], HL[:, :1, -1:]
    gain = (GL ** 2 / (HL + lam) + (Gt - GL) ** 2 / (Ht - HL + lam)
            - Gt ** 2 / (Ht + lam))
    gain = jnp.where(jnp.arange(n_bins)[None, None, :] < n_bins - 1,
                     gain, -jnp.inf)
    best = jnp.argmax(gain.reshape(n_nodes, d * n_bins), axis=1)
    feat = (best // n_bins).astype(jnp.int32)
    thresh = (best % n_bins).astype(jnp.int32)
    x_row = jnp.take_along_axis(binned, feat[node][:, None], 1)[:, 0]
    new_node = 2 * node + (x_row > thresh[node]).astype(jnp.int32)
    return feat, thresh, new_node


def full_train_step(X, binned, y, w, state: TrainStepState, *,
                    n_bins: int = 32) -> TrainStepState:
    """One AutoML macro-step over sharded data (see module docstring)."""
    mean, var = _colstats(X, w)
    beta = _newton_step(X, y, w, state.beta)
    g = jax.nn.sigmoid(X @ beta[:-1] + beta[-1]) - y     # logloss grads
    h = jnp.maximum(g + y, 1e-6) * jnp.maximum(1.0 - g - y, 1e-6)
    node = jnp.zeros(X.shape[0], jnp.int32)
    feat, thresh, _ = _tree_level(binned, g, h, w, node,
                                  state.tree_feat.shape[0], n_bins)
    return TrainStepState(beta, mean, var, feat, thresh)


def make_train_step(mesh: Mesh, n_bins: int = 32):
    """Jit ``full_train_step`` with replicated state in/out on ``mesh``."""
    rep = replicated(mesh)
    step = functools.partial(full_train_step, n_bins=n_bins)
    return jax.jit(step, in_shardings=(matrix_sharding(mesh),
                                       matrix_sharding(mesh),
                                       data_sharding(mesh),
                                       data_sharding(mesh), rep),
                   out_shardings=rep)


def fit_logreg_sharded(X: np.ndarray, y: np.ndarray, mesh: Mesh,
                       w: Optional[np.ndarray] = None, **kwargs):
    """Data/model-parallel logistic regression: shard inputs on the mesh and
    run the standard jitted IRLS trainer — GSPMD partitions the per-iteration
    (D,N)@(N,D) Gram matmuls and psums partial Hessians over ICI.

    The returned fit is sliced back to the caller's feature count (column
    padding used to tile the model axis is stripped)."""
    from ..models.linear import LinearFit, fit_logistic_regression
    d = X.shape[1]
    X_dev, y_dev, w_dev = shard_dataset(X, y, mesh, w)
    fit = fit_logistic_regression(X_dev, y_dev, w_dev, **kwargs)
    coef = fit.coef[..., :d] if fit.coef.shape[-1] != d else fit.coef
    return LinearFit(coef, fit.intercept, fit.n_iter, fit.converged)
