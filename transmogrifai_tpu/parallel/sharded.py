"""Mesh-sharded training programs.

These are the multi-chip paths of the XLA trainers in ``models/``: identical
math, but inputs committed to a (data, model) mesh so GSPMD partitions the
matmuls/scatters and inserts the ICI collectives that replace Spark's
``treeAggregate`` (SanityChecker.scala:407-470) and XGBoost's Rabit
allreduce (SURVEY §2.11-2.12).

``full_train_step`` is the single compiled program the driver dry-runs on an
N-virtual-device mesh: one AutoML macro-step =
  column stats (SanityChecker pass)            — psum over data axis
  Newton-IRLS logistic-regression update       — (D,N)@(N,D) sharded matmul
  one histogram GBDT level (hist+split+route)  — sharded scatter-add + argmax
all under one jit, with explicit sharding constraints on the carried state.
"""
from __future__ import annotations

import functools
import os
from typing import Callable, Iterable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (
    data_sharding, make_mesh, matrix_sharding, replicated, shard_dataset,
)

__all__ = ["TrainStepState", "full_train_step", "make_train_step",
           "fit_logreg_sharded", "grow_forest_sharded",
           "colstats_corr_sharded", "colstats_psum",
           "fit_logreg_newton_psum", "histogram_psum",
           "gbt_chain_rounds_sharded", "grow_rf_grid_sharded",
           "block_kernels_enabled", "block_rows_for", "block_grid",
           "colstats_block_fold", "colstats_from_acc",
           "newton_block_pass", "newton_solve_host",
           "fit_logreg_newton_blocked", "histogram_block_fold",
           "logloss_block_fold"]


class TrainStepState(NamedTuple):
    """Carried state for one AutoML macro-step (all replicated)."""
    beta: jnp.ndarray       # (D+1,) logreg coefficients + intercept
    col_mean: jnp.ndarray   # (D,)
    col_var: jnp.ndarray    # (D,)
    tree_feat: jnp.ndarray  # (2^depth - 1,) int32 — split feature per node
    tree_thresh: jnp.ndarray  # (2^depth - 1,) int32


def _colstats(X, w):
    wsum = jnp.maximum(w.sum(), 1.0)
    mean = (w @ X) / wsum
    var = (w @ (X * X)) / wsum - mean ** 2
    return mean, var


def _newton_step(X, y, w, beta, l2=1e-3):
    from ..models.linear import _damped_solve, _finite_or

    n, d = X.shape
    wsum = jnp.maximum(w.sum(), 1.0)
    z = X @ beta[:d] + beta[d]
    p = jax.nn.sigmoid(z)
    g_z = w * (p - y) / wsum
    s = jnp.maximum(w * p * (1 - p) / wsum, 1e-10)
    Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    grad = Xa.T @ g_z
    grad = grad.at[:d].add(l2 * beta[:d])
    H = (Xa * s[:, None]).T @ Xa
    H = H.at[jnp.arange(d), jnp.arange(d)].add(l2)
    return _finite_or(beta - _damped_solve(H, grad), beta)


def full_train_step(X, binned, y, w, state: TrainStepState, *,
                    n_bins: int = 32) -> TrainStepState:
    """One AutoML macro-step over sharded data (see module docstring).

    The tree component runs the REAL matmul-histogram kernel
    (``gbdt_kernels._grow_tree_traced`` — the exact program production fits
    compile), not a simplified stand-in: GSPMD partitions its histogram
    matmuls over the mesh just like the logreg Gram products.
    """
    from ..models.gbdt_kernels import _grow_tree_traced

    mean, var = _colstats(X, w)
    beta = _newton_step(X, y, w, state.beta)
    g = jax.nn.sigmoid(X @ beta[:-1] + beta[-1]) - y     # logloss grads
    h = jnp.maximum(g + y, 1e-6) * jnp.maximum(1.0 - g - y, 1e-6)
    n_nodes = state.tree_feat.shape[0]
    if n_nodes & (n_nodes + 1):
        raise ValueError(
            f"tree_feat must hold a full heap (2^depth - 1 nodes), got "
            f"{n_nodes}")
    depth = int(np.log2(n_nodes + 1))
    feat, thresh, _leaf, _ = _grow_tree_traced(
        binned, (g * w)[:, None], (h * w)[:, None], w,
        jnp.ones(binned.shape[1], bool), jnp.int32(depth),
        max_depth=depth, n_bins=n_bins, lam=jnp.float32(1.0),
        min_child_weight=jnp.float32(0.0), min_info_gain=jnp.float32(0.0),
        min_instances=jnp.float32(1.0), newton_leaf=jnp.bool_(False),
        learning_rate=jnp.float32(1.0))
    return TrainStepState(beta, mean, var, feat, thresh)


def make_train_step(mesh: Mesh, n_bins: int = 32):
    """Jit ``full_train_step`` with replicated state in/out on ``mesh``."""
    rep = replicated(mesh)
    step = functools.partial(full_train_step, n_bins=n_bins)
    return jax.jit(step, in_shardings=(matrix_sharding(mesh),
                                       matrix_sharding(mesh),
                                       data_sharding(mesh),
                                       data_sharding(mesh), rep),
                   out_shardings=rep)


def grow_forest_sharded(binned: np.ndarray, Y: np.ndarray, BW: np.ndarray,
                        feat_mask: np.ndarray, mesh: Mesh, *,
                        max_depth: int, n_bins: int, lam: float = 1e-3,
                        min_child_weight: float = 0.0,
                        min_info_gain: float = 0.0,
                        min_instances: float = 1.0,
                        newton_leaf: bool = False,
                        learning_rate: float = 1.0,
                        onehot_targets: bool = False):
    """Bagged forest growth with rows sharded over the mesh's data axis.

    Each shard builds partial gradient/hessian/count histograms on its rows;
    one ``psum`` per level over ICI replaces Spark's ``treeAggregate`` and
    XGBoost's Rabit allreduce (SURVEY §2.12 rows 1, 4).  Split decisions are
    computed identically on every shard from the reduced histograms, so row
    routing needs no further communication; leaf sums psum once at the end.

    Rows must tile the data axis (pad with zero bag weights).  Returns
    replicated (T, 2^d-1) feat/thresh and (T, 2^d, K) leaves — identical to
    single-device ``grow_forest`` output for the same inputs.

    Trees are grown in HBM-budgeted chunks: the all-reduce path disables
    node compaction (full 2^level histogram slots so every shard agrees on
    slot layout), so the per-tree working set is 2^depth × bins × features —
    ``forest_chunk_size(compact=False)`` with this shard's row count bounds
    how many trees one launch vmaps over (ADVICE r1).
    """
    from .mesh import shard_map_compat

    from ..models.gbdt_kernels import _grow_tree_traced

    data_axis = mesh.axis_names[0]
    T, n = BW.shape
    d = binned.shape[1]
    k = Y.shape[1]
    psum = functools.partial(lax.psum, axis_name=data_axis)

    def shard_fn(binned_s, Y_s, BW_s, mask_r, limit_r):
        G = BW_s[:, :, None] * Y_s[None, :, :]
        H = jnp.broadcast_to(BW_s[:, :, None], G.shape)
        fn = functools.partial(
            _grow_tree_traced, binned_s, max_depth=max_depth, n_bins=n_bins,
            lam=jnp.float32(lam),
            min_child_weight=jnp.float32(min_child_weight),
            min_info_gain=jnp.float32(min_info_gain),
            min_instances=jnp.float32(min_instances),
            newton_leaf=jnp.bool_(newton_leaf),
            learning_rate=jnp.float32(learning_rate),
            all_reduce=psum,
            bag_mode="onehot" if onehot_targets else "bagged")
        f, t, lf, _ = jax.vmap(fn)(G, H, BW_s, mask_r, limit_r)
        return f, t, lf

    fn = shard_map_compat(
        shard_fn, mesh,
        (P(data_axis, None), P(data_axis, None), P(None, data_axis),
         P(None, None), P(None)),
        (P(None, None), P(None, None), P(None, None, None)))
    # compact=False: the all-reduce path keeps the full 2^level slot layout
    # (no node compaction — shards must agree on histogram indices), so the
    # budget uses the uncompacted slot count with this shard's row count.
    from ..models.gbdt_kernels import forest_chunk_size
    n_shard = max(n // mesh.shape[data_axis], 1)
    chunk = forest_chunk_size(T, max_depth, d, n_bins, k,
                              n_rows=n_shard, compact=False)
    jfn = jax.jit(fn)
    binned_d = jnp.asarray(binned)
    Y_d = jnp.asarray(Y, jnp.float32)
    BW_h = np.asarray(BW, np.float32)
    mask_h = np.asarray(feat_mask, bool)
    limit = jnp.full((chunk,), max_depth, jnp.int32)
    fs, ts, ls = [], [], []
    with mesh:
        for s in range(0, T, chunk):
            e = min(s + chunk, T)
            BWc, Mc = BW_h[s:e], mask_h[s:e]
            if e - s < chunk:  # zero-weight pad keeps one compiled shape
                pad = chunk - (e - s)
                BWc = np.concatenate(
                    [BWc, np.zeros((pad, n), np.float32)], axis=0)
                Mc = np.concatenate([Mc, np.ones((pad, d), bool)], axis=0)
            f, t, lf = jfn(binned_d, Y_d, jnp.asarray(BWc),
                           jnp.asarray(Mc), limit)
            fs.append(f[: e - s])
            ts.append(t[: e - s])
            ls.append(lf[: e - s])
    if len(fs) == 1:
        return fs[0], ts[0], ls[0]
    return (jnp.concatenate(fs), jnp.concatenate(ts), jnp.concatenate(ls))


# ---------------------------------------------------------------------------
# Batched TREE sweeps on the ("data", "grid") mesh (ROADMAP item 2 / PR 11):
# same-shape RF/GBT candidates ride the grid axis while rows shard over the
# data axis — the tree analogue of the linear grid groups.  shard_map
# bodies with EXPLICIT per-level histogram psums (the all_reduce path of
# ``_grow_tree_traced``, which disables node compaction so every shard
# agrees on the full 2^level slot layout); per-chain hyperparameter
# vectors (depth limit, lambda, min_child_weight, eta, gamma / RF gate
# params) commit P("grid"), the binned int8 matrix commits P("data",
# None), and tree outputs replicate over data (identical split decisions
# per shard — the grow_forest_sharded contract, extended to the grid).
# Zero-weight pad rows/chains are inert, so results are invariant to both
# paddings (TM024) and agree with the single-device batched programs
# (TM025).
# ---------------------------------------------------------------------------

#: compiled shard_map programs per (mesh, static-config) — the sweep
#: re-enters these once per es_chunk launch / tree chunk, and rebuilding
#: the shard_map wrapper per call would re-trace every time
_TREE_SWEEP_JITS: dict = {}


def _mesh_cache_key(mesh: Mesh):
    return (tuple(mesh.axis_names), tuple(sorted(mesh.shape.items())),
            tuple(int(d.id) for d in np.asarray(mesh.devices).flat))


def gbt_chain_rounds_sharded(binned, y, W, Fm0, yv, vi, depth_lim, lams,
                             mcws, migs, mins_, lrs, mgrs, mesh: Mesh, *,
                             n_rounds: int, max_depth: int, n_bins: int,
                             obj: str, hist_bf16: bool = False,
                             use_es: bool = False,
                             skip_counts: bool = False, bundle_end=None,
                             acc_bf16: bool = False):
    """``n_rounds`` boosting rounds for S chains, chains sharded over the
    grid axis and rows over the data axis — the mesh form of
    ``gbdt_kernels._gbt_chain_rounds_jit`` with per-level histogram psums.

    Inputs are COMMITTED device arrays: ``binned`` (N_pad, D) at
    P("data", None), ``y`` (N_pad,) at P("data"), ``W``/``Fm0``
    (S_pad, N_pad) at P("grid", "data"), the per-chain vectors (S_pad,)
    at P("grid"); ``vi`` holds GLOBAL validation row indices (replicated)
    whose margins each owning shard contributes and one psum gathers, so
    the early-stopping metric sees exactly the single-device rows.
    ``bundle_end`` is the host EFB end-bin table or None (the identity
    table is used — bit-identical to the standard split form).  Returns
    the same 5-tuple as the single-device kernel, chains still sharded.
    """
    from ..models.gbdt_kernels import (_chain_es_metric_val,
                                       _grow_tree_traced,
                                       _predict_tree_bundled)
    from .mesh import shard_map_compat

    data_axis, grid_axis = mesh.axis_names
    be_host = (np.asarray(bundle_end, np.int32) if bundle_end is not None
               else np.full((n_bins, int(binned.shape[1])), n_bins - 1,
                            np.int32))
    key = ("gbt", _mesh_cache_key(mesh), n_rounds, max_depth, n_bins, obj,
           hist_bf16, use_es, skip_counts, acc_bf16)
    fn = _TREE_SWEEP_JITS.get(key)
    if fn is None:
        psum_d = functools.partial(lax.psum, axis_name=data_axis)

        def shard_fn(binned_s, y_s, W_s, Fm_s, yv_r, vi_r, be_r,
                     dl, la, mc, mg, mi, lr_, mgr_):
            nl, d = binned_s.shape
            mask = jnp.ones(d, bool)
            lo = lax.axis_index(data_axis) * nl

            def round_step(Fm, _):
                if obj == "binary":
                    Pm = jax.nn.sigmoid(Fm)
                    G = W_s * (Pm - y_s[None, :])
                    H = W_s * jnp.maximum(Pm * (1 - Pm), 1e-6)
                else:
                    G = W_s * (Fm - y_s[None, :])
                    H = W_s

                def one(g, h, c, lim, lam_, mcw, mig, mi_, lrr, mgr):
                    return _grow_tree_traced(
                        binned_s, g[:, None], h[:, None], c, mask, lim,
                        max_depth=max_depth, n_bins=n_bins, lam=lam_,
                        min_child_weight=mcw, min_info_gain=mig,
                        min_instances=mi_, newton_leaf=jnp.bool_(True),
                        learning_rate=lrr, hist_bf16=hist_bf16,
                        min_gain_raw=mgr, all_reduce=psum_d,
                        bag_mode="newton" if skip_counts else "none",
                        bundle_end=be_r, acc_bf16=acc_bf16)[:3]

                f, t, lf = jax.vmap(one)(G, H, W_s, dl, la, mc, mg, mi,
                                         lr_, mgr_)
                inc = jax.vmap(lambda ff, tt, ll: _predict_tree_bundled(
                    binned_s, ff, tt, ll, max_depth, be_r))(
                    f, t, lf)[:, :, 0]
                Fm = Fm + inc
                if use_es:
                    owned = (vi_r >= lo) & (vi_r < lo + nl)
                    lvi = jnp.clip(vi_r - lo, 0, nl - 1)
                    Z = psum_d(jnp.where(owned[None, :], Fm[:, lvi], 0.0))
                    m = _chain_es_metric_val(Z, yv_r, obj)
                else:
                    m = jnp.zeros(Fm.shape[0], jnp.float32)
                return Fm, (f, t, lf, m)

            Fm_end, (fs, ts, lfs, ms) = lax.scan(round_step, Fm_s, None,
                                                 length=n_rounds)
            return Fm_end, fs, ts, lfs, ms

        # out_shardings pinned to the shard_map out_specs: the async sweep
        # dispatches block N+1 while block N's outputs are still in flight,
        # and an explicit output layout keeps GSPMD from inserting a
        # resharding (or worse, a host round-trip) between chained launches
        # that feed one block's Fm/metrics into the next chunk's inputs.
        out_specs = (P(grid_axis, data_axis), P(None, grid_axis, None),
                     P(None, grid_axis, None), P(None, grid_axis, None, None),
                     P(None, grid_axis))
        fn = jax.jit(
            shard_map_compat(
                shard_fn, mesh,
                (P(data_axis, None), P(data_axis),
                 P(grid_axis, data_axis), P(grid_axis, data_axis),
                 P(None), P(None), P(None, None),
                 P(grid_axis), P(grid_axis), P(grid_axis), P(grid_axis),
                 P(grid_axis), P(grid_axis), P(grid_axis)),
                out_specs),
            out_shardings=tuple(NamedSharding(mesh, p) for p in out_specs))
        _TREE_SWEEP_JITS[key] = fn
    return fn(binned, y, W, Fm0, yv, vi, jnp.asarray(be_host), depth_lim,
              lams, mcws, migs, mins_, lrs, mgrs)


def grow_rf_grid_sharded(binned, Y, W_tr, BWr, feat_idx, pair_fold,
                         pair_min_ig, pair_min_inst, pair_depth,
                         mesh: Mesh, *, n_trees: int, msub: int,
                         n_bins: int, heap_depth: int, lam: float = 1e-3,
                         min_child_weight: float = 0.0,
                         onehot_targets: bool = False,
                         leaf_levels=()):
    """The mesh form of ``gbdt_kernels.grow_rf_grid``: every (candidate x
    fold) pair's forest grown as chunked shard_map launches — the flat
    tree axis (pair * n_trees + t) sharded over the GRID axis, rows over
    the data axis, per-level histograms psum'd (node compaction off so
    shards agree on slot layout — the ``grow_forest_sharded`` contract).

    Bags come PRE-GENERATED (``rf_bags_and_features`` — the same
    fold_in(seed, tree_id) stream as the on-device single-chip path, so
    both grow identical forests): ``BWr`` (T, N_pad) Poisson bags with
    zero on pad rows, committed P(None, "data") alongside the (F, N_pad)
    fold weights; ``feat_idx`` (T, msub) replicated.  Returns HOST
    (P, T, nodes)/(P, T, leaves, K) arrays (+ the depth-truncation
    snapshot map when ``leaf_levels``), matching ``grow_rf_grid``.
    """
    from ..models.gbdt_kernels import (_accel_bf16, _grow_tree_traced,
                                       forest_chunk_size)
    from ..utils.profiling import count_launch
    from .mesh import grid_sharding, shard_map_compat

    data_axis, grid_axis = mesh.axis_names
    g = int(mesh.shape[grid_axis])
    n_pad, d = binned.shape
    nl = n_pad // int(mesh.shape[data_axis])
    k = Y.shape[1]
    P_pairs = int(pair_fold.shape[0])
    total = n_trees * P_pairs
    hist_bf16 = _accel_bf16()
    leaf_levels = tuple(sorted(set(int(v) for v in leaf_levels
                                   if 0 < int(v) < heap_depth)))
    chunk = forest_chunk_size(
        total, heap_depth, msub, n_bins, k, n_rows=nl, compact=False,
        n_channels=(k if onehot_targets else k + 1), d_full=d,
        onehot_bytes=2 if hist_bf16 else 4)
    chunk = max(g, (chunk // g) * g)

    key = ("rf", _mesh_cache_key(mesh), chunk, heap_depth, n_bins, msub,
           float(lam), float(min_child_weight), onehot_targets,
           leaf_levels, hist_bf16)
    fn = _TREE_SWEEP_JITS.get(key)
    if fn is None:
        psum_d = functools.partial(lax.psum, axis_name=data_axis)

        def shard_fn(binned_s, Y_s, Wtr_s, BWr_s, fi, t_loc, fold,
                     mig, mi, dep, valid):
            bw = (Wtr_s[fold] * BWr_s[t_loc]
                  * valid[:, None].astype(jnp.float32))
            fi_l = fi[t_loc]

            def one(bw_row, mig_, mi_, lim, fidx):
                gm = bw_row[:, None] * Y_s
                h = jnp.broadcast_to(bw_row[:, None], gm.shape)
                return _grow_tree_traced(
                    binned_s, gm, h, bw_row,
                    jnp.ones(binned_s.shape[1], bool), lim,
                    max_depth=heap_depth, n_bins=n_bins,
                    lam=jnp.float32(lam),
                    min_child_weight=jnp.float32(min_child_weight),
                    min_info_gain=mig_, min_instances=mi_,
                    newton_leaf=jnp.bool_(False),
                    learning_rate=jnp.float32(1.0),
                    hist_bf16=hist_bf16, all_reduce=psum_d,
                    bag_mode="onehot" if onehot_targets else "bagged",
                    feat_idx=fidx, leaf_levels=leaf_levels)

            f, t, lf, snaps = jax.vmap(one)(bw, mig, mi, dep, fi_l)
            return f, t, lf, snaps

        # explicit out_shardings matching the shard_map out_specs — chunked
        # async launches keep a fixed grid-sharded output layout, so the
        # dispatch loop never forces a resharding between in-flight chunks
        out_specs = (P(grid_axis, None), P(grid_axis, None),
                     P(grid_axis, None, None),
                     tuple(P(grid_axis, None, None) for _ in leaf_levels))
        fn = jax.jit(
            shard_map_compat(
                shard_fn, mesh,
                (P(data_axis, None), P(data_axis, None), P(None, data_axis),
                 P(None, data_axis), P(None, None),
                 P(grid_axis), P(grid_axis), P(grid_axis), P(grid_axis),
                 P(grid_axis), P(grid_axis)),
                out_specs),
            out_shardings=jax.tree_util.tree_map(
                lambda p: NamedSharding(mesh, p), out_specs,
                is_leaf=lambda x: isinstance(x, P)))
        _TREE_SWEEP_JITS[key] = fn

    gs = grid_sharding(mesh)
    feats, threshs, leaves = [], [], []
    snap_parts = [[] for _ in leaf_levels]
    fi_dev = jnp.asarray(np.asarray(feat_idx, np.int32))
    for s in range(0, total, chunk):
        count_launch("rf_grid_chunk_sharded")
        flat = np.arange(s, s + chunk)
        t_loc = (flat % n_trees).astype(np.int32)
        p_idx = np.minimum(flat // n_trees, P_pairs - 1)
        args = [jax.device_put(np.ascontiguousarray(a), gs) for a in (
            t_loc, np.asarray(pair_fold, np.int32)[p_idx],
            np.asarray(pair_min_ig, np.float32)[p_idx],
            np.asarray(pair_min_inst, np.float32)[p_idx],
            np.asarray(pair_depth, np.int32)[p_idx],
            (flat < total).astype(np.int32))]
        f, t, lf, snaps = fn(binned, Y, W_tr, BWr, fi_dev, *args)
        e = min(s + chunk, total)
        feats.append(np.asarray(f)[: e - s])
        threshs.append(np.asarray(t)[: e - s])
        leaves.append(np.asarray(lf)[: e - s])
        for li, sv in enumerate(snaps):
            snap_parts[li].append(np.asarray(sv)[: e - s])
    feats = np.concatenate(feats) if len(feats) > 1 else feats[0]
    threshs = np.concatenate(threshs) if len(threshs) > 1 else threshs[0]
    leaves = np.concatenate(leaves) if len(leaves) > 1 else leaves[0]
    nodes = feats.shape[1]
    out = (feats.reshape(P_pairs, n_trees, nodes),
           threshs.reshape(P_pairs, n_trees, nodes),
           leaves.reshape(P_pairs, n_trees, *leaves.shape[1:]))
    if not leaf_levels:
        return out
    snap_map = {}
    for lv, parts in zip(leaf_levels, snap_parts):
        sv = np.concatenate(parts) if len(parts) > 1 else parts[0]
        snap_map[lv] = sv.reshape(P_pairs, n_trees, *sv.shape[1:])
    return (*out, snap_map)


# ---------------------------------------------------------------------------
# Explicit-collective rewrites of the sweep's inner steps (ROADMAP item 1):
# shard_map programs where each device reduces ITS rows and one psum over
# the data axis replaces the driver-side reduce — the hand-written form of
# what GSPMD derives for the whole-array paths above, kept explicit so the
# per-shard partial/psum contract (zero-weight pad rows are inert, results
# invariant to pad amount) is directly testable.
#
# These bodies run with check_rep/check_vma OFF (jax 0.4.x has no
# replication rule for the while_loop inside the Newton body), so the
# runtime never verifies that a replicated out_spec really is replicated.
# Two guards stand in: the shard-safety lint (analysis/shard_lint.py,
# TM040 — a reduction of sharded data with no collective in the body is
# flagged statically; this module is its regression corpus) and the
# TMOG_CHECK=1 pad-invariance/parity contracts (analysis/contracts.py,
# TM024/TM025) exercised by the tier-1 multichip smoke.
# ---------------------------------------------------------------------------

def colstats_psum(X, w, mesh: Mesh):
    """Weighted per-column (mean, var) with explicit per-shard partials.

    Each shard computes (sum w, w@X, w@X^2) over its rows; one ``psum``
    over the data axis merges them — the shard_map rewrite of
    ``_colstats`` (numerically identical: the reduction order over shards
    is fixed by the mesh).  Zero-weight rows (padding) contribute exactly
    nothing to every partial.
    """
    from .mesh import shard_map_compat

    data_axis = mesh.axis_names[0]

    def shard_fn(X_s, w_s):
        part = jnp.stack([jnp.concatenate([w_s.sum()[None], w_s @ X_s]),
                          jnp.concatenate([jnp.zeros((1,), X_s.dtype),
                                           w_s @ (X_s * X_s)])])
        tot = lax.psum(part, axis_name=data_axis)
        wsum = jnp.maximum(tot[0, 0], 1.0)
        mean = tot[0, 1:] / wsum
        var = tot[1, 1:] / wsum - mean ** 2
        return mean, var

    fn = shard_map_compat(shard_fn, mesh,
                          (P(data_axis, None), P(data_axis)),
                          (P(None), P(None)))
    return jax.jit(fn)(X, w)


def fit_logreg_newton_psum(X, y, mesh: Mesh, w=None, reg_param: float = 0.0,
                           max_iter: int = 50, tol: float = 1e-6):
    """Newton-IRLS logistic regression with per-shard Gram/gradient
    partials ``psum``-merged over the data axis — the explicit shard_map
    form of ``models.linear.fit_logistic_regression``'s L2 path (L1
    callers use the whole-array ``fit_logreg_sharded``).

    Each iteration: every shard computes its rows' (D+1, D+1) weighted
    Gram and (D+1,) gradient partials, one psum each merges them, and the
    replicated (D+1) solve runs identically on every device.  Zero-weight
    pad rows are inert in both partials, so the fit is invariant to the
    row-padding used to tile the mesh.  Returns host (coef, intercept).
    """
    from .mesh import shard_map_compat

    from ..models.linear import _damped_solve, _finite_or
    from .mesh import data_sharding, pad_to_multiple, sweep_matrix_sharding

    X = np.asarray(X, np.float32)
    n, d = X.shape
    if w is None:
        w = np.ones(n, np.float32)
    ndata = mesh.shape[mesh.axis_names[0]]
    Xp, _ = pad_to_multiple(X, ndata, axis=0)
    yp, _ = pad_to_multiple(np.asarray(y, np.float32), ndata)
    wp, _ = pad_to_multiple(np.asarray(w, np.float32), ndata)
    data_axis = mesh.axis_names[0]
    l2 = float(reg_param)

    def shard_fn(X_s, y_s, w_s):
        m = X_s.shape[0]
        Xa = jnp.concatenate([X_s, jnp.ones((m, 1), X_s.dtype)], axis=1)
        wsum = jnp.maximum(lax.psum(w_s.sum(), axis_name=data_axis), 1.0)

        def step(state):
            beta, _, it = state
            z = Xa @ beta
            p = jax.nn.sigmoid(z)
            g_part = Xa.T @ (w_s * (p - y_s) / wsum)
            s = jnp.maximum(w_s * p * (1 - p) / wsum, 1e-10) \
                * (w_s > 0)                       # pad rows: exactly zero
            H_part = (Xa * s[:, None]).T @ Xa
            grad = lax.psum(g_part, axis_name=data_axis)
            H = lax.psum(H_part, axis_name=data_axis)
            grad = grad.at[:d].add(l2 * beta[:d])
            H = H.at[jnp.arange(d), jnp.arange(d)].add(l2)
            nb = _finite_or(beta - _damped_solve(H, grad), beta)
            return nb, jnp.max(jnp.abs(nb - beta)), it + 1

        def cond(state):
            _, dn, it = state
            return (dn > tol) & (it < max_iter)

        beta0 = jnp.zeros(d + 1, jnp.float32)
        beta, _, _ = lax.while_loop(
            cond, step, (beta0, jnp.float32(jnp.inf), jnp.int32(0)))
        return beta

    fn = shard_map_compat(shard_fn, mesh,
                          (P(data_axis, None), P(data_axis), P(data_axis)),
                          P(None))
    xs = sweep_matrix_sharding(mesh)
    ds = data_sharding(mesh)
    beta = np.asarray(jax.jit(fn)(jax.device_put(Xp, xs),
                                  jax.device_put(yp, ds),
                                  jax.device_put(wp, ds)))
    return beta[:d], float(beta[d])


def histogram_psum(binned, g, h, w, mesh: Mesh, n_bins: int = 32):
    """Per-feature gradient/hessian/count histograms with per-shard
    partials ``psum``-merged over the data axis — the standalone form of
    the histogram build inside the sharded tree grower (the per-level
    ``all_reduce=psum`` in ``grow_forest_sharded``), exposed so the
    sweep's histogram step has a directly testable collective contract.

    ``binned``: (N, D) int bin ids; ``g``/``h``/``w``: (N,) per-row
    gradient / hessian / sample weight.  Returns replicated host
    (n_bins, D, 3) stacks of [g*w, h*w, w] sums per bin — zero-weight
    (padding) rows contribute nothing.
    """
    from .mesh import shard_map_compat

    from .mesh import data_sharding, pad_to_multiple, sweep_matrix_sharding

    binned = np.asarray(binned)
    n, d = binned.shape
    ndata = mesh.shape[mesh.axis_names[0]]
    bp, _ = pad_to_multiple(binned, ndata, axis=0)
    gp, _ = pad_to_multiple(np.asarray(g, np.float32), ndata)
    hp, _ = pad_to_multiple(np.asarray(h, np.float32), ndata)
    wp, _ = pad_to_multiple(np.asarray(w, np.float32), ndata)
    data_axis = mesh.axis_names[0]

    def shard_fn(b_s, g_s, h_s, w_s):
        oh = (b_s[:, None, :] == jnp.arange(n_bins)[None, :, None])
        oh = oh.astype(jnp.float32)                       # (m, B, D)
        vals = jnp.stack([g_s * w_s, h_s * w_s, w_s], axis=1)  # (m, 3)
        part = jnp.einsum("mbd,mk->bdk", oh, vals)
        return lax.psum(part, axis_name=data_axis)

    fn = shard_map_compat(
        shard_fn, mesh,
        (P(data_axis, None), P(data_axis), P(data_axis), P(data_axis)),
        P(None, None, None))
    xs = sweep_matrix_sharding(mesh)
    ds = data_sharding(mesh)
    out = jax.jit(fn, static_argnames=())(
        jax.device_put(bp, xs), jax.device_put(gp, ds),
        jax.device_put(hp, ds), jax.device_put(wp, ds))
    return np.asarray(out)


# ---------------------------------------------------------------------------
# Block-decomposed reductions (ROADMAP item 3 / the 10M-row pod data plane):
# the same inner sums as colstats_psum / fit_logreg_newton_psum /
# histogram_psum, decomposed into fixed-size row blocks folded through a
# DEVICE-RESIDENT accumulator — per-host memory scales with the block
# budget (TMOG_STREAM_RETAIN_MB), not the shard.  Each fold call is one
# async jit launch (acc' = acc + partial(block)), so JAX's async dispatch
# overlaps the next block's host prep/upload with the in-flight fold, the
# grid-group pattern from PR 17.  Cross-host combination happens ONCE per
# pass at the accumulator level (distributed/podstream.py gathers the
# per-host partials and sums them in host order — the allgather analogue
# of the resident kernels' lax.psum), so a pass over any number of hosts
# costs one exchange.
#
# Accumulation order is FIXED by the block grid (a pure function of
# (rows, cols, budget)), so two runs over the same rows fold bit-
# identically regardless of where the blocks live — the property the
# bench_scale10m parity and resume gates assert.  TMOG_BLOCK_KERNELS=0
# (read at call time, like TMOG_SYNC_SWEEP) collapses the grid to ONE
# whole-shard block: a single resident-style reduction, byte-identical to
# the pre-block path.
# ---------------------------------------------------------------------------

_BLOCK_KERNELS_ENV = "TMOG_BLOCK_KERNELS"
_BLOCK_ROWS_MIN = 1024


def block_kernels_enabled() -> bool:
    """Kill-switch, read at call time so tests/benches flip it per run:
    ``TMOG_BLOCK_KERNELS=0`` restores the resident (single whole-shard
    block) path byte-identically."""
    return os.environ.get(_BLOCK_KERNELS_ENV, "") != "0"


def block_rows_for(cols: int, dtype_bytes: int = 4,
                   retain_mb: Optional[int] = None) -> int:
    """Rows per block from the streaming retain budget.

    One quarter of the ``TMOG_STREAM_RETAIN_MB`` budget (default: the
    streaming driver's 256MB) — the block itself, its transient device
    copy, the accumulators, and chunk-parse headroom share the envelope,
    the same 1/4 rule as ``tuning.planner.advise_plan``'s retain_mb.
    Deterministic in (cols, dtype_bytes, env) only, so every host, every
    pass, and every resume derives the identical block grid without an
    exchange."""
    if retain_mb is None:
        from ..workflow.streaming import (_RETAIN_MB_DEFAULT,
                                          _RETAIN_MB_ENV)

        try:
            retain_mb = int(os.environ.get(_RETAIN_MB_ENV, "") or
                            _RETAIN_MB_DEFAULT)
        except ValueError:
            retain_mb = _RETAIN_MB_DEFAULT
    row_bytes = max(int(cols), 1) * int(dtype_bytes)
    target = (max(int(retain_mb), 1) << 20) // 4
    return max(target // row_bytes, _BLOCK_ROWS_MIN)


def block_grid(rows: int, cols: int, dtype_bytes: int = 4,
               retain_mb: Optional[int] = None) -> List[Tuple[int, int]]:
    """The [start, stop) row blocks one host folds, in fold order.

    With the kill-switch off the grid is one whole-range block (the
    resident path); otherwise fixed-size blocks with a short tail."""
    rows = int(rows)
    if rows <= 0:
        return []
    if not block_kernels_enabled():
        return [(0, rows)]
    br = block_rows_for(cols, dtype_bytes, retain_mb)
    return [(s, min(s + br, rows)) for s in range(0, rows, br)]


@jax.jit
def _colstats_fold_jit(acc, X_b, w_b):
    part = jnp.stack([jnp.concatenate([w_b.sum()[None], w_b @ X_b]),
                      jnp.concatenate([jnp.zeros((1,), X_b.dtype),
                                       w_b @ (X_b * X_b)])])
    return acc + part


def colstats_block_fold(blocks: Iterable[Tuple[np.ndarray, np.ndarray]],
                        cols: int) -> np.ndarray:
    """Fold (X_block, w_block) pairs into the (2, cols+1) colstats
    accumulator ``[[sum w, w@X], [0, w@X^2]]`` — THIS host's partial.
    Blocks stay on device only one at a time; the accumulator is device
    resident across the whole pass.  Returns the host partial (the
    caller cross-host combines, then ``colstats_from_acc``)."""
    acc = jnp.zeros((2, int(cols) + 1), jnp.float32)
    for X_b, w_b in blocks:
        acc = _colstats_fold_jit(acc, jnp.asarray(X_b, jnp.float32),
                                 jnp.asarray(w_b, jnp.float32))
    return np.asarray(acc)


def colstats_from_acc(acc: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(mean, var) from a COMBINED colstats accumulator — the replicated
    epilogue of ``colstats_psum``, identical formulas."""
    wsum = max(float(acc[0, 0]), 1.0)
    mean = acc[0, 1:] / wsum
    var = acc[1, 1:] / wsum - mean ** 2
    return mean, var


@jax.jit
def _newton_fold_jit(acc_g, acc_H, X_b, y_b, w_b, beta, inv_wsum):
    m = X_b.shape[0]
    Xa = jnp.concatenate([X_b, jnp.ones((m, 1), X_b.dtype)], axis=1)
    z = Xa @ beta
    p = jax.nn.sigmoid(z)
    g_part = Xa.T @ (w_b * (p - y_b) * inv_wsum)
    s = jnp.maximum(w_b * p * (1 - p) * inv_wsum, 1e-10) \
        * (w_b > 0)                           # zero-weight rows: inert
    H_part = (Xa * s[:, None]).T @ Xa
    return acc_g + g_part, acc_H + H_part


def newton_block_pass(blocks: Iterable[
        Tuple[np.ndarray, np.ndarray, np.ndarray]],
        beta: np.ndarray, wsum: float,
        d: int) -> Tuple[np.ndarray, np.ndarray]:
    """ONE Newton-IRLS pass over (X, y, w) blocks at the current ``beta``:
    per-block Gram/gradient partials folded into device-resident (D+1,)
    / (D+1, D+1) accumulators.  Returns the host partials; the caller
    combines across hosts and solves (``newton_solve_host``)."""
    inv = jnp.float32(1.0 / max(float(wsum), 1.0))
    beta_d = jnp.asarray(beta, jnp.float32)
    acc_g = jnp.zeros(d + 1, jnp.float32)
    acc_H = jnp.zeros((d + 1, d + 1), jnp.float32)
    for X_b, y_b, w_b in blocks:
        acc_g, acc_H = _newton_fold_jit(
            acc_g, acc_H, jnp.asarray(X_b, jnp.float32),
            jnp.asarray(y_b, jnp.float32), jnp.asarray(w_b, jnp.float32),
            beta_d, inv)
    return np.asarray(acc_g), np.asarray(acc_H)


@functools.partial(jax.jit, static_argnames=("d",))
def _newton_solve_jit(grad, H, beta, l2, d: int):
    from ..models.linear import _damped_solve, _finite_or

    grad = grad.at[:d].add(l2 * beta[:d])
    H = H.at[jnp.arange(d), jnp.arange(d)].add(l2)
    nb = _finite_or(beta - _damped_solve(H, grad), beta)
    return nb, jnp.max(jnp.abs(nb - beta))


def newton_solve_host(grad: np.ndarray, H: np.ndarray, beta: np.ndarray,
                      l2: float, d: int) -> Tuple[np.ndarray, float]:
    """The replicated (D+1) damped solve on COMBINED partials — the same
    ``_damped_solve``/``_finite_or`` step the resident kernel runs inside
    its while_loop.  Returns (new beta, max |step|)."""
    nb, dn = _newton_solve_jit(jnp.asarray(grad, jnp.float32),
                               jnp.asarray(H, jnp.float32),
                               jnp.asarray(beta, jnp.float32),
                               jnp.float32(l2), d)
    return np.asarray(nb), float(dn)


def fit_logreg_newton_blocked(blocks_fn: Callable[[], Iterable[
        Tuple[np.ndarray, np.ndarray, np.ndarray]]],
        d: int, *, reg_param: float = 0.0, max_iter: int = 50,
        tol: float = 1e-6, wsum: Optional[float] = None,
        combine: Optional[Callable[[np.ndarray], np.ndarray]] = None
        ) -> Tuple[np.ndarray, float, int]:
    """Newton-IRLS logistic regression over row blocks that never
    co-reside: the block-streaming rewrite of
    ``fit_logreg_newton_psum``'s Gram/grad inner step.

    ``blocks_fn()`` yields a FRESH (X, y, w) block iterator per call (one
    pass per Newton iteration — spilled blocks re-read from disk);
    ``combine`` merges a host-partial array across hosts (identity when
    single-host; the pod driver sums gathered partials in host order).
    One combine per pass: the g/H partials ride one stacked exchange.
    Returns host (coef, intercept, n_iter)."""
    if combine is None:
        combine = lambda a: a  # noqa: E731 - single-host identity
    if wsum is None:
        acc = np.zeros(1, np.float32)
        for _X_b, _y_b, w_b in blocks_fn():
            acc = acc + np.asarray(w_b, np.float32).sum(dtype=np.float32)
        wsum = float(combine(acc)[0])
    wsum = max(float(wsum), 1.0)
    beta = np.zeros(d + 1, np.float32)
    it = 0
    while it < max_iter:
        g, H = newton_block_pass(blocks_fn(), beta, wsum, d)
        # ONE cross-host exchange per pass: gradient + Gram stacked
        packed = combine(np.concatenate([g[None, :], H], axis=0))
        g, H = packed[0], packed[1:]
        beta, dn = newton_solve_host(g, H, beta, float(reg_param), d)
        it += 1
        if dn <= tol:
            break
    return beta[:d], float(beta[d]), it


@functools.partial(jax.jit, static_argnames=("n_bins",))
def _histogram_fold_jit(acc, b_b, g_b, h_b, w_b, n_bins: int):
    oh = (b_b[:, None, :] == jnp.arange(n_bins)[None, :, None])
    oh = oh.astype(jnp.float32)                        # (m, B, D)
    vals = jnp.stack([g_b * w_b, h_b * w_b, w_b], axis=1)   # (m, 3)
    return acc + jnp.einsum("mbd,mk->bdk", oh, vals)


def histogram_block_fold(blocks: Iterable[Tuple[
        np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
        d: int, n_bins: int = 32) -> np.ndarray:
    """Fold (binned, g, h, w) blocks into the (n_bins, D, 3) histogram
    accumulator — the block-streaming form of ``histogram_psum``'s
    per-shard partial.  Returns this host's partial; the caller combines
    across hosts (same [g*w, h*w, w] stacking)."""
    acc = jnp.zeros((n_bins, int(d), 3), jnp.float32)
    for b_b, g_b, h_b, w_b in blocks:
        acc = _histogram_fold_jit(
            acc, jnp.asarray(b_b, jnp.int32),
            jnp.asarray(g_b, jnp.float32), jnp.asarray(h_b, jnp.float32),
            jnp.asarray(w_b, jnp.float32), n_bins)
    return np.asarray(acc)


@jax.jit
def _logloss_fold_jit(acc, X_b, y_b, w_b, beta):
    m = X_b.shape[0]
    Xa = jnp.concatenate([X_b, jnp.ones((m, 1), X_b.dtype)], axis=1)
    z = Xa @ beta
    # numerically stable weighted logloss partial: [sum w*loss, sum w]
    loss = jnp.maximum(z, 0.0) - z * y_b + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return acc + jnp.stack([(w_b * loss).sum(), w_b.sum()])


def logloss_block_fold(blocks: Iterable[
        Tuple[np.ndarray, np.ndarray, np.ndarray]],
        beta: np.ndarray) -> np.ndarray:
    """Fold (X, y, w) blocks into the (2,) ``[sum w*logloss, sum w]``
    accumulator for a fixed ``beta`` — the candidate-scoring pass of the
    blocked linear sweep (winner = argmin combined loss/weight)."""
    acc = jnp.zeros(2, jnp.float32)
    beta_d = jnp.asarray(beta, jnp.float32)
    for X_b, y_b, w_b in blocks:
        acc = _logloss_fold_jit(acc, jnp.asarray(X_b, jnp.float32),
                                jnp.asarray(y_b, jnp.float32),
                                jnp.asarray(w_b, jnp.float32), beta_d)
    return np.asarray(acc)


@jax.jit
def _colstats_corr_jit(X, y, w):
    """Weighted column stats + Pearson-with-label, formulas matching the
    SanityChecker host path exactly (variance ddof=1, label centered over
    real rows) so mesh and single-device runs drop the same features."""
    wsum = jnp.maximum(w.sum(), 2.0)
    mean = (w @ X) / wsum
    var = (w @ ((X - mean) ** 2)) / (wsum - 1.0)
    big = jnp.float32(3.0e38)
    mn = jnp.min(jnp.where(w[:, None] > 0, X, big), axis=0)
    mx = jnp.max(jnp.where(w[:, None] > 0, X, -big), axis=0)
    ymean = (w @ y) / wsum
    yc = (y - ymean) * w
    num = yc @ (X - mean)
    den = (jnp.sqrt(jnp.maximum(var, 1e-30) * (wsum - 1.0))
           * jnp.sqrt(jnp.maximum(yc @ yc, 1e-30)))
    corr = jnp.nan_to_num(num / den)
    return mean, var, mn, mx, corr


def colstats_corr_sharded(X: np.ndarray, y: np.ndarray, mesh: Mesh):
    """SanityChecker statistics over a row-sharded matrix: one jitted
    program whose column reductions GSPMD psums over ICI — the TPU
    replacement for the reference's executor-distributed
    ``Statistics.colStats``/``corr`` (SanityChecker.scala:380-470).

    Returns host (mean, variance, min, max, corr_with_label) numpy arrays;
    padded rows carry zero weight so results match the host formulas.
    """
    from .mesh import data_sharding, pad_to_multiple

    n = X.shape[0]
    ndata = mesh.shape[mesh.axis_names[0]]
    Xp, _ = pad_to_multiple(np.asarray(X, np.float32), ndata, axis=0)
    yp, _ = pad_to_multiple(np.asarray(y, np.float32), ndata)
    w = np.zeros(Xp.shape[0], np.float32)
    w[:n] = 1.0
    ds = data_sharding(mesh)
    out = _colstats_corr_jit(jax.device_put(Xp, ds),
                             jax.device_put(yp, ds), jax.device_put(w, ds))
    packed = np.asarray(jnp.stack(out))  # one host fetch
    return tuple(packed)


#: row block for the sharded numeric-profile histogram build (bounds the
#: transient (rows, bins, D) one-hot)
_PROFILE_ROW_BLOCK = 32768


@functools.partial(jax.jit, static_argnames=("n_bins",))
def _profile_numeric_jit(X, m, n_bins: int):
    """Per-column count/nulls/moments/min/max + fixed-grid histogram in ONE
    program; on sharded inputs GSPMD psums every reduction over ICI.

    Moments are accumulated about a per-column ANCHOR (the column's
    midrange): raw f32 sums of e.g. ms-epoch date values (~1.7e12) are
    pure rounding noise, while centered deviations keep full relative
    precision — the host reconstructs the raw f64 moments from (anchor,
    centered sums)."""
    n, d = X.shape
    mf = m & jnp.isfinite(X)
    cnt = m.sum(axis=0).astype(jnp.float32)
    valid = mf.sum(axis=0).astype(jnp.float32)
    big = jnp.float32(3.0e38)
    mn = jnp.min(jnp.where(mf, X, big), axis=0)
    mx = jnp.max(jnp.where(mf, X, -big), axis=0)
    anchor = jnp.where(valid > 0, 0.5 * (mn + mx), 0.0)
    Xc = jnp.where(mf, X - anchor[None, :], 0.0)
    s = Xc.sum(axis=0)
    s2 = (Xc * Xc).sum(axis=0)
    w = jnp.maximum(mx - mn, 1e-30)
    b = jnp.clip(((X - mn[None, :]) / w[None, :] * n_bins).astype(jnp.int32),
                 0, n_bins - 1)
    n_blk = -(-n // _PROFILE_ROW_BLOCK)
    pad = n_blk * _PROFILE_ROW_BLOCK - n
    b_p = jnp.pad(b, ((0, pad), (0, 0))).reshape(n_blk, -1, d)
    m_p = jnp.pad(mf, ((0, pad), (0, 0))).reshape(n_blk, -1, d)

    def block(acc, xs):
        bb, mm = xs
        oh = ((bb[:, None, :] == jnp.arange(n_bins)[None, :, None])
              & mm[:, None, :]).astype(jnp.float32)
        return acc + oh.sum(axis=0), None

    hist, _ = lax.scan(block, jnp.zeros((n_bins, d), jnp.float32),
                       (b_p, m_p))
    return cnt, valid, s, s2, mn, mx, hist, anchor


def profile_numeric_sharded(X: np.ndarray, mask: np.ndarray, mesh: Mesh,
                            n_bins: int = 100):
    """RawFeatureFilter's numeric distribution pass over a row-sharded
    matrix: ONE jitted program whose column reductions (counts, moments,
    min/max, fixed-grid histogram) GSPMD psums over ICI — the TPU analogue
    of the reference's executor-distributed per-partition profile +
    monoid reduce (RawFeatureFilter.scala:489-545,
    FeatureDistribution.scala:187-192).

    Returns host arrays (nulls, valid, sum, sum2, min, max,
    hist (n_bins, D), edges (n_bins+1, D)); padded rows carry mask=False
    so results match an unsharded pass."""
    from .mesh import data_sharding, pad_to_multiple

    n = X.shape[0]
    ndata = mesh.shape[mesh.axis_names[0]]
    Xp, _ = pad_to_multiple(np.asarray(X, np.float32), ndata, axis=0)
    mp = np.zeros(Xp.shape, bool)
    mp[:n] = np.asarray(mask, bool)
    ds = data_sharding(mesh)
    out = _profile_numeric_jit(jax.device_put(Xp, ds),
                               jax.device_put(mp, ds), n_bins)
    nonnull, valid, s_c, s2_c, mn, mx = (np.asarray(v, np.float64)
                                         for v in out[:6])
    hist = np.asarray(out[6])
    anchor = np.asarray(out[7], np.float64)
    nulls = n - nonnull
    # all-null/non-finite columns keep the +-big sentinels: collapse to 0
    # so the edge grid below stays finite (their histograms are all-zero)
    empty = valid == 0
    mn = np.where(empty, 0.0, mn)
    mx = np.where(empty, 0.0, mx)
    # reconstruct raw f64 moments from the anchor-centered device sums:
    # sum(x) = sum(x-a) + n*a ; sum(x^2) = sum((x-a)^2) + 2a*sum(x-a) + n*a^2
    s = s_c + valid * anchor
    s2 = s2_c + 2.0 * anchor * s_c + valid * anchor * anchor
    edges = np.linspace(mn, mx, n_bins + 1)          # (n_bins+1, D)
    return nulls, valid, s, s2, mn, mx, hist, edges


def fit_logreg_sharded(X: np.ndarray, y: np.ndarray, mesh: Mesh,
                       w: Optional[np.ndarray] = None, **kwargs):
    """Data/model-parallel logistic regression: shard inputs on the mesh and
    run the standard jitted IRLS trainer — GSPMD partitions the per-iteration
    (D,N)@(N,D) Gram matmuls and psums partial Hessians over ICI.

    The returned fit is sliced back to the caller's feature count (column
    padding used to tile the model axis is stripped)."""
    from ..models.linear import LinearFit, fit_logistic_regression
    d = X.shape[1]
    X_dev, y_dev, w_dev = shard_dataset(X, y, mesh, w)
    fit = fit_logistic_regression(X_dev, y_dev, w_dev, **kwargs)
    coef = fit.coef[..., :d] if fit.coef.shape[-1] != d else fit.coef
    return LinearFit(coef, fit.intercept, fit.n_iter, fit.converged)


def quantile_bins_sharded(X: np.ndarray, mesh: Mesh, max_bins: int = 32,
                          sample_rows: int = 200_000) -> np.ndarray:
    """Mesh-sharded quantile sketch — the distributed analogue of
    ``gbdt_kernels.quantile_bins`` (the reference computes its feature
    distributions executor-distributed, RawFeatureFilter.scala:489-545;
    XGBoost sketches with Rabit allreduce).

    Each shard stride-samples its local rows, the per-shard samples
    ``all_gather`` over ICI into one pooled (S·k, D) sample, and the
    per-feature quantiles compute replicated on every device — one
    program, one collective, no host pass over the matrix.  With
    ``sample_rows >= N`` the pooled sample is exactly the whole matrix, so
    the edges match the host sketch bit-for-bit (same linear-interpolation
    quantiles); under sampling they agree to sketch tolerance.
    """
    from .mesh import data_sharding, pad_to_multiple

    X = np.asarray(X, np.float32)
    n, d = X.shape
    data_axis = mesh.axis_names[0]
    n_shards = mesh.shape[data_axis]
    Xp, _ = pad_to_multiple(X, n_shards, axis=0)
    rows_valid = np.zeros(Xp.shape[0], np.float32)
    rows_valid[:n] = 1.0
    local = Xp.shape[0] // n_shards
    k = max(1, min(local, -(-min(sample_rows, n) // n_shards)))
    qs = np.linspace(0, 1, max_bins + 1)[1:-1].astype(np.float32)

    from .mesh import shard_map_compat

    def shard_fn(X_s, valid_s):
        # stride-sample k local rows; pad rows re-sample row 0 of the
        # shard but carry weight 0 via +inf sentinel replacement below
        stride = max(1, X_s.shape[0] // k)
        idx = (jnp.arange(k) * stride) % X_s.shape[0]
        samp = X_s[idx]                                  # (k, D)
        ok = valid_s[idx] > 0
        # invalid (padding) rows -> NaN, excluded by nanquantile
        samp = jnp.where(ok[:, None], samp, jnp.nan)
        pooled = lax.all_gather(samp, data_axis).reshape(-1, samp.shape[1])
        return jnp.nanquantile(pooled, jnp.asarray(qs), axis=0).T  # (D, B-1)

    ds = data_sharding(mesh)
    fn = shard_map_compat(shard_fn, mesh,
                          (P(data_axis, None), P(data_axis)),
                          P(None, None))
    edges = np.array(fn(jax.device_put(Xp, ds),
                        jax.device_put(rows_valid, ds)),
                     np.float32)   # np.array: writable host copy
    # same dedup rule as the host sketch: collapse non-increasing edges
    eps = 1e-7
    for j in range(d):
        e = edges[j]
        dup = np.concatenate([[False], np.diff(e) <= eps])
        edges[j] = np.where(dup, np.inf, e)
    return edges
