"""Multi-slice sweep scheduling — grid candidates across pod slices.

Reference mapping (SURVEY §2.12 row 2, §5.8): the reference parallelises its
hyperparameter grid with a JVM thread pool over Spark jobs
(``OpCrossValidation.scala:113-138``).  At datacenter scale the TPU-native
analogue is TWO nested levels of parallelism:

 * WITHIN a slice: each candidate's fit is mesh-sharded over ICI (the
   ``with_mesh`` paths — GSPMD inserts psum/all_gather from shardings);
 * ACROSS slices: whole grid candidates are scheduled onto different pod
   slices, coordinated over DCN.  Candidates are embarrassingly parallel
   (they share only the input data and the final argmax), so the only
   cross-slice traffic is the scalar metric table — exactly the property
   that makes grid scheduling the right thing to put on the slow
   inter-slice fabric.

This module implements the scheduling + merge logic against a list of
``jax.sharding.Mesh`` objects (one per slice).  On one host the slices run
their partitions sequentially (a single controller cannot execute two
meshes concurrently); in a true multi-slice deployment each slice's
controller runs ``run_slice_partition`` on its own share and the
coordinator merges with ``merge_slice_results`` — the partition/merge
semantics (round-robin by candidate index, original candidate order restored,
single argbest) are identical either way, which is what the dryrun and the
CPU tests pin down.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["partition_candidates", "run_slice_partition",
           "merge_slice_results", "sliced_selector_sweep"]


def partition_candidates(models_and_params: Sequence[Tuple[Any, List[dict]]],
                         n_slices: int):
    """Round-robin (estimator, params) candidates across slices.

    Returns per-slice ``models_and_params`` lists plus, per slice, the
    original candidate indices (for order-preserving merge).  Round-robin
    at CANDIDATE granularity balances heterogeneous grids (a slice never
    holds two copies of the same long-running family back to back while
    another idles).
    """
    flat: List[Tuple[int, Any, Dict[str, Any]]] = []
    i = 0
    for proto, grid_points in models_and_params:
        for params in grid_points:
            flat.append((i, proto, params))
            i += 1
    slices: List[List[Tuple[int, Any, Dict[str, Any]]]] = [
        [] for _ in range(n_slices)]
    for j, entry in enumerate(flat):
        slices[j % n_slices].append(entry)
    out = []
    for members in slices:
        mp: List[Tuple[Any, List[dict]]] = []
        for _, proto, params in members:
            # one grid point per entry keeps the original index mapping
            # trivial; grid_groups re-batches same-family runs downstream
            if mp and mp[-1][0] is proto:
                mp[-1][1].append(params)
            else:
                mp.append((proto, [params]))
        out.append((mp, [idx for idx, _, _ in members]))
    return out


def run_slice_partition(selector, partition, mesh, X, y, base_weights):
    """Validate one slice's candidate share on that slice's mesh.

    ``selector`` provides the metric/validator configuration; the partition's
    candidates are fit mesh-sharded (each estimator's own ``with_mesh``
    path).  Returns this slice's ``ValidationResult`` list (slice order).
    """
    sub = type(selector)(
        models_and_params=partition,
        problem_type=selector.problem_type,
        validator=selector.validator,
        splitter=selector.splitter,
        validation_metric=selector.validation_metric)
    if mesh is not None:
        sub.with_mesh(mesh)
    candidates = sub._candidates()
    _, results = sub.validator.validate(
        candidates, X, y, base_weights,
        eval_fn=sub._metric, metric_name=sub.validation_metric,
        larger_better=sub.larger_better)
    return results


def merge_slice_results(per_slice_results, per_slice_indices,
                        larger_better: bool):
    """Merge slice result lists back into original candidate order and pick
    the global winner — the coordinator's entire DCN-side job (a scalar
    table per slice)."""
    from ..selector.validators import ValidationResult, _argbest

    total = sum(len(ix) for ix in per_slice_indices)
    merged: List[Optional[ValidationResult]] = [None] * total
    for results, indices in zip(per_slice_results, per_slice_indices):
        for r, idx in zip(results, indices):
            merged[idx] = r
    worst = float("-inf") if larger_better else float("inf")
    best = _argbest([r.metric_value if r is not None and r.error is None
                     else worst for r in merged], larger_better)
    return best, merged


def sliced_selector_sweep(selector, X: np.ndarray, y: np.ndarray,
                          base_weights: np.ndarray,
                          meshes: Sequence) -> Tuple[int, list]:
    """Full two-level sweep: candidates partitioned across ``meshes``
    (slices), each share validated mesh-sharded, results merged.

    Single-controller execution runs slices sequentially; the scheduling
    and merge semantics match a true per-slice-controller deployment.
    """
    parts = partition_candidates(selector.models_and_params, len(meshes))
    per_results, per_indices = [], []
    for (partition, indices), mesh in zip(parts, meshes):
        if not partition:
            per_results.append([])
            per_indices.append([])
            continue
        per_results.append(run_slice_partition(
            selector, partition, mesh, X, y, base_weights))
        per_indices.append(indices)
    return merge_slice_results(per_results, per_indices,
                               selector.larger_better)
