from .sanity_checker import (  # noqa: F401
    SanityChecker, SanityCheckerModel, MinVarianceFilter,
)
