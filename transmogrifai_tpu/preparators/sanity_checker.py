"""SanityChecker — automated feature validation against the label.

Reference: ``SanityChecker`` (core/.../impl/preparators/SanityChecker.scala:232,
fitFn :367-470, model :544-560), drop logic
``DerivedFeatureFilterUtils.getFeaturesToDrop``
(impl/preparators/DerivedFeatureFilterUtils.scala), summary metadata
``SanityCheckerMetadata`` (impl/preparators/SanityCheckerMetadata.scala), and
``MinVarianceFilter`` (impl/preparators/MinVarianceFilter.scala).

TPU design: colStats + label correlations are two matmul-reductions over the
device-resident (N, D) matrix (ops.stats); Cramér's V per categorical group is
a one-hot matmul contingency.  The fitted model is an index-gather on the
vector — the same "filter the slots" semantics as the reference.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..ops.stats import (
    col_stats, cramers_v, pearson_with_label, spearman_with_label,
)
from ..ops.vector_metadata import VectorMetadata
from ..stages.base import BinaryEstimator, BinaryModel
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import OPNumeric, OPVector

__all__ = ["SanityChecker", "SanityCheckerModel", "SanityCheckerSummary",
           "MinVarianceFilter"]


@dataclasses.dataclass
class ColumnStat:
    name: str
    parent_feature: str
    mean: float
    variance: float
    min: float
    max: float
    corr_label: float
    cramers_v: Optional[float]
    dropped: bool
    reasons: List[str]

    def to_json(self):
        return dataclasses.asdict(self)


class SanityCheckerSummary:
    """Structured fit summary (SanityCheckerSummary metadata parity)."""

    def __init__(self, stats: List[ColumnStat], dropped: List[str],
                 correlation_type: str, sample_size: float):
        self.stats = stats
        self.dropped = dropped
        self.correlation_type = correlation_type
        self.sample_size = sample_size

    def to_json(self):
        return {
            "correlationType": self.correlation_type,
            "sampleSize": self.sample_size,
            "dropped": self.dropped,
            "columnStats": [s.to_json() for s in self.stats],
        }


def _matrix_f32(values) -> np.ndarray:
    """The feature matrix as float32 WITHOUT re-packing when the upstream
    vectorizer already produced a float32 ndarray (VectorsCombiner emits
    C-contiguous float32); everything else (float64, device arrays, lists)
    still converts.  Callers must treat the result as read-only — it may
    alias the live column buffer."""
    if isinstance(values, np.ndarray) and values.dtype == np.float32:
        return values
    return np.asarray(values, dtype=np.float32)


class SanityChecker(BinaryEstimator):
    """Inputs: (label RealNN, features OPVector) -> cleaned OPVector."""

    # the stats pass is a big BLAS/XLA program; the execution plan
    # (workflow/plan.py) runs it serially, not on the host stage pool
    device_heavy = True

    # input schema (SchemaError at wiring, TM004 statically); the label
    # slot is declared for the leakage lint (TM006)
    input_types = (OPNumeric, OPVector)
    label_input_positions = (0,)

    def __init__(self,
                 check_sample: float = 1.0,
                 sample_seed: int = 42,
                 min_variance: float = 1e-5,
                 min_correlation: float = 0.0,
                 max_correlation: float = 0.95,
                 max_cramers_v: float = 0.95,
                 correlation_type: str = "pearson",
                 remove_bad_features: bool = True,
                 remove_feature_group: bool = True,
                 categorical_label: Optional[bool] = None,
                 max_label_classes: int = 100,
                 uid: Optional[str] = None):
        super().__init__(operation_name="sanityCheck", output_type=OPVector,
                         uid=uid)
        self.check_sample = check_sample
        self.sample_seed = sample_seed
        self.min_variance = min_variance
        self.min_correlation = min_correlation
        self.max_correlation = max_correlation
        self.max_cramers_v = max_cramers_v
        self.correlation_type = correlation_type
        self.remove_bad_features = remove_bad_features
        self.remove_feature_group = remove_feature_group
        self.categorical_label = categorical_label
        self.max_label_classes = max_label_classes
        self.mesh = None

    def with_mesh(self, mesh) -> "SanityChecker":
        """Multi-chip stats: colStats + label correlations run as one
        row-sharded program with GSPMD ICI reductions
        (parallel/sharded.colstats_corr_sharded) — the reference distributes
        exactly these over executors (SanityChecker.scala:380-470).
        Spearman needs a global rank sort and stays single-device."""
        self.mesh = mesh
        return self

    def fit_columns(self, data: ColumnarDataset, label_col: FeatureColumn,
                    features_col: FeatureColumn):
        X = _matrix_f32(features_col.values)
        y = np.nan_to_num(np.asarray(label_col.values, dtype=np.float32))
        n, d = X.shape
        if self.check_sample < 1.0:
            rng = np.random.default_rng(self.sample_seed)
            idx = rng.random(n) < self.check_sample
            X, y = X[idx], y[idx]
            n = len(y)
        vmeta = features_col.vmeta or VectorMetadata(
            "features", [])

        if (self.mesh is not None and self.correlation_type != "spearman"
                and X.size <= (1 << 24)):
            # mesh stats for data that is not yet past the host-BLAS
            # threshold; above it, host-resident matrices stay on the host
            # path below — shipping GBs to the device for a one-pass stat
            # costs more than the stat (a genuinely multi-host deployment
            # would feed device-resident shards instead)
            from ..parallel.sharded import colstats_corr_sharded

            mean_h, variance, min_h, max_h, corr = colstats_corr_sharded(
                X, y, self.mesh)
            corr = np.nan_to_num(corr)
        elif X.size > (1 << 24) and self.correlation_type != "spearman":
            # big host matrices: means/variance/Pearson are one BLAS pass on
            # host (~1 s/GB); shipping the matrix to the device first costs
            # ~70 s of tunnel upload per GB
            mean_h = X.mean(axis=0, dtype=np.float64)
            variance = X.var(axis=0, ddof=1, dtype=np.float64)
            min_h, max_h = X.min(axis=0), X.max(axis=0)
            yc = (y - y.mean()).astype(np.float64)
            # center X before the dot: an uncentered f32 product cancels
            # catastrophically for large-offset columns (e.g. timestamps)
            num = yc @ (X - mean_h)
            den = (np.sqrt(np.maximum(variance, 1e-30) * (n - 1))
                   * np.sqrt(max(float(yc @ yc), 1e-30)))
            with np.errstate(invalid="ignore", divide="ignore"):
                corr = np.nan_to_num(num / den)
        else:
            import jax.numpy as jnp

            stats = col_stats(X)
            corr_dev = (spearman_with_label(X, y)
                        if self.correlation_type == "spearman"
                        else pearson_with_label(X, y))
            # ONE stacked fetch for all per-column stats + correlations —
            # each separate np.asarray costs a full device round trip
            packed = np.asarray(jnp.stack([
                jnp.asarray(stats.mean), jnp.asarray(stats.variance),
                jnp.asarray(stats.min), jnp.asarray(stats.max),
                jnp.asarray(corr_dev)]))
            mean_h, variance, min_h, max_h, corr = packed
            corr = np.nan_to_num(corr)

        # label categorical? -> Cramér's V per categorical group
        uniq = np.unique(y)
        is_cat_label = (self.categorical_label
                        if self.categorical_label is not None
                        else len(uniq) <= min(self.max_label_classes, n // 2))
        group_cv: Dict[Tuple[str, Optional[str]], float] = {}
        if is_cat_label and vmeta.size == d:
            labels_int = np.searchsorted(uniq, y)
            for key, idxs in self._indicator_groups(vmeta).items():
                res = cramers_v(labels_int, X[:, idxs], len(uniq))
                group_cv[key] = res["cramersV"]

        return self._finalize(mean_h, variance, min_h, max_h, corr,
                              group_cv, vmeta, n, d)

    @staticmethod
    def _indicator_groups(vmeta) -> Dict[Tuple[str, Optional[str]], List[int]]:
        groups: Dict[Tuple[str, Optional[str]], List[int]] = {}
        for i, c in enumerate(vmeta.columns):
            if c.indicator_value is not None:
                groups.setdefault((c.parent_feature, c.grouping), []).append(i)
        return groups

    def _finalize(self, mean_h, variance, min_h, max_h, corr, group_cv,
                  vmeta, n: int, d: int) -> "SanityCheckerModel":
        """Drop rules + summary + model from computed column statistics
        (DerivedFeatureFilterUtils.getFeaturesToDrop parity) — shared by
        the in-core fit and the streaming finish_fit."""
        to_drop = np.zeros(d, dtype=bool)
        reasons: List[List[str]] = [[] for _ in range(d)]
        for j in range(d):
            if variance[j] < self.min_variance:
                to_drop[j] = True
                reasons[j].append("low variance")
            a = abs(corr[j])
            if a > self.max_correlation:
                to_drop[j] = True
                reasons[j].append(
                    f"label correlation {a:.3f} > {self.max_correlation} (leakage)")
            elif 0 < self.min_correlation and a < self.min_correlation:
                to_drop[j] = True
                reasons[j].append("correlation below minimum")
        if vmeta.size == d:
            for j, c in enumerate(vmeta.columns):
                cv = group_cv.get((c.parent_feature, c.grouping))
                if cv is not None and cv > self.max_cramers_v:
                    to_drop[j] = True
                    reasons[j].append(
                        f"group Cramér's V {cv:.3f} > {self.max_cramers_v}")

        col_names = (vmeta.column_names() if vmeta.size == d
                     else [f"f_{j}" for j in range(d)])
        parents = ([c.parent_feature for c in vmeta.columns]
                   if vmeta.size == d else ["features"] * d)
        col_stats_out = [
            ColumnStat(
                name=col_names[j], parent_feature=parents[j],
                mean=float(mean_h[j]), variance=float(variance[j]),
                min=float(min_h[j]),
                max=float(max_h[j]),
                corr_label=float(corr[j]),
                cramers_v=(group_cv.get((vmeta.columns[j].parent_feature,
                                         vmeta.columns[j].grouping))
                           if vmeta.size == d else None),
                dropped=bool(to_drop[j]), reasons=reasons[j])
            for j in range(d)
        ]

        if not self.remove_bad_features:
            keep = list(range(d))
        else:
            keep = [j for j in range(d) if not to_drop[j]]
        summary = SanityCheckerSummary(
            stats=col_stats_out,
            dropped=[col_names[j] for j in range(d) if to_drop[j]],
            correlation_type=self.correlation_type, sample_size=float(n))
        self.metadata["summary"] = summary.to_json()
        # vector-level moment baseline over the KEPT slots — the drift
        # monitor's feature-space view (serving/drift.py compares scored
        # traffic's slot moments via z-scores; raw-feature baselines come
        # from the vectorizers).  ndarrays so persistence externalizes
        # them bit-exactly into arrays.npz.
        self.metadata["drift_baseline_vector"] = {
            "names": [col_names[j] for j in keep],
            "n": float(n),
            "mean": np.asarray(mean_h, np.float64)[keep],
            "variance": np.asarray(variance, np.float64)[keep],
        }
        new_meta = vmeta.select(keep) if vmeta.size == d else None
        model = SanityCheckerModel(keep_indices=keep)
        model._new_vmeta = new_meta
        return model

    # -- streaming fit: moment + co-moment + contingency accumulators -------
    #
    # Column stats and label correlation accumulate via PearsonSketch
    # (Chan-merged float64 moments: matches in-core to ~1e-6, limited by the
    # in-core float32 stat paths; KEEP decisions are threshold comparisons
    # and match exactly on non-degenerate data).  Cramér's V contingency
    # sums are exact (integer-valued one-hot sums).  Spearman needs a
    # global rank sort and cannot stream — supports_streaming_fit is False
    # then and the two-pass driver materializes instead.

    @property
    def supports_streaming_fit(self) -> bool:  # type: ignore[override]
        return self.correlation_type != "spearman"

    class _StreamState:
        __slots__ = ("pearson", "label_values", "label_sums", "vmeta",
                     "d", "rng")

        def __init__(self, rng):
            from ..utils.sketches import PearsonSketch

            self.pearson = PearsonSketch()
            self.label_values = np.zeros(0, np.float64)
            self.label_sums: Optional[Dict[float, np.ndarray]] = {}
            self.vmeta = None
            self.d: Optional[int] = None
            self.rng = rng

    def begin_fit(self):
        if self.correlation_type == "spearman":
            raise ValueError(
                "SanityChecker streaming fit requires a streamable "
                "correlation (spearman needs a global rank sort)")
        rng = (np.random.default_rng(self.sample_seed)
               if self.check_sample < 1.0 else None)
        return SanityChecker._StreamState(rng)

    # -- checkpoint hooks: _StreamState <-> codec-safe dict -----------------
    # The rng round-trips through the bit generator's exact state, so a
    # resumed sampled fit draws the SAME row-selection stream it would
    # have drawn uninterrupted.

    def export_fit_state(self, state):
        return {"pearson": state.pearson,
                "label_values": state.label_values,
                "label_sums": state.label_sums,
                "vmeta": state.vmeta,
                "d": state.d,
                "rng": state.rng}

    def import_fit_state(self, payload):
        state = SanityChecker._StreamState(payload["rng"])
        state.pearson = payload["pearson"]
        state.label_values = np.asarray(payload["label_values"],
                                        dtype=np.float64)
        sums = payload["label_sums"]
        state.label_sums = (None if sums is None
                            else {float(k): np.asarray(v, np.float64)
                                  for k, v in sums.items()})
        state.vmeta = payload["vmeta"]
        state.d = None if payload["d"] is None else int(payload["d"])
        return state

    #: streaming Cramér's V tracks per-label column sums; past this many
    #: distinct label values the label cannot be categorical for any
    #: reasonable config and the contingency accumulator is abandoned
    _STREAM_LABEL_CAP_HARD = 4096

    def update_chunk(self, state, data, label_col, features_col):
        X = _matrix_f32(features_col.values)
        y = np.nan_to_num(np.asarray(label_col.values, dtype=np.float32))
        if state.rng is not None:
            # the SAME rng stream as the in-core sample: successive
            # chunk-length draws continue one PCG64 sequence, so the
            # selected rows match the monolithic fit's row-for-row
            sel = state.rng.random(len(y)) < self.check_sample
            X, y = X[sel], y[sel]
        if state.d is None:
            state.d = X.shape[1]
            state.vmeta = features_col.vmeta
        if len(y) == 0:
            return state
        state.pearson.update(X, y)
        uniq = np.unique(y)
        state.label_values = np.union1d(state.label_values, uniq)
        cap = (self._STREAM_LABEL_CAP_HARD if self.categorical_label
               else self.max_label_classes)
        if self.categorical_label is False \
                or len(state.label_values) > cap:
            state.label_sums = None
        if state.label_sums is not None:
            for uv in uniq:
                # gather stays float32 (no full f64 copy); the per-column
                # accumulation is float64 and exact for one-hot indicators
                sums = X[y == uv].sum(axis=0, dtype=np.float64)
                key = float(uv)
                prev = state.label_sums.get(key)
                state.label_sums[key] = (sums if prev is None
                                         else prev + sums)
        return state

    def merge_states(self, a, b):
        if b.d is None:
            return a
        if a.d is None:
            return b
        a.pearson.merge(b.pearson)
        a.label_values = np.union1d(a.label_values, b.label_values)
        if a.label_sums is None or b.label_sums is None:
            a.label_sums = None
        else:
            for k, v in b.label_sums.items():
                prev = a.label_sums.get(k)
                a.label_sums[k] = v if prev is None else prev + v
        return a

    def finish_fit(self, state) -> "SanityCheckerModel":
        from ..ops.stats import contingency_stats

        d = state.d or 0
        n = int(state.pearson.x.n) if state.pearson.c is not None else 0
        if n == 0 or d == 0:
            raise ValueError("SanityChecker streaming fit saw no rows")
        vmeta = state.vmeta or VectorMetadata("features", [])
        mean_h = np.asarray(state.pearson.x.mean)
        variance = np.asarray(state.pearson.x.variance(ddof=1))
        min_h = np.asarray(state.pearson.x.min)
        max_h = np.asarray(state.pearson.x.max)
        corr = state.pearson.correlation()

        uniq = state.label_values
        is_cat_label = (self.categorical_label
                        if self.categorical_label is not None
                        else len(uniq) <= min(self.max_label_classes,
                                              n // 2))
        group_cv: Dict[Tuple[str, Optional[str]], float] = {}
        if (is_cat_label and vmeta.size == d
                and state.label_sums is not None):
            tbl_full = np.stack([state.label_sums[float(v)] for v in uniq])
            for key, idxs in self._indicator_groups(vmeta).items():
                group_cv[key] = contingency_stats(
                    tbl_full[:, idxs])["cramersV"]

        return self._finalize(mean_h, variance, min_h, max_h, corr,
                              group_cv, vmeta, n, d)


class _VmetaExtraState:
    """Shared persistence of the filtered vector metadata (_new_vmeta)."""

    def extra_state(self):
        return ({"new_vmeta": self._new_vmeta.to_json()}
                if self._new_vmeta is not None else {})

    def set_extra_state(self, state):
        if "new_vmeta" in state:
            self._new_vmeta = VectorMetadata.from_json(state["new_vmeta"])


class SanityCheckerModel(_VmetaExtraState, BinaryModel):
    input_types = (OPNumeric, OPVector)
    label_input_positions = (0,)

    """Index-filter on the feature vector (SanityChecker.scala:544-560)."""

    def __init__(self, keep_indices: List[int], uid: Optional[str] = None):
        super().__init__(operation_name="sanityCheck", output_type=OPVector,
                         uid=uid)
        self.keep_indices = list(keep_indices)
        self._new_vmeta: Optional[VectorMetadata] = None

    def transform_columns(self, label_col, features_col) -> FeatureColumn:
        X = np.asarray(features_col.values)
        out = X[:, self.keep_indices]
        vmeta = self._new_vmeta
        if vmeta is None and features_col.vmeta is not None:
            vmeta = features_col.vmeta.select(self.keep_indices)
            self._new_vmeta = vmeta
        return FeatureColumn(OPVector, out.astype(np.float32), vmeta=vmeta)


class MinVarianceFilter(BinaryEstimator):
    """Unlabeled variance-only filter (MinVarianceFilter.scala parity).

    Accepts (anything, features OPVector); the first input is ignored so the
    stage shape matches SanityChecker and DAG wiring stays uniform.
    """

    input_arity = (1, 2)
    # first input may be anything (ignored, SanityChecker shape parity) and
    # may legitimately be the label
    label_input_positions = (0,)

    def __init__(self, min_variance: float = 1e-5, uid: Optional[str] = None):
        super().__init__(operation_name="minVariance", output_type=OPVector,
                         uid=uid)
        self.min_variance = min_variance

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        features_col = cols[-1]
        X = _matrix_f32(features_col.values)
        variance = np.asarray(col_stats(X).variance)
        keep = [j for j in range(X.shape[1])
                if variance[j] >= self.min_variance]
        vmeta = features_col.vmeta
        self.metadata["summary"] = {
            "dropped": ([vmeta.column_names()[j] for j in range(X.shape[1])
                         if j not in set(keep)]
                        if vmeta and vmeta.size == X.shape[1] else []),
        }
        model = MinVarianceFilterModel(keep_indices=keep)
        model._new_vmeta = (vmeta.select(keep)
                            if vmeta and vmeta.size == X.shape[1] else None)
        return model

    # -- streaming fit: variance via Welford moments ------------------------

    supports_streaming_fit = True

    def begin_fit(self):
        from ..utils.sketches import WelfordMoments

        return {"moments": WelfordMoments(), "vmeta": None, "d": None}

    def update_chunk(self, state, data, *cols):
        features_col = cols[-1]
        X = _matrix_f32(features_col.values)
        if state["d"] is None:
            state["d"] = X.shape[1]
            state["vmeta"] = features_col.vmeta
        state["moments"].update(X)
        return state

    def merge_states(self, a, b):
        if b["d"] is None:
            return a
        if a["d"] is None:
            return b
        a["moments"].merge(b["moments"])
        return a

    def finish_fit(self, state) -> "MinVarianceFilterModel":
        if state["d"] is None:
            raise ValueError("MinVarianceFilter streaming fit saw no rows")
        d = state["d"]
        variance = np.asarray(state["moments"].variance(ddof=1))
        keep = [j for j in range(d) if variance[j] >= self.min_variance]
        vmeta = state["vmeta"]
        self.metadata["summary"] = {
            "dropped": ([vmeta.column_names()[j] for j in range(d)
                         if j not in set(keep)]
                        if vmeta and vmeta.size == d else []),
        }
        model = MinVarianceFilterModel(keep_indices=keep)
        model._new_vmeta = (vmeta.select(keep)
                            if vmeta and vmeta.size == d else None)
        return model


class MinVarianceFilterModel(_VmetaExtraState, BinaryModel):
    input_arity = (1, 2)
    label_input_positions = (0,)

    def __init__(self, keep_indices: List[int], uid: Optional[str] = None):
        super().__init__(operation_name="minVariance", output_type=OPVector,
                         uid=uid)
        self.keep_indices = list(keep_indices)
        self._new_vmeta = None

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        features_col = cols[-1]
        X = np.asarray(features_col.values)
        vmeta = self._new_vmeta
        if vmeta is None and features_col.vmeta is not None:
            vmeta = features_col.vmeta.select(self.keep_indices)
        return FeatureColumn(OPVector, X[:, self.keep_indices].astype(np.float32),
                             vmeta=vmeta)
