from .base import Reader, DataFrameReader, RecordsReader, reader_for  # noqa: F401
from .streaming import (AsyncBatcher, FileStreamingReader,  # noqa: F401
                        IteratorStreamingReader, StreamingReader,
                        StreamingReaders)
from .files import CSVReader, CSVAutoReader, ParquetReader, JSONLinesReader, DataReaders  # noqa: F401
from .aggregates import AggregateDataReader, ConditionalDataReader, JoinedDataReader  # noqa: F401
