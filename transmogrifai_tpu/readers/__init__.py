from .base import (Reader, DataFrameReader, RecordsReader,  # noqa: F401
                   reader_for, ChunkStream)
from .streaming import (AsyncBatcher, FileStreamingReader,  # noqa: F401
                        IteratorStreamingReader, StreamingReader,
                        StreamingReaders)
from .files import CSVReader, CSVAutoReader, ParquetReader, JSONLinesReader, DataReaders  # noqa: F401
from .aggregates import (AggregateDataReader, ConditionalDataReader,  # noqa: F401
                         JoinedDataReader, JoinedAggregateDataReader,
                         TimeBasedFilter)
from .events import (StreamingAggregateReader,  # noqa: F401
                     StreamingConditionalReader, EventFoldState,
                     merge_fold_states, key_owner, streaming_view)
from .avro import (AvroReader, AvroSchemaCSVReader, read_avro,  # noqa: F401
                   write_avro, schema_feature_types)
