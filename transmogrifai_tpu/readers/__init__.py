from .base import Reader, DataFrameReader, RecordsReader, reader_for  # noqa: F401
from .files import CSVReader, CSVAutoReader, ParquetReader, JSONLinesReader, DataReaders  # noqa: F401
from .aggregates import AggregateDataReader, ConditionalDataReader, JoinedDataReader  # noqa: F401
