"""Aggregate / conditional / joined readers.

Reference: ``AggregateDataReader``/``ConditionalDataReader`` run the monoid
aggregation of SURVEY §2.4 keyed by entity with response/predictor cutoffs
(readers/DataReader.scala:206-351); ``JoinedDataReader`` joins readers on
keys with inner/left/outer semantics plus post-join aggregation
(readers/JoinedDataReader.scala:119-223, readers/JoinTypes.scala); factory
catalogue ``DataReaders.{Simple,Aggregate,Conditional}``
(readers/DataReaders.scala:44-270).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..aggregators import (
    AGGREGATOR_REGISTRY, CutOffTime, default_aggregator,
)
from ..features.feature import Feature
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import ID
from .base import Reader

__all__ = ["AggregateDataReader", "ConditionalDataReader",
           "JoinedDataReader", "JoinedAggregateDataReader",
           "TimeBasedFilter"]


def _records_of(source) -> List[dict]:
    if hasattr(source, "to_dict"):          # pandas
        return source.to_dict("records")
    if hasattr(source, "records"):          # AvroReader-like: lazy + cached
        return list(source.records)
    return list(source)


class AggregateDataReader(Reader):
    """Group records by entity key, monoid-aggregate each feature's events
    around a cutoff (DataReader.scala:206-278).

    Since the event-time ingestion algebra landed (readers/events.py) this
    class is a facade over the ONE streamed aggregation code path: every
    dataset generation — in-core or chunked — delegates to the equivalent
    :class:`~.events.StreamingAggregateReader`, whose full-range fold is
    asserted byte-identical to the historical in-core grouping
    (tests/test_events_streaming.py, tests/test_aggregators_readers.py).
    The streamed twin is cached so the key-scan pass (which also backs the
    EXACT ``estimate_rows``) runs once per reader, not once per call.
    """

    def __init__(self, source, key_fn: Callable[[dict], Any],
                 time_fn: Callable[[dict], int],
                 cutoff: Optional[CutOffTime] = None,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None):
        self.source = source
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.cutoff = cutoff or CutOffTime.no_cutoff()
        self.predictor_window_ms = predictor_window_ms
        self.response_window_ms = response_window_ms
        self._streamed = None

    def _streaming(self):
        from .events import streaming_view

        if self._streamed is None:
            self._streamed = streaming_view(self)
        # resilience can be attached after construction (with_resilience
        # returns self) — re-sync on every use so both views share ONE
        # config and therefore one dedup-ing quarantine sink
        self._streamed.resilience = self.resilience
        return self._streamed

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        return self._streaming().generate_dataset(raw_features)

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int, host_range=None):
        """True streamed chunks (the fold buffers only in-window events of
        owned keys — never the record log); ``host_range`` slices the
        sorted KEY universe, the row grid of aggregate readers."""
        return self._streaming().iter_chunks(raw_features, chunk_rows,
                                             host_range=host_range)

    def estimate_rows(self) -> Optional[int]:
        """EXACT: one output row per distinct post-policy entity key
        (counted by the cached key scan)."""
        return self._streaming().estimate_rows()

    def estimate_rows_exact(self) -> bool:
        return True


class ConditionalDataReader(AggregateDataReader):
    """Entity cutoff = time of the first record matching ``target_condition``
    (DataReader.scala:280-351); entities with no match are dropped
    (drop_if_no_target)."""

    def __init__(self, source, key_fn, time_fn,
                 target_condition: Callable[[dict], bool],
                 drop_if_no_target: bool = True,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None):
        super().__init__(source, key_fn, time_fn,
                         cutoff=CutOffTime.no_cutoff(),
                         predictor_window_ms=predictor_window_ms,
                         response_window_ms=response_window_ms)
        self.target_condition = target_condition
        self.drop_if_no_target = drop_if_no_target


class TimeBasedFilter:
    """Window spec for post-join aggregation (JoinedDataReader.scala:69-74):
    keep a child row when its ``condition`` time falls inside ``window_ms``
    before the entity's ``primary`` time."""

    def __init__(self, condition: str, primary: str, window_ms: int,
                 keep_condition: bool = False, keep_primary: bool = False):
        self.condition = condition
        self.primary = primary
        self.window_ms = int(window_ms)
        self.keep_condition = keep_condition
        self.keep_primary = keep_primary


_EMPTY_BY_STORAGE = {"text_list": (), "date_list": (), "map": {},
                     "multi_pick_list": frozenset()}


def _gather(col: FeatureColumn, idx: np.ndarray) -> FeatureColumn:
    """Vectorized gather with -1 = missing (masked / empty per storage).

    Missing object-storage rows get the SAME empty value ``from_values``
    uses ((), {}, frozenset(), None for text) so downstream vectorizers
    keep their iteration invariants.
    """
    missing = idx < 0
    if missing.all() or len(col.values) == 0:
        # one join side empty (or no matches at all): synthesize an
        # all-missing column without touching the empty source array
        return FeatureColumn.from_values(col.ftype, [None] * len(idx))
    safe = np.where(missing, 0, idx)
    out = col.take(safe)
    if missing.any():
        vals = out.values
        if isinstance(vals, np.ndarray) and vals.dtype == object:
            vals = vals.copy()
            empty = _EMPTY_BY_STORAGE.get(col.ftype.storage)
            for i in np.where(missing)[0]:
                # fresh dict per row (a shared mutable empty would alias)
                vals[i] = dict() if isinstance(empty, dict) else empty
        elif isinstance(vals, np.ndarray) and vals.dtype.kind == "f":
            vals = vals.copy()
            vals[missing] = np.nan
        mask = (out.mask if out.mask is not None
                else np.ones(len(idx), bool)) & ~missing
        return FeatureColumn(col.ftype, vals, mask, col.vmeta)
    return out


class JoinedDataReader(Reader):
    """Join two readers' datasets on key columns
    (JoinedDataReader.scala:119-223, JoinTypes.scala).

    The join is vectorized: per-side positional indices are matched with a
    pandas hash merge (duplicate right keys fan out like a SQL join) and
    every feature column is materialized with one ``take`` gather — feature
    materialization does no per-row Python work; only key stringification
    is one host pass per key column.  ``left_key`` / ``right_key`` accept a
    single name or a sequence (multi-key joins).
    """

    def __init__(self, left: Reader, right: Reader,
                 left_features: Sequence[Feature],
                 right_features: Sequence[Feature],
                 join_type: str = "outer",
                 left_key="key", right_key="key"):
        if join_type not in ("inner", "left", "outer"):
            raise ValueError(f"unknown join type {join_type!r}")
        self.left = left
        self.right = right
        self.left_features = list(left_features)
        self.right_features = list(right_features)
        self.join_type = join_type
        self.left_key = ([left_key] if isinstance(left_key, str)
                         else list(left_key))
        self.right_key = ([right_key] if isinstance(right_key, str)
                          else list(right_key))
        if len(self.left_key) != len(self.right_key):
            raise ValueError("left_key and right_key must have the same "
                             "number of columns")

    def with_secondary_aggregation(
            self, time_filter: TimeBasedFilter) -> "JoinedAggregateDataReader":
        """Post-join aggregation (JoinedDataReader.scala:225-236)."""
        return JoinedAggregateDataReader(
            self.left, self.right, self.left_features, self.right_features,
            join_type=self.join_type, left_key=self.left_key,
            right_key=self.right_key, time_filter=time_filter)

    @staticmethod
    def _with_key(reader: Reader, features: Sequence[Feature],
                  keys: Sequence[str]) -> ColumnarDataset:
        data = reader.generate_dataset(list(features))
        missing = [k for k in keys if k not in data]
        if missing:
            from ..features.builder import FeatureBuilder

            # one batched pass for ALL missing key columns (each extra
            # generate_dataset can be a full file re-parse)
            key_data = reader.generate_dataset(
                [FeatureBuilder.ID(k).as_predictor() for k in missing])
            for k in missing:
                data.set(k, key_data[k])
        return data

    def _join_indices(self, ldata: ColumnarDataset, rdata: ColumnarDataset):
        """(left_idx, right_idx, key strings) — -1 marks a missing side."""
        import pandas as pd

        def key_frame(data, keys, idx_name):
            cols = {f"k{i}": [str(v) for v in data[k].to_list()]
                    for i, k in enumerate(keys)}
            df = pd.DataFrame(cols)
            df[idx_name] = np.arange(len(df), dtype=np.int64)
            return df

        lf = key_frame(ldata, self.left_key, "_il")
        rf = key_frame(rdata, self.right_key, "_ir")
        on = [c for c in lf.columns if c != "_il"]
        merged = lf.merge(rf, on=on, how=self.join_type, sort=False)
        li = merged["_il"].fillna(-1).to_numpy(np.int64)
        ri = merged["_ir"].fillna(-1).to_numpy(np.int64)
        # composite keys join on \x1f (unit separator) — a printable
        # separator like '|' would let distinct tuples collide, silently
        # merging entities in the post-join aggregation
        keys = merged[on[0]].astype(str).to_numpy() if len(on) == 1 else \
            np.asarray(["\x1f".join(t) for t in
                        merged[on].astype(str).itertuples(index=False)])
        return li, ri, keys

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        lnames = {f.name for f in self.left_features}
        ldata = self._with_key(self.left, self.left_features, self.left_key)
        rdata = self._with_key(self.right, self.right_features,
                               self.right_key)
        li, ri, keys = self._join_indices(ldata, rdata)
        out = ColumnarDataset()
        for f in raw_features:
            src, idx = ((ldata, li) if f.name in lnames else (rdata, ri))
            if f.name not in src:
                raise KeyError(f"feature {f.name!r} not produced by either "
                               "side of the join")
            out.set(f.name, _gather(src[f.name], idx))
        out.set("key", FeatureColumn.from_values(ID, list(keys)))
        return out

    def stream(self, raw_features: Sequence[Feature], chunk_rows: int,
               host_range=None):
        """Chunked sort-merge join over key-sorted spill runs
        (readers/events.py), bounded by ``TMOG_STREAM_RETAIN_MB``.  Row
        ORDER is key-sorted (stable within a key) — a documented
        divergence from :meth:`generate_dataset`'s pandas hash-merge
        order; row CONTENT is identical."""
        from .events import stream_join

        return stream_join(self, raw_features, chunk_rows,
                           host_range=host_range)

    def _key_counts(self):
        """Per-side ``Counter`` of composite key strings (cached): the
        exact join cardinality needs multiplicities, not just distincts."""
        if getattr(self, "_key_counts_cache", None) is None:
            from collections import Counter

            def side(reader, keys):
                data = self._with_key(reader, [], keys)
                parts = [[str(v) for v in data[k].to_list()] for k in keys]
                return Counter("\x1f".join(p[i] for p in parts)
                               for i in range(len(parts[0])))

            self._key_counts_cache = (side(self.left, self.left_key),
                                      side(self.right, self.right_key))
        return self._key_counts_cache

    def estimate_rows(self) -> Optional[int]:
        """EXACT joined row count from per-side key multiplicities —
        matched keys fan out multiplicatively; left/outer add the
        unmatched side(s).  Host sharding can trust this instead of
        falling back to the counting pre-pass."""
        lc, rc = self._key_counts()
        n = sum(c * rc[k] for k, c in lc.items() if k in rc)
        if self.join_type in ("left", "outer"):
            n += sum(c for k, c in lc.items() if k not in rc)
        if self.join_type == "outer":
            n += sum(c for k, c in rc.items() if k not in lc)
        return n

    def estimate_rows_exact(self) -> bool:
        return True


class JoinedAggregateDataReader(JoinedDataReader):
    """Join then aggregate back to one row per key
    (JoinedAggregateDataReader, JoinedDataReader.scala:240-330): left
    (parent) features keep one copy per key; right (child) features
    monoid-aggregate over the rows whose ``time_filter.condition`` time
    falls within ``window_ms`` before the key's ``primary`` time."""

    def __init__(self, left, right, left_features, right_features,
                 join_type="outer", left_key="key", right_key="key",
                 time_filter: Optional[TimeBasedFilter] = None):
        super().__init__(left, right, left_features, right_features,
                         join_type=join_type, left_key=left_key,
                         right_key=right_key)
        if time_filter is None:
            raise ValueError("JoinedAggregateDataReader requires a "
                             "TimeBasedFilter")
        self.time_filter = time_filter

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        tf = self.time_filter
        feats = list(raw_features)
        names = {f.name for f in feats}
        extra = []
        for f in self.left_features + self.right_features:
            if f.name in (tf.condition, tf.primary) and f.name not in names:
                extra.append(f)
        joined = super().generate_dataset(feats + extra)
        keys = np.asarray(joined["key"].to_list())
        cond_t = joined[tf.condition].masked_values(fill=np.nan)
        prim_t = joined[tf.primary].masked_values(fill=np.nan)
        # entity primary time = max per key (the parent row's timestamp is
        # replicated by the join; max also covers duplicate parents)
        uniq, inv = np.unique(keys, return_inverse=True)
        prim_per_key = np.full(len(uniq), -np.inf)
        np.maximum.at(prim_per_key, inv, np.nan_to_num(prim_t, nan=-np.inf))
        prim_row = prim_per_key[inv]
        in_window = (np.nan_to_num(cond_t, nan=np.inf) <= prim_row) & (
            np.nan_to_num(cond_t, nan=-np.inf)
            > prim_row - tf.window_ms)
        lnames = {f.name for f in self.left_features}
        out = ColumnarDataset()
        for f in feats:
            if f.name == tf.condition and not tf.keep_condition:
                continue
            if f.name == tf.primary and not tf.keep_primary:
                continue
            col_vals = joined[f.name].to_list()
            if f.name in lnames:
                # parent: first non-missing copy per key (dummy aggregator,
                # JoinedDataReader.scala:285-292)
                vals = [None] * len(uniq)
                for g, v in zip(inv, col_vals):
                    if vals[g] is None and v is not None:
                        vals[g] = v
            else:
                gen = f.origin_stage
                agg = getattr(gen, "aggregator", None)
                if isinstance(agg, str):
                    agg = AGGREGATOR_REGISTRY[agg]
                agg = agg or default_aggregator(f.ftype)
                groups: Dict[int, List] = {}
                for g, v, ok in zip(inv, col_vals, in_window):
                    if ok and v is not None:
                        groups.setdefault(g, []).append(v)
                vals = [agg.reduce(groups.get(g, [])) if groups.get(g)
                        else None for g in range(len(uniq))]
            out.set(f.name, FeatureColumn.from_values(f.ftype, vals))
        out.set("key", FeatureColumn.from_values(ID, list(uniq)))
        return out

    def stream(self, raw_features: Sequence[Feature], chunk_rows: int,
               host_range=None):
        """Chunked sort-merge join + secondary aggregation — one row per
        key in sorted-key order, byte-identical to
        :meth:`generate_dataset` (whose ``np.unique`` key order is the
        same lexicographic sort)."""
        from .events import stream_join_aggregate

        return stream_join_aggregate(self, raw_features, chunk_rows,
                                     host_range=host_range)

    def estimate_rows(self) -> Optional[int]:
        """EXACT: one row per distinct joined key (inner: both sides;
        left: left keys; outer: either side)."""
        lc, rc = self._key_counts()
        if self.join_type == "inner":
            return len(lc.keys() & rc.keys())
        if self.join_type == "left":
            return len(lc)
        return len(lc.keys() | rc.keys())


