"""Aggregate / conditional / joined readers.

Reference: ``AggregateDataReader``/``ConditionalDataReader`` run the monoid
aggregation of SURVEY §2.4 keyed by entity with response/predictor cutoffs
(readers/DataReader.scala:206-351); ``JoinedDataReader`` joins readers on
keys with inner/left/outer semantics plus post-join aggregation
(readers/JoinedDataReader.scala:119-223, readers/JoinTypes.scala); factory
catalogue ``DataReaders.{Simple,Aggregate,Conditional}``
(readers/DataReaders.scala:44-270).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..aggregators import (
    AGGREGATOR_REGISTRY, CutOffTime, Event, FeatureAggregator,
)
from ..features.feature import Feature
from ..stages.generator import FeatureGeneratorStage
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import ID
from .base import DataFrameReader, Reader, RecordsReader, reader_for

__all__ = ["AggregateDataReader", "ConditionalDataReader",
           "JoinedDataReader"]


def _records_of(source) -> List[dict]:
    if hasattr(source, "to_dict"):          # pandas
        return source.to_dict("records")
    return list(source)


def _extract(gen: FeatureGeneratorStage, record: dict) -> Any:
    fn = gen.extract_fn or (lambda r: r.get(gen.name))
    return fn(record)


class AggregateDataReader(Reader):
    """Group records by entity key, monoid-aggregate each feature's events
    around a cutoff (DataReader.scala:206-278)."""

    def __init__(self, source, key_fn: Callable[[dict], Any],
                 time_fn: Callable[[dict], int],
                 cutoff: Optional[CutOffTime] = None,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None):
        self.source = source
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.cutoff = cutoff or CutOffTime.no_cutoff()
        self.predictor_window_ms = predictor_window_ms
        self.response_window_ms = response_window_ms

    def _grouped(self):
        groups: Dict[Any, List[dict]] = {}
        for r in _records_of(self.source):
            groups.setdefault(self.key_fn(r), []).append(r)
        return groups

    def _cutoff_for(self, records: List[dict]) -> Optional[int]:
        return self.cutoff.cutoff_for(records[0])

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        groups = self._grouped()
        keys = sorted(groups, key=repr)
        data = ColumnarDataset()
        aggs = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            agg = (AGGREGATOR_REGISTRY[gen.aggregator]
                   if gen.aggregator else None)
            window = gen.aggregate_window_ms
            aggs[f.name] = FeatureAggregator(
                f.ftype, f.is_response, aggregator=agg,
                predictor_window_ms=window or self.predictor_window_ms,
                response_window_ms=window or self.response_window_ms)
        for f in raw_features:
            gen = f.origin_stage
            vals = []
            for k in keys:
                records = groups[k]
                cutoff = self._cutoff_for(records)
                events = [Event(self.time_fn(r), _extract(gen, r))
                          for r in records]
                vals.append(aggs[f.name].extract(events, cutoff))
            data.set(f.name, FeatureColumn.from_values(f.ftype, vals))
        data.set("key", FeatureColumn.from_values(ID, [str(k) for k in keys]))
        return data


class ConditionalDataReader(AggregateDataReader):
    """Entity cutoff = time of the first record matching ``target_condition``
    (DataReader.scala:280-351); entities with no match are dropped
    (drop_if_no_target)."""

    def __init__(self, source, key_fn, time_fn,
                 target_condition: Callable[[dict], bool],
                 drop_if_no_target: bool = True,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None):
        super().__init__(source, key_fn, time_fn,
                         cutoff=CutOffTime.no_cutoff(),
                         predictor_window_ms=predictor_window_ms,
                         response_window_ms=response_window_ms)
        self.target_condition = target_condition
        self.drop_if_no_target = drop_if_no_target

    def _grouped(self):
        groups = super()._grouped()
        if self.drop_if_no_target:
            groups = {k: rs for k, rs in groups.items()
                      if any(self.target_condition(r) for r in rs)}
        return groups

    def _cutoff_for(self, records: List[dict]) -> Optional[int]:
        matching = [self.time_fn(r) for r in records
                    if self.target_condition(r)]
        return min(matching) if matching else None


class JoinedDataReader(Reader):
    """Join two readers' datasets on key columns
    (JoinedDataReader.scala:119-223)."""

    def __init__(self, left: Reader, right: Reader,
                 left_features: Sequence[Feature],
                 right_features: Sequence[Feature],
                 join_type: str = "outer",
                 left_key: str = "key", right_key: str = "key"):
        if join_type not in ("inner", "left", "outer"):
            raise ValueError(f"unknown join type {join_type!r}")
        self.left = left
        self.right = right
        self.left_features = list(left_features)
        self.right_features = list(right_features)
        self.join_type = join_type
        self.left_key = left_key
        self.right_key = right_key

    @staticmethod
    def _with_key(reader: Reader, features: Sequence[Feature],
                  key: str) -> ColumnarDataset:
        data = reader.generate_dataset(list(features))
        if key not in data:
            from ..features.builder import FeatureBuilder

            key_f = FeatureBuilder.ID(key).as_predictor()
            data.set(key, reader.generate_dataset([key_f])[key])
        return data

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        lnames = {f.name for f in self.left_features}
        ldata = self._with_key(self.left, self.left_features, self.left_key)
        rdata = self._with_key(self.right, self.right_features,
                               self.right_key)
        lkeys = [str(v) for v in ldata[self.left_key].to_list()]
        rkeys = [str(v) for v in rdata[self.right_key].to_list()]
        lidx = {k: i for i, k in enumerate(lkeys)}
        ridx = {k: i for i, k in enumerate(rkeys)}
        if self.join_type == "inner":
            keys = [k for k in lkeys if k in ridx]
        elif self.join_type == "left":
            keys = list(lkeys)
        else:
            keys = list(lkeys) + [k for k in rkeys if k not in lidx]

        out = ColumnarDataset()
        for f in raw_features:
            src, idx = ((ldata, lidx) if f.name in lnames else (rdata, ridx))
            vals = src[f.name].to_list() if f.name in src else []
            joined = [vals[idx[k]] if k in idx and idx[k] < len(vals) else None
                      for k in keys]
            out.set(f.name, FeatureColumn.from_values(f.ftype, joined))
        out.set("key", FeatureColumn.from_values(ID, keys))
        return out


