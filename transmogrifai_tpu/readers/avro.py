"""Avro ingestion — the reference's canonical data format.

Reference: ``AvroReaders.scala`` (simple/aggregate/conditional Avro readers),
``utils/io/avro/AvroInOut.scala`` (read/write helpers), and
``CSVReaders.scala`` (CSV rows TYPED via an Avro schema — the reference's
CSV path round-trips through Avro records, ``CSVToAvro.scala``).

The environment has no Avro package, so this module implements the Avro 1.x
Object Container File format directly (spec: binary zig-zag varint
primitives, blocked records between 16-byte sync markers, null/deflate
codecs).  This is host-side IO — the device pipeline starts after columns
are extracted — so pure Python mirrors the reference's JVM Avro lib role.

Supported schema surface: null, boolean, int, long, float, double, bytes,
string, fixed, enum, array, map, union, record (with named-type references).
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..features.feature import Feature
from ..types import feature_types as ft
from ..types.columns import ColumnarDataset, FeatureColumn
from .base import Reader, RecordsReader

__all__ = ["read_avro", "write_avro", "AvroReader", "AvroSchemaCSVReader",
           "avro_to_feature_type", "schema_feature_types",
           "AvroBlockError", "AvroRecordError"]

_MAGIC = b"Obj\x01"


class AvroBlockError(ValueError):
    """A corrupt Avro container block, attributed: the message carries the
    block index and the block's byte offset in the file, so an operator
    (or the quarantine sidecar) can point at the exact bytes."""

    def __init__(self, path: str, block_index: int, byte_offset: int,
                 reason: str):
        super().__init__(
            f"{path}: corrupt avro block {block_index} "
            f"(byte offset {byte_offset}): {reason}")
        self.path = path
        self.block_index = block_index
        self.byte_offset = byte_offset
        self.reason = reason


class AvroRecordError(AvroBlockError):
    """A record-level decode failure inside an otherwise-framed block —
    attributable down to the record index.  ``decoded`` holds the records
    that decoded cleanly BEFORE the failure (binary decoding desyncs at
    the first bad record, so everything after it in the block is
    unrecoverable and the quarantine policy drops block remainder)."""

    def __init__(self, path: str, block_index: int, byte_offset: int,
                 record_index: int, reason: str, decoded=None):
        super().__init__(path, block_index, byte_offset,
                         f"record {record_index} failed to decode: {reason}")
        self.record_index = record_index
        self.decoded = decoded if decoded is not None else []
_PRIMITIVES = ("null", "boolean", "int", "long", "float", "double",
               "bytes", "string")


# ---------------------------------------------------------------------------
# binary decoder / encoder (Avro spec §Binary Encoding)
# ---------------------------------------------------------------------------

class _Decoder:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def read_long(self) -> int:
        shift, acc = 0, 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zig-zag

    def read_null(self):
        return None

    def read_int(self) -> int:
        return self.read_long()  # same zig-zag varint wire format

    def read_boolean(self) -> bool:
        return self.read(1) != b"\x00"

    def read_float(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def read_double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")


class _Encoder:
    def __init__(self):
        self.out = io.BytesIO()

    def write(self, b: bytes):
        self.out.write(b)

    def write_long(self, v: int):
        v = (v << 1) ^ (v >> 63) if v >= 0 else ((-v - 1) << 1 | 1)
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.write(bytes([b | 0x80]))
            else:
                self.out.write(bytes([b]))
                break

    def write_boolean(self, v: bool):
        self.out.write(b"\x01" if v else b"\x00")

    def write_float(self, v: float):
        self.out.write(struct.pack("<f", v))

    def write_double(self, v: float):
        self.out.write(struct.pack("<d", v))

    def write_bytes(self, v: bytes):
        self.write_long(len(v))
        self.out.write(v)

    def write_string(self, v: str):
        self.write_bytes(v.encode("utf-8"))

    def getvalue(self) -> bytes:
        return self.out.getvalue()


# ---------------------------------------------------------------------------
# schema-driven (de)serialization
# ---------------------------------------------------------------------------

def _register_named(schema, named: Dict[str, Any]):
    if isinstance(schema, dict) and schema.get("type") in ("record", "enum",
                                                           "fixed"):
        name = schema.get("name", "")
        ns = schema.get("namespace", "")
        named[name] = schema
        if ns:
            named[f"{ns}.{name}"] = schema
        for f in schema.get("fields", []) or []:
            _register_named(f.get("type"), named)
    elif isinstance(schema, dict) and schema.get("type") in ("array", "map"):
        _register_named(schema.get("items") or schema.get("values"), named)
    elif isinstance(schema, list):
        for s in schema:
            _register_named(s, named)


def _decode(schema, dec: _Decoder, named: Dict[str, Any]):
    if isinstance(schema, str):
        if schema in _PRIMITIVES:
            return getattr(dec, f"read_{schema}")()
        return _decode(named[schema], dec, named)  # named-type reference
    if isinstance(schema, list):  # union: long index then value
        return _decode(schema[dec.read_long()], dec, named)
    t = schema["type"]
    if t in _PRIMITIVES:
        return getattr(dec, f"read_{t}")()
    if t == "record":
        return {f["name"]: _decode(f["type"], dec, named)
                for f in schema["fields"]}
    if t == "enum":
        return schema["symbols"][dec.read_long()]
    if t == "fixed":
        return dec.read(schema["size"])
    if t == "array":
        out = []
        while True:
            n = dec.read_long()
            if n == 0:
                break
            if n < 0:  # block with byte size prefix
                n = -n
                dec.read_long()
            for _ in range(n):
                out.append(_decode(schema["items"], dec, named))
        return out
    if t == "map":
        out = {}
        while True:
            n = dec.read_long()
            if n == 0:
                break
            if n < 0:
                n = -n
                dec.read_long()
            for _ in range(n):
                # key must decode BEFORE the value (subscript assignment
                # would evaluate the RHS first)
                k = dec.read_string()
                out[k] = _decode(schema["values"], dec, named)
        return out
    if isinstance(t, (dict, list)):  # nested {"type": {...}} wrapper
        return _decode(t, dec, named)
    raise ValueError(f"unsupported avro type {t!r}")


def _union_branch(schema_list, value):
    """Index of the union branch matching a Python value (writer side).

    Two passes — exact type matches first (int -> int/long, str -> string,
    enum only when the symbol is a member), widening matches second (int
    under a ['double'] union) — so the written branch index agrees with a
    reference Avro writer's choice instead of whichever loose match comes
    first."""
    def matches(s, v, exact):
        base = s if isinstance(s, str) else s.get("type")
        if v is None:
            return base == "null"
        if isinstance(v, bool):
            return base == "boolean"
        if isinstance(v, (int, np.integer)):
            return (base in ("int", "long") if exact
                    else base in ("int", "long", "double", "float"))
        if isinstance(v, (float, np.floating)):
            return base in ("double", "float")
        if isinstance(v, str):
            if exact:
                return base == "string" or (
                    base == "enum" and not isinstance(s, str)
                    and v in s.get("symbols", ()))
            return base in ("string", "enum")
        if isinstance(v, bytes):
            return base in ("bytes", "fixed")
        if isinstance(v, dict):
            return base in ("record", "map")
        if isinstance(v, (list, tuple)):
            return base == "array"
        return False
    for exact in (True, False):
        for i, s in enumerate(schema_list):
            if matches(s, value, exact):
                return i
    raise ValueError(f"no union branch in {schema_list} for {value!r}")


def _encode(schema, enc: _Encoder, value, named: Dict[str, Any]):
    if isinstance(schema, str):
        if schema in _PRIMITIVES:
            if schema == "null":
                return
            if schema in ("int", "long"):
                return enc.write_long(int(value))
            return getattr(enc, f"write_{schema}")(value)
        return _encode(named[schema], enc, value, named)
    if isinstance(schema, list):
        i = _union_branch(schema, value)
        enc.write_long(i)
        return _encode(schema[i], enc, value, named)
    t = schema["type"]
    if t in _PRIMITIVES or isinstance(t, (dict, list)):
        return _encode(t, enc, value, named)
    if t == "record":
        for f in schema["fields"]:
            v = value.get(f["name"]) if isinstance(value, dict) else None
            if v is None and "default" in f and not isinstance(
                    f["type"], list):
                v = f["default"]
            _encode(f["type"], enc, v, named)
        return
    if t == "enum":
        return enc.write_long(schema["symbols"].index(value))
    if t == "fixed":
        return enc.write(value)
    if t == "array":
        if value:
            enc.write_long(len(value))
            for v in value:
                _encode(schema["items"], enc, v, named)
        return enc.write_long(0)
    if t == "map":
        if value:
            enc.write_long(len(value))
            for k, v in value.items():
                enc.write_string(k)
                _encode(schema["values"], enc, v, named)
        return enc.write_long(0)
    raise ValueError(f"unsupported avro type {t!r}")


def _snappy_decompress(data: bytes) -> bytes:
    """Raw-snappy decompressor (decode only — written blocks use deflate).

    Format: varint uncompressed length, then tagged elements — 2-bit type:
    00 literal, 01/10/11 back-references with 1/2/4-byte offsets.
    """
    pos, shift, n = 0, 0, 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                ln = int.from_bytes(data[pos:pos + nb], "little")
                pos += nb
            ln += 1
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError("corrupt snappy stream (bad offset)")
        start = len(out) - off
        for i in range(ln):  # may self-overlap: copy byte-wise
            out.append(out[start + i])
    if len(out) != n:
        raise ValueError("corrupt snappy stream (length mismatch)")
    return bytes(out)


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------

class _FileDecoder:
    """Varint/bytes primitives over a FILE OBJECT — used for the container
    header and block framing of the streaming reader, so a chunked read
    never loads the whole file.  Record payloads still decode through the
    in-memory ``_Decoder`` hot path, block by block."""

    def __init__(self, fh):
        self.fh = fh

    def read(self, n: int) -> bytes:
        b = self.fh.read(n)
        if len(b) != n:
            raise EOFError("truncated avro data")
        return b

    def read_long(self) -> int:
        shift, acc = 0, 0
        while True:
            b = self.fh.read(1)
            if not b:
                raise EOFError("truncated avro varint")
            acc |= (b[0] & 0x7F) << shift
            if not b[0] & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zig-zag

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")


def _read_header(dec, path: str):
    """(schema, codec, sync, named) from an OCF header."""
    if dec.read(4) != _MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = dec.read_long()
        if n == 0:
            break
        if n < 0:
            n = -n
            dec.read_long()
        for _ in range(n):
            k = dec.read_string()
            meta[k] = dec.read_bytes()
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate", "snappy"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    sync = dec.read(16)
    named: Dict[str, Any] = {}
    _register_named(schema, named)
    return schema, codec, sync, named


def _decode_block(block: bytes, count: int, codec: str, schema, named,
                  path: str, block_index: int = 0,
                  byte_offset: int = 0) -> List[dict]:
    """Decode one container block's records.  Corruption is attributed:
    codec failures raise :class:`AvroBlockError` (block index + byte
    offset), per-record decode failures raise :class:`AvroRecordError`
    (record index too, with the cleanly-decoded prefix attached)."""
    try:
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec == "snappy":
            crc = int.from_bytes(block[-4:], "big")
            block = _snappy_decompress(block[:-4])
            if zlib.crc32(block) & 0xFFFFFFFF != crc:
                raise ValueError("snappy block CRC mismatch")
    except AvroBlockError:
        raise
    except Exception as exc:
        raise AvroBlockError(path, block_index, byte_offset,
                             f"{codec} decompression failed: {exc}") from exc
    bdec = _Decoder(block)
    out: List[dict] = []
    for i in range(count):
        try:
            out.append(_decode(schema, bdec, named))
        except Exception as exc:
            raise AvroRecordError(path, block_index, byte_offset, i,
                                  str(exc), decoded=out) from exc
    return out


def _handle_block_error(exc: AvroBlockError, count: int, resilience):
    """Quarantine a corrupt block's lost rows (policy permitting) and
    return the salvageable prefix records; re-raises under ``fail``."""
    if resilience is None or not resilience.quarantines:
        raise exc
    decoded = list(getattr(exc, "decoded", []) or [])
    lost = count - len(decoded)
    resilience.handle_bad_record(
        exc.path, f"block {exc.block_index} (byte {exc.byte_offset})",
        exc.reason, rows=max(lost, 1))
    return decoded


def read_avro(path: str, resilience=None) -> Tuple[Dict[str, Any],
                                                   List[dict]]:
    """Read an Avro OCF: returns (writer schema, records).

    ``resilience`` (a ``readers.resilience.ResilienceConfig`` with the
    quarantine policy) routes corrupt blocks to the sidecar and keeps
    going; the default fails fast with an attributed AvroBlockError.  A
    sync-marker mismatch always raises — past it the block FRAMING is
    gone, and silently resynchronizing could drop data unaccounted."""
    raw = open(path, "rb").read()
    dec = _Decoder(raw)
    schema, codec, sync, named = _read_header(dec, path)
    records: List[dict] = []
    block_index = 0
    while dec.pos < len(raw):
        byte_offset = dec.pos
        try:
            count = dec.read_long()
            size = dec.read_long()
            block = dec.read(size)
        except (EOFError, IndexError) as exc:
            raise AvroBlockError(path, block_index, byte_offset,
                                 f"truncated block framing: {exc}") from exc
        try:
            records.extend(_decode_block(block, count, codec, schema,
                                         named, path, block_index,
                                         byte_offset))
        except AvroBlockError as exc:
            records.extend(_handle_block_error(exc, count, resilience))
        if dec.read(16) != sync:
            raise AvroBlockError(path, block_index, byte_offset,
                                 "sync marker mismatch")
        block_index += 1
    return schema, records


def iter_avro_blocks(path: str, bytes_pos: Optional[dict] = None,
                     resilience=None):
    """Stream an Avro OCF block by block: yields ``(schema, records)`` per
    container block without ever holding the whole file or record list.
    ``bytes_pos["bytes"]``, when a dict is passed, tracks the file position
    after each yielded block (ingest byte accounting).  Corrupt blocks are
    attributed (index + byte offset) and, under a quarantine policy,
    skipped with their salvageable record prefix kept — the framing
    (size + sync marker) survives payload corruption, so the stream
    resumes at the next block."""
    from ..utils import faults

    with open(path, "rb") as fh:
        dec = _FileDecoder(fh)
        schema, codec, sync, named = _read_header(dec, path)
        block_index = 0
        while True:
            probe = fh.read(1)
            if not probe:
                return
            fh.seek(-1, 1)
            byte_offset = fh.tell()
            faults.fire("avro.block", index=block_index)
            try:
                count = dec.read_long()
                size = dec.read_long()
                block = dec.read(size)
            except EOFError as exc:
                raise AvroBlockError(path, block_index, byte_offset,
                                     f"truncated block framing: {exc}"
                                     ) from exc
            try:
                records = _decode_block(block, count, codec, schema, named,
                                        path, block_index, byte_offset)
            except AvroBlockError as exc:
                records = _handle_block_error(exc, count, resilience)
            if dec.read(16) != sync:
                raise AvroBlockError(path, block_index, byte_offset,
                                     "sync marker mismatch")
            if bytes_pos is not None:
                bytes_pos["bytes"] = fh.tell()
            block_index += 1
            yield schema, records


def write_avro(path: str, schema: Dict[str, Any], records: Sequence[dict],
               codec: str = "deflate", sync: bytes = b"\x07" * 16,
               block_records: int = 4096) -> None:
    """Write records as an Avro OCF (null or deflate codec)."""
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    named: Dict[str, Any] = {}
    _register_named(schema, named)
    enc = _Encoder()
    enc.write(_MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    enc.write_long(len(meta))
    for k, v in meta.items():
        enc.write_string(k)
        enc.write_bytes(v)
    enc.write_long(0)
    enc.write(sync)
    for s in range(0, len(records), block_records):
        chunk = records[s:s + block_records]
        benc = _Encoder()
        for r in chunk:
            _encode(schema, benc, r, named)
        payload = benc.getvalue()
        if codec == "deflate":
            co = zlib.compressobj(9, zlib.DEFLATED, -15)
            payload = co.compress(payload) + co.flush()
        enc.write_long(len(chunk))
        enc.write_long(len(payload))
        enc.write(payload)
        enc.write(sync)
    with open(path, "wb") as f:
        f.write(enc.getvalue())


# ---------------------------------------------------------------------------
# avro types -> feature types (cli/gen/AvroField.scala analogue)
# ---------------------------------------------------------------------------

def _unwrap_union(t):
    """['null', T] / [T, 'null'] -> T (nullability lives in the feature
    type); multi-branch unions fall back to text."""
    if isinstance(t, list):
        branches = [b for b in t if b != "null"]
        return branches[0] if len(branches) == 1 else "string"
    return t


def avro_to_feature_type(avro_type) -> Type[ft.FeatureType]:
    t = _unwrap_union(avro_type)
    if isinstance(t, dict):
        inner = t.get("type")
        if inner == "enum":
            return ft.PickList
        if inner == "fixed":
            return ft.Base64
        if inner == "array":
            item = _unwrap_union(t.get("items"))
            if item in ("int", "long"):
                return ft.DateList if "date" in str(
                    t.get("name", "")).lower() else ft.TextList
            return ft.TextList
        if inner == "map":
            val = _unwrap_union(t.get("values"))
            if val in ("float", "double"):
                return ft.RealMap
            if val in ("int", "long"):
                return ft.IntegralMap
            if val == "boolean":
                return ft.BinaryMap
            return ft.TextMap
        return avro_to_feature_type(inner)
    return {
        "boolean": ft.Binary,
        "int": ft.Integral, "long": ft.Integral,
        "float": ft.Real, "double": ft.Real,
        "string": ft.Text, "bytes": ft.Base64,
    }.get(t, ft.Text)


def schema_feature_types(schema: Dict[str, Any]) -> Dict[str, Type[ft.FeatureType]]:
    """Record schema -> {field name: feature type} (the typing contract the
    reference gets from Avro schemas, cli/gen/AvroField.scala)."""
    if schema.get("type") != "record":
        raise ValueError("expected a record schema")
    return {f["name"]: avro_to_feature_type(f["type"])
            for f in schema["fields"]}


# ---------------------------------------------------------------------------
# readers
# ---------------------------------------------------------------------------

class AvroReader(Reader):
    """Simple Avro reader (AvroReaders.scala CSVAutoReader analogue)."""

    def __init__(self, path: str, key_field: Optional[str] = None):
        self.path = path
        self.key_field = key_field
        self._cache: Optional[Tuple[Dict, List[dict]]] = None

    def _load(self) -> Tuple[Dict, List[dict]]:
        if self._cache is None:
            self._cache = read_avro(self.path, resilience=self.resilience)
        return self._cache

    @property
    def schema(self) -> Dict[str, Any]:
        return self._load()[0]

    @property
    def records(self) -> List[dict]:
        return self._load()[1]

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        key_fn = ((lambda r: str(r.get(self.key_field)))
                  if self.key_field else None)
        return RecordsReader(self.records,
                             key_fn=key_fn).generate_dataset(raw_features)

    def estimate_rows(self) -> Optional[int]:
        """EXACT record count from the container block headers: each
        block's framing carries its record count and payload size, so the
        scan seeks past every payload without decoding a single record —
        O(blocks) file reads.  Replaces the loose whole-file estimate the
        host-shard satellite called out."""
        cfg = self.resilience
        if cfg is not None and cfg.quarantines:
            # a quarantine policy can DROP records mid-block; the framing
            # count then over-reports the yield — not exact
            return None
        try:
            with open(self.path, "rb") as fh:
                dec = _FileDecoder(fh)
                _schema, _codec, _sync, _named = _read_header(dec, self.path)
                total = 0
                while True:
                    probe = fh.read(1)
                    if not probe:
                        return total
                    fh.seek(-1, 1)
                    count = dec.read_long()
                    size = dec.read_long()
                    fh.seek(size + 16, 1)  # payload + sync marker
                    total += count
        except (OSError, EOFError, ValueError):
            return None

    def estimate_rows_exact(self) -> bool:
        return self.estimate_rows() is not None

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int, host_range=None):
        """Block-streaming chunked read: container blocks decode one at a
        time and regroup into ``chunk_rows`` record batches — at most one
        block plus one chunk of records is ever resident."""
        from .base import ChunkStream, window_gen

        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        key_fn = ((lambda r: str(r.get(self.key_field)))
                  if self.key_field else None)
        pos = {"bytes": 0}

        def gen():
            pending: List[dict] = []
            for _schema, records in iter_avro_blocks(
                    self.path, bytes_pos=pos, resilience=self.resilience):
                pending.extend(records)
                while len(pending) >= chunk_rows:
                    batch, pending = (pending[:chunk_rows],
                                      pending[chunk_rows:])
                    yield RecordsReader(batch, key_fn=key_fn
                                        ).generate_dataset(raw_features)
            if pending:
                yield RecordsReader(pending, key_fn=key_fn
                                    ).generate_dataset(raw_features)

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g, bytes_fn=lambda: pos["bytes"])


class AvroSchemaCSVReader(Reader):
    """CSV columns NAMED by an Avro schema (CSVReaders.scala /
    ``CSVToAvro.scala``: headerless CSV rows are addressed via the .avsc).

    The schema's field→feature-type mapping is exposed as
    ``feature_types`` (available at construction — the CLI codegen derives
    typed FeatureBuilders from it, cli/gen/AvroField.scala); a feature's
    DECLARED type stays authoritative for column materialization, exactly
    as the reference's FeatureBuilder declarations override raw Avro types.
    """

    def __init__(self, csv_path: str, schema_path: str,
                 key_field: Optional[str] = None):
        self.csv_path = csv_path
        self.schema_path = schema_path
        self.key_field = key_field
        self.schema = json.loads(open(schema_path).read())
        if self.schema.get("type") != "record":
            raise ValueError(f"{schema_path}: expected a record schema")
        #: {field name: feature type} per the .avsc (codegen introspection)
        self.feature_types = schema_feature_types(self.schema)

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        import pandas as pd

        names = [f["name"] for f in self.schema["fields"]]
        df = pd.read_csv(self.csv_path, header=None, names=names,
                         skipinitialspace=True)
        out = ColumnarDataset()
        for f in raw_features:
            if f.name not in df.columns:
                raise KeyError(f"{f.name!r} not in avro schema fields "
                               f"{names}")
            out.set(f.name, FeatureColumn.from_values(
                f.ftype, df[f.name].tolist()))
        if self.key_field and self.key_field in df.columns:
            out.set("key", FeatureColumn.from_values(
                ft.ID, [str(v) for v in df[self.key_field].tolist()]))
        return out

    def estimate_rows(self) -> Optional[int]:
        """Line count of the headerless CSV — an ESTIMATE (quoted
        embedded newlines over-count; the schema-CSV satellite contract
        keeps this inexact so host sharding counts instead)."""
        from .files import _count_lines

        try:
            return _count_lines(self.csv_path)
        except OSError:
            return None

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int, host_range=None):
        """Chunked schema-typed CSV: pandas' streaming parser with the
        .avsc field names; feature-declared types drive materialization
        exactly as in ``generate_dataset``."""
        import pandas as pd

        from .base import ChunkStream, window_gen

        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        names = [f["name"] for f in self.schema["fields"]]
        fh = open(self.csv_path, "rb")
        pos = {"bytes": 0}

        def one(df) -> ColumnarDataset:
            out = ColumnarDataset()
            for f in raw_features:
                if f.name not in df.columns:
                    raise KeyError(f"{f.name!r} not in avro schema fields "
                                   f"{names}")
                out.set(f.name, FeatureColumn.from_values(
                    f.ftype, df[f.name].tolist()))
            if self.key_field and self.key_field in df.columns:
                out.set("key", FeatureColumn.from_values(
                    ft.ID, [str(v) for v in df[self.key_field].tolist()]))
            return out

        def gen():
            try:
                with pd.read_csv(fh, header=None, names=names,
                                 skipinitialspace=True,
                                 chunksize=chunk_rows) as it:
                    for df in it:
                        pos["bytes"] = fh.tell()
                        yield one(df)
            finally:
                fh.close()

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g, bytes_fn=lambda: pos["bytes"])
