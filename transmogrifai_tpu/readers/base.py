"""Data readers — host-side ingestion into columnar batches.

Reference: ``Reader.generateDataFrame`` contract (readers/Reader.scala:96,168),
``DataReader.read`` + key extraction (readers/DataReader.scala:57-173),
``DataReaders`` factory catalogue (readers/DataReaders.scala:44-270).

TPU design: readers run on host CPU (pandas/pyarrow) and produce a
``ColumnarDataset``; aggregate/conditional readers apply the monoid
aggregation of ``transmogrifai_tpu.aggregators`` grouped by entity key before
columnarization.  The device never sees raw records.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..features.feature import Feature
from ..stages.generator import FeatureGeneratorStage
from ..types.columns import ColumnarDataset, FeatureColumn
from ..utils import faults

__all__ = ["Reader", "DataFrameReader", "RecordsReader", "reader_for",
           "ChunkStream", "window_gen"]


def window_gen(gen, host_range):
    """Restrict a chunk generator to the global row window [start, stop).

    The generic ``host_range`` implementation every reader shares
    (distributed/hostshard.py): chunks entirely before the window are
    drained and discarded (streaming parses cannot seek rows), chunks
    overlapping an edge are sliced zero-copy, and iteration STOPS at
    ``stop`` — a pod process never parses the file past its own range.
    Chunk boundaries stay on the source's GLOBAL chunk grid (the first
    and last window chunks may be partial); the sequence is a pure
    function of (source chunking, window), which is the determinism the
    cross-host-count checkpoint cursor counts on.
    """
    start, stop = int(host_range[0]), int(host_range[1])
    if start < 0 or stop < start:
        raise ValueError(f"bad host_range ({start}, {stop})")

    def windowed():
        offset = 0          # global rows consumed from the source
        if start == stop:
            return
        for ds in gen:
            n = len(ds)
            lo = max(start - offset, 0)
            hi = min(stop - offset, n)
            offset += n
            if hi > lo:
                yield ds if (lo == 0 and hi == n) else ds.slice(lo, hi)
            if offset >= stop:
                break

    return windowed()


class ChunkStream:
    """Iterator of bounded ``ColumnarDataset`` chunks with byte accounting.

    ``bytes_read`` is a running total maintained by the producing reader
    (file position where available, else decoded-payload size); readers
    that cannot attribute bytes leave it at 0.  The out-of-core driver
    reads it from the SAME thread that advances the iterator (the prefetch
    pump), so no locking is needed.

    Every chunk production passes the ``reader.chunk`` fault-injection
    point (utils/faults.py) keyed by chunk index — a no-op unless a test
    armed a plan; the retry wrapper (readers/resilience.py) sits ABOVE this
    stream, so injected IO errors exercise the real recovery path.
    """

    def __init__(self, gen, bytes_fn=None):
        self._gen = iter(gen)
        self._bytes_fn = bytes_fn
        self._idx = 0
        self.bytes_read: int = 0

    def __iter__(self):
        return self

    def __next__(self) -> ColumnarDataset:
        faults.fire("reader.chunk", index=self._idx)
        ds = next(self._gen)
        self._idx += 1
        if self._bytes_fn is not None:
            self.bytes_read = int(self._bytes_fn())
        return ds


class Reader:
    """Produces the raw-feature dataset for a workflow."""

    #: optional ingestion resilience (retry/backoff + bad-record policy);
    #: ``None`` keeps the historical fail-fast behavior byte-identical
    resilience = None

    def with_resilience(self, retry=None, bad_records: str = "fail",
                        quarantine_path: Optional[str] = None,
                        max_bad_records: int = 1000) -> "Reader":
        """Attach a :class:`~..readers.resilience.ResilienceConfig`.

        ``retry``: a ``RetryPolicy``, ``True`` for the defaults, or None
        (no retries).  ``bad_records``: ``"fail"`` (default) or
        ``"quarantine"`` (requires ``quarantine_path``; unparseable rows
        land in that JSONL sidecar until ``max_bad_records`` rows, then
        the read fails fast).
        """
        from .resilience import (BadRecordPolicy, ResilienceConfig,
                                 RetryPolicy)

        if retry is True:
            retry = RetryPolicy()
        self.resilience = ResilienceConfig(
            retry=retry,
            bad_records=BadRecordPolicy(
                mode=bad_records, quarantine_path=quarantine_path,
                max_bad_records=max_bad_records))
        return self

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        raise NotImplementedError

    def estimate_rows(self) -> Optional[int]:
        """Cheap row-count estimate BEFORE reading (the cost planner's
        stream-vs-in-core input, tuning/planner.py) — None when the source
        cannot say without a full parse (file readers)."""
        return None

    def estimate_rows_exact(self) -> bool:
        """True when :meth:`estimate_rows` is the EXACT post-policy row
        count (in-memory readers; Avro block headers).  Host sharding
        (distributed/hostshard.py) trusts exact estimates and runs a
        counting pre-pass otherwise — line-count heuristics (CSV quoted
        newlines, quarantined rows) must return False here."""
        return False

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range: Optional[tuple] = None) -> ChunkStream:
        """Yield the dataset as bounded row chunks (out-of-core ingestion).

        Base fallback: materialize once and yield zero-copy row slices —
        correct for any reader — while the file readers override it with
        true streaming parses that never hold the full dataset, and the
        aggregate/conditional readers override it with the streamed
        event-time fold (readers/events.py) whose buffers hold only
        in-window events of owned keys.

        ``host_range=(start, stop)`` restricts the stream to that global
        row window (:func:`window_gen`) — the pod runtime's host-sharded
        ingest, honored by every reader.
        """
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")

        def gen():
            ds = self.generate_dataset(raw_features)
            n = len(ds)
            for start in range(0, n, chunk_rows):
                yield ds.slice(start, min(start + chunk_rows, n))

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g)


class DataFrameReader(Reader):
    """Wraps an in-memory pandas DataFrame (OpWorkflow.setInputDataset parity).

    Fast path: features without an ``extract_fn`` read their column directly;
    features with one fall back to per-record extraction.
    """

    def __init__(self, df, key_col: Optional[str] = None):
        self.df = df
        self.key_col = key_col

    def estimate_rows(self) -> Optional[int]:
        return len(self.df)

    def estimate_rows_exact(self) -> bool:
        return True

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        records: Optional[List[dict]] = None
        cols: Dict[str, FeatureColumn] = {}
        missing = [f.name for f in raw_features
                   if f.origin_stage.extract_fn is None  # type: ignore[union-attr]
                   and f.name not in self.df.columns]
        if missing:
            raise KeyError(
                f"input data is missing raw feature column(s) {missing}")
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            if gen.extract_fn is None:
                series = self.df[f.name]
                # ndarray fast path for numeric dtypes; object/string columns
                # go through the per-value converter (None handling)
                if series.dtype.kind in "fiub":
                    vals = series.to_numpy()
                else:
                    vals = series.tolist()
                cols[f.name] = FeatureColumn.from_values(f.ftype, vals)
            else:
                if records is None:
                    records = self.df.to_dict("records")
                cols[f.name] = gen.extract_column(records)
        return ColumnarDataset(cols)

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range: Optional[tuple] = None) -> "ChunkStream":
        """Row-range chunks over the wrapped frame; per-chunk extraction
        yields values identical to the monolithic path (numeric dtypes are
        frame-wide, so slicing cannot change per-chunk coercions)."""
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")

        def gen():
            n = len(self.df)
            for start in range(0, n, chunk_rows):
                part = self.df.iloc[start:min(start + chunk_rows, n)]
                yield DataFrameReader(part, self.key_col).generate_dataset(
                    raw_features)

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g)


class RecordsReader(Reader):
    """Wraps a list of dict/object records (setInputRDD parity)."""

    def __init__(self, records: Sequence[Any], key_fn: Optional[Callable[[Any], str]] = None):
        self.records = list(records)
        self.key_fn = key_fn

    def estimate_rows(self) -> Optional[int]:
        return len(self.records)

    def estimate_rows_exact(self) -> bool:
        return True

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        from ..types.feature_types import ID

        cols = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            cols[f.name] = gen.extract_column(self.records)
        ds = ColumnarDataset(cols)
        if self.key_fn is not None:
            ds.set("key", FeatureColumn.from_values(
                ID, [str(self.key_fn(r)) for r in self.records]))
        return ds

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range: Optional[tuple] = None) -> "ChunkStream":
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")

        def gen():
            n = len(self.records)
            for start in range(0, n, chunk_rows):
                yield RecordsReader(
                    self.records[start:start + chunk_rows],
                    key_fn=self.key_fn).generate_dataset(raw_features)

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g)


def reader_for(data) -> Reader:
    """Coerce user input to a Reader."""
    if isinstance(data, Reader):
        return data
    if isinstance(data, ColumnarDataset):
        return _PassthroughReader(data)
    if isinstance(data, (list, tuple)):
        return RecordsReader(data)
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return DataFrameReader(data)
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(f"cannot build a reader from {type(data)}")


class _PassthroughReader(Reader):
    def __init__(self, ds: ColumnarDataset):
        self.ds = ds

    def estimate_rows(self) -> Optional[int]:
        return len(self.ds)

    def estimate_rows_exact(self) -> bool:
        return True

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        missing = [f.name for f in raw_features if f.name not in self.ds]
        if missing:
            raise ValueError(f"dataset missing raw feature columns {missing}")
        return self.ds.select([f.name for f in raw_features])
