"""Data readers — host-side ingestion into columnar batches.

Reference: ``Reader.generateDataFrame`` contract (readers/Reader.scala:96,168),
``DataReader.read`` + key extraction (readers/DataReader.scala:57-173),
``DataReaders`` factory catalogue (readers/DataReaders.scala:44-270).

TPU design: readers run on host CPU (pandas/pyarrow) and produce a
``ColumnarDataset``; aggregate/conditional readers apply the monoid
aggregation of ``transmogrifai_tpu.aggregators`` grouped by entity key before
columnarization.  The device never sees raw records.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..features.feature import Feature
from ..stages.generator import FeatureGeneratorStage
from ..types.columns import ColumnarDataset, FeatureColumn

__all__ = ["Reader", "DataFrameReader", "RecordsReader", "reader_for"]


class Reader:
    """Produces the raw-feature dataset for a workflow."""

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        raise NotImplementedError


class DataFrameReader(Reader):
    """Wraps an in-memory pandas DataFrame (OpWorkflow.setInputDataset parity).

    Fast path: features without an ``extract_fn`` read their column directly;
    features with one fall back to per-record extraction.
    """

    def __init__(self, df, key_col: Optional[str] = None):
        self.df = df
        self.key_col = key_col

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        records: Optional[List[dict]] = None
        cols: Dict[str, FeatureColumn] = {}
        missing = [f.name for f in raw_features
                   if f.origin_stage.extract_fn is None  # type: ignore[union-attr]
                   and f.name not in self.df.columns]
        if missing:
            raise KeyError(
                f"input data is missing raw feature column(s) {missing}")
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            if gen.extract_fn is None:
                series = self.df[f.name]
                # ndarray fast path for numeric dtypes; object/string columns
                # go through the per-value converter (None handling)
                if series.dtype.kind in "fiub":
                    vals = series.to_numpy()
                else:
                    vals = series.tolist()
                cols[f.name] = FeatureColumn.from_values(f.ftype, vals)
            else:
                if records is None:
                    records = self.df.to_dict("records")
                cols[f.name] = gen.extract_column(records)
        return ColumnarDataset(cols)


class RecordsReader(Reader):
    """Wraps a list of dict/object records (setInputRDD parity)."""

    def __init__(self, records: Sequence[Any], key_fn: Optional[Callable[[Any], str]] = None):
        self.records = list(records)
        self.key_fn = key_fn

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        from ..types.feature_types import ID

        cols = {}
        for f in raw_features:
            gen = f.origin_stage
            assert isinstance(gen, FeatureGeneratorStage)
            cols[f.name] = gen.extract_column(self.records)
        ds = ColumnarDataset(cols)
        if self.key_fn is not None:
            ds.set("key", FeatureColumn.from_values(
                ID, [str(self.key_fn(r)) for r in self.records]))
        return ds


def reader_for(data) -> Reader:
    """Coerce user input to a Reader."""
    if isinstance(data, Reader):
        return data
    if isinstance(data, ColumnarDataset):
        return _PassthroughReader(data)
    if isinstance(data, (list, tuple)):
        return RecordsReader(data)
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return DataFrameReader(data)
    except ImportError:  # pragma: no cover
        pass
    raise TypeError(f"cannot build a reader from {type(data)}")


class _PassthroughReader(Reader):
    def __init__(self, ds: ColumnarDataset):
        self.ds = ds

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        missing = [f.name for f in raw_features if f.name not in self.ds]
        if missing:
            raise ValueError(f"dataset missing raw feature columns {missing}")
        return self.ds.select([f.name for f in raw_features])
