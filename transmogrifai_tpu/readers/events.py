"""Streamed event-time readers — the chunked per-key monoid fold.

Reference: ``AggregateDataReader``/``ConditionalDataReader`` apply the
monoid aggregation of SURVEY §2.4 keyed by entity around per-key cutoffs
(readers/DataReader.scala:206-351).  The in-core port
(readers/aggregates.py) materializes every record before grouping; this
module is the out-of-core twin: the SAME aggregation semantics as a
two-pass streamed fold over any chunked source, so the event-log workload
(clickstream -> "predict at the moment of event X") rides the streaming
trainer, checkpoint/resume, RFF, workflow-CV and the pod substrate with
no special cases.

Shape of the fold (both passes stream record chunks, never the file):

* **Pass A (key scan)** — resolve the key universe and per-key cutoffs:
  plain readers take the ``CutOffTime`` (absolute, or the cutoff function
  applied to the key's FIRST record, matching the in-core reader);
  conditional readers take the minimum ``target_condition`` match time.
  Keys sort by ``repr`` — the in-core key order — so one row per key on a
  deterministic global row grid.  The scan is cached: it also answers
  ``estimate_rows()`` EXACTLY (distinct keys), so ``plan_host_shard``
  never falls back to a counting pre-pass for event sources.
* **Pass B (fold)** — buffer each owned key's in-window events as
  ``(time_ms, seq, values)`` rows in an :class:`EventFoldState` (the
  reader-side monoid: associative ``merge``, ``to_state``/``from_state``
  riding the utils/sketches codec idiom).  Events outside every feature's
  cutoff window are dropped at fold time — peak memory is the in-window
  event set of OWNED keys, not the record log.
* **Finalize** — per key, sort buffered events by ``(time_ms, seq)``
  (identical to the in-core stable time sort: ``seq`` is the global
  record ordinal, so ties keep encounter order) and hand them to the
  SAME ``FeatureAggregator.extract`` the in-core reader uses.  Output
  chunks stay on the GLOBAL key grid (first/last window chunks may be
  partial, exactly like ``window_gen``) — the determinism the checkpoint
  cursor and cross-host-count resume count on.

``host_range`` ownership is the contiguous key-range slice of the sorted
key universe (the pod substrate's row ranges ARE key ranges here: one row
per key).  :func:`key_owner`/``EventFoldState.shard`` provide the
key-hash partition of the same state algebra (crc32 of ``repr`` — never
``hash()``, which is PYTHONHASHSEED-dependent across pod processes), and
:func:`merge_fold_states` is the host-order merge; the `(time, seq)`
finalize sort makes the merged fold bit-identical under ANY partition.

Joins: :func:`stream_join` / :func:`stream_join_aggregate` turn
``JoinedDataReader`` into a chunked sort-merge over key-sorted spill runs
bounded by the SAME ``TMOG_STREAM_RETAIN_MB`` budget as the streaming
driver's ``_BlockStore`` (workflow/streaming.py).  Row order is
key-sorted (documented divergence from the in-core pandas merge order);
the secondary-aggregation variant is byte-identical to its in-core
``generate_dataset`` (whose ``np.unique`` key order is already sorted).

Fault injection: ``event.window`` fires before each finalized key-window
chunk, ``join.chunk`` before each joined chunk (utils/faults.py).
"""
from __future__ import annotations

import heapq
import itertools
import os
import tempfile
import zlib
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from ..aggregators import (AGGREGATOR_REGISTRY, CutOffTime, Event,
                           FeatureAggregator)
from ..features.feature import Feature
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import ID
from ..utils import faults
from .base import ChunkStream, Reader

__all__ = ["StreamingAggregateReader", "StreamingConditionalReader",
           "EventFoldState", "merge_fold_states", "key_owner",
           "streaming_view", "stream_join", "stream_join_aggregate"]

#: record-chunk size for the scan/fold passes over the SOURCE (decoded
#: records resident at once; independent of the output chunk_rows, which
#: counts KEYS)
_SCAN_CHUNK_ROWS = 8192

#: exception families a corrupt event row raises out of user extract/key/
#: time lambdas — quarantined under the bad-record policy; anything else
#: (assertion, import, ...) is a programming error and propagates
_BAD_RECORD_EXC = (TypeError, ValueError, KeyError, AttributeError,
                   IndexError)


def key_owner(key: Any, process_count: int) -> int:
    """Stable key-hash ownership: crc32 of ``repr(key)``.  Python's
    ``hash()`` is PYTHONHASHSEED-randomized per process, so two pod hosts
    would disagree about ownership; crc32 of the repr bytes is identical
    everywhere."""
    return zlib.crc32(repr(key).encode("utf-8")) % int(process_count)


# ---------------------------------------------------------------------------
# record-chunk iteration over any supported source
# ---------------------------------------------------------------------------

def _source_desc(source) -> str:
    path = getattr(source, "path", None)
    return path if isinstance(path, str) else type(source).__name__


def _iter_record_chunks(source, chunk_rows: int) -> Iterator[List[Any]]:
    """Bounded record chunks from any event source: file readers stream
    (their own quarantine attribution intact), in-memory shapes slice."""
    from .files import CSVReader, JSONLinesReader, ParquetReader

    if isinstance(source, JSONLinesReader):
        def jsonl():
            records, nbytes, line_no = [], 0, 0
            with open(source.path, "rb") as fh:
                for line in fh:
                    line_no += 1
                    s = line.strip()
                    if s:
                        rec = source._parse_line(s, line_no, nbytes)
                        if rec is not None:
                            records.append(rec)
                    nbytes += len(line)
                    if len(records) >= chunk_rows:
                        yield records
                        records = []
                if records:
                    yield records
        return jsonl()

    if isinstance(source, CSVReader):
        def csv():
            import pandas as pd

            kwargs = dict(chunksize=chunk_rows, **source._bad_line_kwargs())
            if not source.has_header:
                kwargs.update(header=None, names=source.column_names)
            with pd.read_csv(source.path, **kwargs) as it:
                for df in it:
                    yield df.to_dict("records")
        return csv()

    if isinstance(source, ParquetReader):
        def parquet():
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(source.path)
            for batch in pf.iter_batches(batch_size=chunk_rows):
                yield batch.to_pandas().to_dict("records")
        return parquet()

    from .aggregates import _records_of
    from .base import DataFrameReader

    if isinstance(source, DataFrameReader):
        records = source.df.to_dict("records")
    else:
        # raw pandas frame / AvroReader-like (.records) / records list —
        # the exact source shapes the in-core readers accept
        records = _records_of(source)

    def slices():
        for i in range(0, len(records), chunk_rows):
            yield records[i:i + chunk_rows]
    return slices()


# ---------------------------------------------------------------------------
# fold state — the reader-side monoid
# ---------------------------------------------------------------------------

class EventFoldState:
    """Mergeable per-key event buffer: ``key -> [(time_ms, seq, values)]``
    with ``values`` aligned to ``feature_names``.

    ``merge`` is associative and — because finalize re-sorts every key's
    rows by ``(time_ms, seq)`` — commutative up to the finalized output,
    so partial folds partitioned ANY way (contiguous key ranges, key-hash
    shards) reassemble bit-identically.  ``to_state``/``from_state``
    follow the utils/sketches codec (plain dict of lists), so fold states
    ride the same transport as estimator states at pod pass boundaries.
    """

    def __init__(self, feature_names: Sequence[str]):
        self.feature_names = list(feature_names)
        self.rows: Dict[Any, List[Tuple[int, int, tuple]]] = {}

    def add(self, key: Any, time_ms: int, seq: int,
            values: Sequence[Any]) -> None:
        self.rows.setdefault(key, []).append((time_ms, seq, tuple(values)))

    def event_count(self) -> int:
        return sum(len(v) for v in self.rows.values())

    def merge(self, other: "EventFoldState") -> "EventFoldState":
        if other.feature_names != self.feature_names:
            raise ValueError("cannot merge fold states over different "
                             f"features: {self.feature_names} vs "
                             f"{other.feature_names}")
        for k, rs in other.rows.items():
            self.rows.setdefault(k, []).extend(rs)
        return self

    def shard(self, process_count: int) -> List["EventFoldState"]:
        """Key-hash partition (crc32 ownership) — each key's rows land in
        exactly one shard; ``merge_fold_states`` reassembles losslessly."""
        parts = [EventFoldState(self.feature_names)
                 for _ in range(process_count)]
        for k, rs in self.rows.items():
            parts[key_owner(k, process_count)].rows[k] = list(rs)
        return parts

    def to_state(self) -> Dict[str, Any]:
        keys = list(self.rows.keys())
        return {
            "features": list(self.feature_names),
            "keys": keys,
            "rows": [[[int(t), int(s), list(v)] for t, s, v in self.rows[k]]
                     for k in keys],
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "EventFoldState":
        out = cls(state["features"])
        for k, rs in zip(state["keys"], state["rows"]):
            out.rows[k] = [(int(t), int(s), tuple(v)) for t, s, v in rs]
        return out


def merge_fold_states(states: Sequence[EventFoldState]) -> EventFoldState:
    """Host-order merge of partial folds (the pod pass-boundary shape)."""
    if not states:
        raise ValueError("no fold states to merge")
    acc = EventFoldState(states[0].feature_names)
    for st in states:
        acc.merge(st)
    return acc


class _KeyIndex:
    """Pass-A product: sorted key universe, per-key cutoffs, the seqs of
    records quarantined during the scan (pass B skips them identically)."""

    def __init__(self, keys: List[Any], cutoffs: Dict[Any, Optional[int]],
                 n_records: int, bad_seqs: frozenset):
        self.keys = keys
        self.pos = {k: i for i, k in enumerate(keys)}
        self.cutoffs = cutoffs
        self.n_records = n_records
        self.bad_seqs = bad_seqs


# ---------------------------------------------------------------------------
# streamed aggregate / conditional readers
# ---------------------------------------------------------------------------

class StreamingAggregateReader(Reader):
    """Out-of-core ``AggregateDataReader``: same per-key monoid aggregation
    and cutoff-window semantics, as a two-pass streamed fold (see module
    docstring).  ``source`` is any chunkable event source: CSV / JSONL /
    Parquet / Avro readers, a pandas DataFrame, or a records list."""

    def __init__(self, source, key_fn: Callable[[dict], Any],
                 time_fn: Callable[[dict], int],
                 cutoff: Optional[CutOffTime] = None,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None,
                 scan_chunk_rows: int = _SCAN_CHUNK_ROWS):
        self.source = source
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.cutoff = cutoff or CutOffTime.no_cutoff()
        self.predictor_window_ms = predictor_window_ms
        self.response_window_ms = response_window_ms
        self.scan_chunk_rows = int(scan_chunk_rows)
        self._index_cache: Optional[_KeyIndex] = None

    # -- source plumbing --------------------------------------------------

    def _source_desc(self) -> str:
        return _source_desc(self.source)

    def _arm_source(self) -> None:
        # quarantine attribution flows through to the underlying parse
        # (JSONL line numbers, CSV bad-line ordinals); sharing ONE config
        # means one sink, and the sink's (source, location) dedupe makes a
        # corrupt row quarantine once across every scan/fold pass
        if (self.resilience is not None and isinstance(self.source, Reader)
                and self.source.resilience is None):
            self.source.resilience = self.resilience

    def _record_chunks(self) -> Iterator[List[Any]]:
        self._arm_source()
        return _iter_record_chunks(self.source, self.scan_chunk_rows)

    def _guard(self, fn, record, seq: int, what: str):
        """(ok, value) for one user-callable over one record; corrupt rows
        quarantine (deterministic ``event-record#seq`` location) under the
        bad-record policy and propagate raw without one — the in-core
        fail-fast behavior, byte-identical."""
        try:
            return True, fn(record)
        except _BAD_RECORD_EXC as exc:
            cfg = self.resilience
            if cfg is not None and cfg.quarantines:
                cfg.handle_bad_record(
                    self._source_desc(), f"event-record#{seq}",
                    f"{what} failed: {exc!r}", record=record)
                return False, None
            raise

    # -- pass A: key scan -------------------------------------------------

    def _index(self) -> _KeyIndex:
        if self._index_cache is None:
            self._index_cache = self._build_index()
        return self._index_cache

    def _build_index(self) -> _KeyIndex:
        from ..obs.trace import begin_span, end_span

        cond = getattr(self, "target_condition", None)
        drop = getattr(self, "drop_if_no_target", False)
        kind = self.cutoff.kind
        sp = begin_span("events.scan", cat="ingest",
                        reader=type(self).__name__,
                        source=self._source_desc())
        seen: set = set()
        bad: set = set()
        fn_cut: Dict[Any, Optional[int]] = {}
        match_min: Dict[Any, int] = {}
        seq = 0
        for records in self._record_chunks():
            for r in records:
                s = seq
                seq += 1
                ok, k = self._guard(self.key_fn, r, s, "key_fn")
                if not ok:
                    bad.add(s)
                    continue
                ok, t = self._guard(self.time_fn, r, s, "time_fn")
                if not ok:
                    bad.add(s)
                    continue
                if cond is not None:
                    ok, m = self._guard(cond, r, s, "target_condition")
                    if not ok:
                        bad.add(s)
                        continue
                    if m and (k not in match_min or t < match_min[k]):
                        match_min[k] = int(t)
                elif kind == "function" and k not in seen:
                    # in-core parity: cutoff fn applies to the key's FIRST
                    # record in encounter order
                    ok, c = self._guard(self.cutoff.fn, r, s, "cutoff")
                    if not ok:
                        bad.add(s)
                        continue
                    fn_cut[k] = c
                seen.add(k)
        keys = sorted(seen, key=repr)
        if cond is not None:
            if drop:
                keys = [k for k in keys if k in match_min]
            cutoffs = {k: match_min.get(k) for k in keys}
        elif kind == "unix":
            cutoffs = {k: self.cutoff.time_ms for k in keys}
        elif kind == "function":
            cutoffs = {k: fn_cut.get(k) for k in keys}
        else:
            cutoffs = {k: None for k in keys}
        end_span(sp, keys=len(keys), records=seq, bad_records=len(bad))
        return _KeyIndex(keys, cutoffs, seq, frozenset(bad))

    # -- estimates (exact: one row per key) -------------------------------

    def estimate_rows(self) -> Optional[int]:
        return len(self._index().keys)

    def estimate_rows_exact(self) -> bool:
        return True

    # -- pass B: fold + finalize ------------------------------------------

    def _aggregators(self, raw_features) -> Dict[str, FeatureAggregator]:
        aggs = {}
        for f in raw_features:
            gen = f.origin_stage
            agg = (AGGREGATOR_REGISTRY[gen.aggregator]
                   if gen.aggregator else None)
            window = gen.aggregate_window_ms
            aggs[f.name] = FeatureAggregator(
                f.ftype, f.is_response, aggregator=agg,
                predictor_window_ms=window or self.predictor_window_ms,
                response_window_ms=window or self.response_window_ms)
        return aggs

    def _feature_windows(self, raw_features) -> List[Tuple[bool, Optional[int]]]:
        out = []
        for f in raw_features:
            gen = f.origin_stage
            window = gen.aggregate_window_ms
            out.append((f.is_response,
                        window or (self.response_window_ms if f.is_response
                                   else self.predictor_window_ms)))
        return out

    @staticmethod
    def _in_any_window(t: int, cutoff: Optional[int],
                       windows: List[Tuple[bool, Optional[int]]]) -> bool:
        """Union of the features' cutoff windows — the fold-time prefilter.
        ``FeatureAggregator.extract`` re-applies each feature's own window
        at finalize, so dropping events outside EVERY window changes
        nothing but peak memory."""
        if cutoff is None:
            return True
        for is_response, w in windows:
            if is_response:
                if t >= cutoff and (w is None or t < cutoff + w):
                    return True
            elif t < cutoff and (w is None or t >= cutoff - w):
                return True
        return False

    def _fold(self, raw_features, index: _KeyIndex,
              start: int, stop: int) -> EventFoldState:
        from ..obs.trace import begin_span, end_span

        gens = [f.origin_stage for f in raw_features]
        extract_fns = [g.extract_fn or
                       (lambda r, _n=g.name: r.get(_n)) for g in gens]
        windows = self._feature_windows(raw_features)
        state = EventFoldState([f.name for f in raw_features])
        sp = begin_span("events.fold", cat="ingest",
                        reader=type(self).__name__,
                        keys=stop - start)
        seq = 0
        for records in self._record_chunks():
            for r in records:
                s = seq
                seq += 1
                if s in index.bad_seqs:
                    continue
                ok, k = self._guard(self.key_fn, r, s, "key_fn")
                if not ok:
                    continue
                p = index.pos.get(k)
                if p is None or not (start <= p < stop):
                    continue
                ok, t = self._guard(self.time_fn, r, s, "time_fn")
                if not ok:
                    continue
                # extract BEFORE the window prefilter: the in-core reader
                # extracted every record, so a corrupt value fails fast
                # (or quarantines) even when its event lies outside every
                # window — only the BUFFERING is window-gated
                try:
                    values = [fn(r) for fn in extract_fns]
                except _BAD_RECORD_EXC as exc:
                    cfg = self.resilience
                    if cfg is not None and cfg.quarantines:
                        cfg.handle_bad_record(
                            self._source_desc(), f"event-record#{s}",
                            f"extract failed: {exc!r}", record=r)
                        continue
                    raise
                if not self._in_any_window(t, index.cutoffs.get(k), windows):
                    continue
                state.add(k, int(t), s, values)
        end_span(sp, buffered_events=state.event_count())
        return state

    def _finalize_block(self, raw_features, aggs, index: _KeyIndex,
                        state: EventFoldState, lo: int, hi: int
                        ) -> ColumnarDataset:
        keys = index.keys[lo:hi]
        cols: Dict[str, List[Any]] = {f.name: [] for f in raw_features}
        for k in keys:
            cutoff = index.cutoffs.get(k)
            rows = sorted(state.rows.get(k, ()),
                          key=lambda r: (r[0], r[1]))
            for j, f in enumerate(raw_features):
                events = [Event(t, v[j]) for t, _s, v in rows]
                cols[f.name].append(aggs[f.name].extract(events, cutoff))
        data = ColumnarDataset()
        for f in raw_features:
            data.set(f.name, FeatureColumn.from_values(f.ftype, cols[f.name]))
        data.set("key", FeatureColumn.from_values(
            ID, [str(k) for k in keys]))
        return data

    # -- Reader protocol --------------------------------------------------

    def generate_dataset(self, raw_features: Sequence[Feature]
                         ) -> ColumnarDataset:
        raw_features = list(raw_features)
        index = self._index()
        aggs = self._aggregators(raw_features)
        state = self._fold(raw_features, index, 0, len(index.keys))
        return self._finalize_block(raw_features, aggs, index, state,
                                    0, len(index.keys))

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range: Optional[tuple] = None) -> ChunkStream:
        """One streamed fold per pass: scan (cached) -> fold the owned key
        range -> finalize chunk blocks on the GLOBAL key grid.  With
        ``host_range=(start, stop)`` only keys in that slice of the sorted
        key universe are ever buffered — the pod's host-sharded ingest."""
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        raw_features = list(raw_features)
        if host_range is not None:
            start, stop = int(host_range[0]), int(host_range[1])
            if start < 0 or stop < start:
                raise ValueError(f"bad host_range ({start}, {stop})")
        else:
            start, stop = 0, None

        def gen():
            index = self._index()
            n = len(index.keys)
            lo_w, hi_w = start, n if stop is None else min(stop, n)
            if hi_w <= lo_w:
                return
            aggs = self._aggregators(raw_features)
            state = self._fold(raw_features, index, lo_w, hi_w)
            out_idx = 0
            for c0 in range(0, n, chunk_rows):
                if c0 >= hi_w:
                    break
                c1 = min(c0 + chunk_rows, n)
                lo, hi = max(c0, lo_w), min(c1, hi_w)
                if hi <= lo:
                    continue
                faults.fire("event.window", index=out_idx)
                out_idx += 1
                yield self._finalize_block(raw_features, aggs, index,
                                           state, lo, hi)

        return ChunkStream(gen())


class StreamingConditionalReader(StreamingAggregateReader):
    """Out-of-core ``ConditionalDataReader``: per-key cutoff = time of the
    first (minimum-time) record matching ``target_condition``; keys with
    no match drop when ``drop_if_no_target``."""

    def __init__(self, source, key_fn, time_fn,
                 target_condition: Callable[[dict], bool],
                 drop_if_no_target: bool = True,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None,
                 scan_chunk_rows: int = _SCAN_CHUNK_ROWS):
        super().__init__(source, key_fn, time_fn,
                         cutoff=CutOffTime.no_cutoff(),
                         predictor_window_ms=predictor_window_ms,
                         response_window_ms=response_window_ms,
                         scan_chunk_rows=scan_chunk_rows)
        self.target_condition = target_condition
        self.drop_if_no_target = drop_if_no_target


def streaming_view(reader) -> StreamingAggregateReader:
    """The streamed twin of an in-core aggregate/conditional reader — the
    ONE aggregation code path (`in-core generate_dataset` delegates here,
    asserted byte-identical by tests/test_events_streaming.py)."""
    from .aggregates import AggregateDataReader, ConditionalDataReader

    if isinstance(reader, ConditionalDataReader):
        view = StreamingConditionalReader(
            reader.source, reader.key_fn, reader.time_fn,
            target_condition=reader.target_condition,
            drop_if_no_target=reader.drop_if_no_target,
            predictor_window_ms=reader.predictor_window_ms,
            response_window_ms=reader.response_window_ms)
    elif isinstance(reader, AggregateDataReader):
        view = StreamingAggregateReader(
            reader.source, reader.key_fn, reader.time_fn,
            cutoff=reader.cutoff,
            predictor_window_ms=reader.predictor_window_ms,
            response_window_ms=reader.response_window_ms)
    else:
        raise TypeError(f"not an aggregate reader: {type(reader).__name__}")
    view.resilience = reader.resilience
    return view


# ---------------------------------------------------------------------------
# chunked sort-merge joins over key-sorted spill runs
# ---------------------------------------------------------------------------

def _join_budget_bytes() -> int:
    """The join spiller shares the streaming driver's retention budget
    (``TMOG_STREAM_RETAIN_MB``, workflow/streaming.py) — one knob bounds
    every out-of-core buffer."""
    from ..workflow.streaming import _retain_budget_bytes

    return _retain_budget_bytes(None)


def _row_cost(key: str, values: Sequence[Any]) -> int:
    # cheap deterministic approximation (exact accounting would getsizeof
    # every nested value per row); the budget is a bound knob, not a meter
    return 96 + len(key) + 48 * (2 + len(values))


class _SpillSorter:
    """External merge sort of ``(key, seq, values)`` rows.

    Rows accumulate in RAM until the byte budget, then sort (stable: the
    ``(key, seq)`` composite keeps each key's original row order) and
    spill as sequential ``np.save`` blocks in one temp file — the k-way
    heap merge holds one block per run, never a whole run (the
    ``_BlockStore`` discipline, workflow/streaming.py)."""

    BLOCK_ROWS = 2048

    def __init__(self, budget_bytes: int):
        self.budget = max(int(budget_bytes), 1 << 16)
        self.buf: List[Tuple[str, int, list]] = []
        self.buf_bytes = 0
        self.runs: List[Tuple[str, int]] = []   # (path, n_blocks)
        self.spilled_rows = 0

    def add(self, key: str, seq: int, values: list) -> None:
        self.buf.append((key, seq, values))
        self.buf_bytes += _row_cost(key, values)
        if self.buf_bytes >= self.budget:
            self._spill()

    def _spill(self) -> None:
        if not self.buf:
            return
        self.buf.sort(key=lambda r: (r[0], r[1]))
        fd, path = tempfile.mkstemp(prefix="tmog_join_run_")
        n_blocks = 0
        ok = False
        try:
            with os.fdopen(fd, "wb") as fh:
                for i in range(0, len(self.buf), self.BLOCK_ROWS):
                    block = self.buf[i:i + self.BLOCK_ROWS]
                    arr = np.empty(len(block), dtype=object)
                    arr[:] = block
                    np.save(fh, arr, allow_pickle=True)
                    n_blocks += 1
            ok = True
        finally:
            if not ok:
                os.unlink(path)
        self.spilled_rows += len(self.buf)
        self.runs.append((path, n_blocks))
        self.buf = []
        self.buf_bytes = 0

    @staticmethod
    def _run_iter(path: str, n_blocks: int):
        with open(path, "rb") as fh:
            for _ in range(n_blocks):
                for row in np.load(fh, allow_pickle=True):
                    yield tuple(row)

    def sorted_rows(self) -> Iterator[Tuple[str, int, list]]:
        if not self.runs:
            self.buf.sort(key=lambda r: (r[0], r[1]))
            buf, self.buf = self.buf, []
            yield from buf
            return
        self._spill()   # flush the in-RAM remainder as the last run
        runs, self.runs = self.runs, []
        try:
            yield from heapq.merge(
                *(self._run_iter(p, nb) for p, nb in runs),
                key=lambda r: (r[0], r[1]))
        finally:
            for p, _nb in runs:
                try:
                    os.unlink(p)
                except OSError:
                    pass


def _side_chunks(reader, features, key_cols: Sequence[str], chunk_rows: int):
    """One side's chunks with every key column present — the streaming
    twin of ``JoinedDataReader._with_key``: peek at the first chunk, and
    only when a key column is genuinely absent (not in the features AND
    not auto-emitted, like an aggregate reader's ``key``) re-open with
    synthesized ID key features."""
    stream = iter(reader.iter_chunks(list(features), chunk_rows))
    first = next(stream, None)
    if first is None:
        return iter(())
    missing = [k for k in key_cols if k not in first]
    if not missing:
        return itertools.chain([first], stream)
    from ..features.builder import FeatureBuilder

    key_feats = [FeatureBuilder.ID(k).as_predictor() for k in missing]
    return iter(reader.iter_chunks(list(features) + key_feats, chunk_rows))


def _side_sorted(reader, features, key_cols: Sequence[str],
                 chunk_rows: int, budget: int):
    """One join side as key-sorted ``(key, seq, values)`` rows; composite
    keys join on \\x1f exactly like the in-core ``_join_indices``."""
    sorter = _SpillSorter(budget)
    seq = 0
    for ds in _side_chunks(reader, features, key_cols, chunk_rows):
        key_parts = [[str(v) for v in ds[k].to_list()] for k in key_cols]
        col_lists = [ds[f.name].to_list() for f in features]
        for i in range(len(ds)):
            key = "\x1f".join(p[i] for p in key_parts)
            sorter.add(key, seq, [c[i] for c in col_lists])
            seq += 1
    return sorter.sorted_rows()


def _grouped(rows) -> Iterator[Tuple[str, List[Tuple[str, int, list]]]]:
    for k, rs in itertools.groupby(rows, key=lambda r: r[0]):
        yield k, list(rs)


def _joined_groups(jr, lcols, rcols, chunk_rows: int
                   ) -> Iterator[Tuple[str, List[Tuple[Optional[list],
                                                       Optional[list]]]]]:
    """Sort-merge the two sides: per key (ascending), the fan-out rows as
    ``(left_values | None, right_values | None)`` — within a key, left
    rows in original order, each paired with right rows in original order
    (the pandas-merge fan-out order the in-core join produces)."""
    budget = _join_budget_bytes() // 4    # two sides + merge-block headroom
    lg = _grouped(_side_sorted(jr.left, lcols, jr.left_key,
                               chunk_rows, budget))
    rg = _grouped(_side_sorted(jr.right, rcols, jr.right_key,
                               chunk_rows, budget))
    want_left_only = jr.join_type in ("left", "outer")
    want_right_only = jr.join_type == "outer"
    lcur = next(lg, None)
    rcur = next(rg, None)
    while lcur is not None or rcur is not None:
        if rcur is None or (lcur is not None and lcur[0] < rcur[0]):
            if want_left_only:
                yield lcur[0], [(row[2], None) for row in lcur[1]]
            lcur = next(lg, None)
        elif lcur is None or rcur[0] < lcur[0]:
            if want_right_only:
                yield rcur[0], [(None, row[2]) for row in rcur[1]]
            rcur = next(rg, None)
        else:
            rrows = [row[2] for row in rcur[1]]
            yield lcur[0], [(lrow[2], rvals)
                            for lrow in lcur[1] for rvals in rrows]
            lcur = next(lg, None)
            rcur = next(rg, None)


def _split_join_columns(jr, raw_features):
    lnames = {f.name for f in jr.left_features}
    rnames = {f.name for f in jr.right_features}
    lcols = [f for f in raw_features if f.name in lnames]
    rcols = [f for f in raw_features if f.name not in lnames]
    for f in rcols:
        if f.name not in rnames:
            raise KeyError(f"feature {f.name!r} not produced by either "
                           "side of the join")
    return lcols, rcols


def stream_join(jr, raw_features, chunk_rows: int,
                host_range: Optional[tuple] = None) -> ChunkStream:
    """``JoinedDataReader.stream()``: the chunked sort-merge join.  Row
    order is KEY-SORTED, stable within a key (documented divergence from
    the in-core pandas hash-merge order); every other value, including
    per-storage missing-side empties, matches ``generate_dataset``."""
    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    raw_features = list(raw_features)
    lcols, rcols = _split_join_columns(jr, raw_features)

    def gen():
        buf: Dict[str, list] = {f.name: [] for f in raw_features}
        keys: List[str] = []
        out_idx = 0

        def flush():
            nonlocal out_idx
            faults.fire("join.chunk", index=out_idx)
            out_idx += 1
            ds = ColumnarDataset()
            for f in raw_features:
                ds.set(f.name,
                       FeatureColumn.from_values(f.ftype, buf[f.name]))
                buf[f.name] = []
            ds.set("key", FeatureColumn.from_values(ID, list(keys)))
            keys.clear()
            return ds

        for key, pairs in _joined_groups(jr, lcols, rcols, chunk_rows):
            for lvals, rvals in pairs:
                for i, f in enumerate(lcols):
                    buf[f.name].append(None if lvals is None else lvals[i])
                for i, f in enumerate(rcols):
                    buf[f.name].append(None if rvals is None else rvals[i])
                keys.append(key)
                if len(keys) >= chunk_rows:
                    yield flush()
        if keys:
            yield flush()

    from .base import window_gen

    g = gen() if host_range is None else window_gen(gen(), host_range)
    return ChunkStream(g)


def stream_join_aggregate(jr, raw_features, chunk_rows: int,
                          host_range: Optional[tuple] = None) -> ChunkStream:
    """``JoinedAggregateDataReader.stream()``: sort-merge join + secondary
    per-key aggregation, one output row per key in sorted-key order —
    byte-identical to the in-core ``generate_dataset`` (its ``np.unique``
    key order is the same lexicographic sort)."""
    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    from ..aggregators import default_aggregator

    tf = jr.time_filter
    feats = list(raw_features)
    names = {f.name for f in feats}
    extra = [f for f in jr.left_features + jr.right_features
             if f.name in (tf.condition, tf.primary) and f.name not in names]
    all_feats = feats + extra
    lcols, rcols = _split_join_columns(jr, all_feats)
    lnames = {f.name for f in jr.left_features}
    out_feats = [f for f in feats
                 if not (f.name == tf.condition and not tf.keep_condition)
                 and not (f.name == tf.primary and not tf.keep_primary)]

    aggs = {}
    for f in out_feats:
        if f.name in lnames:
            continue
        agg = getattr(f.origin_stage, "aggregator", None)
        if isinstance(agg, str):
            agg = AGGREGATOR_REGISTRY[agg]
        aggs[f.name] = agg or default_aggregator(f.ftype)

    def gen():
        buf: Dict[str, list] = {f.name: [] for f in out_feats}
        keys: List[str] = []
        out_idx = 0

        def flush():
            nonlocal out_idx
            faults.fire("join.chunk", index=out_idx)
            out_idx += 1
            ds = ColumnarDataset()
            for f in out_feats:
                ds.set(f.name,
                       FeatureColumn.from_values(f.ftype, buf[f.name]))
                buf[f.name] = []
            ds.set("key", FeatureColumn.from_values(ID, list(keys)))
            keys.clear()
            return ds

        for key, pairs in _joined_groups(jr, lcols, rcols, chunk_rows):
            rows = []      # per fan-out row: {name: value}
            for lvals, rvals in pairs:
                row = {}
                for i, f in enumerate(lcols):
                    row[f.name] = None if lvals is None else lvals[i]
                for i, f in enumerate(rcols):
                    row[f.name] = None if rvals is None else rvals[i]
                rows.append(row)
            # entity primary time = max per key (in-core parity: missing
            # primaries are -inf, so an all-missing key admits nothing)
            prim = [r.get(tf.primary) for r in rows]
            prim_max = max((float(p) for p in prim if p is not None),
                           default=float("-inf"))
            in_window = []
            for r in rows:
                c = r.get(tf.condition)
                in_window.append(c is not None and float(c) <= prim_max
                                 and float(c) > prim_max - tf.window_ms)
            for f in out_feats:
                if f.name in lnames:
                    val = next((r[f.name] for r in rows
                                if r[f.name] is not None), None)
                else:
                    vals = [r[f.name] for r, ok in zip(rows, in_window)
                            if ok and r[f.name] is not None]
                    val = aggs[f.name].reduce(vals) if vals else None
                buf[f.name].append(val)
            keys.append(key)
            if len(keys) >= chunk_rows:
                yield flush()
        if keys:
            yield flush()

    from .base import window_gen

    g = gen() if host_range is None else window_gen(gen(), host_range)
    return ChunkStream(g)
