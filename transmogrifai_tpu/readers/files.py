"""File readers: CSV (with/without header, typed or auto), Parquet, JSON lines.

Reference: ``CSVReaders``/``CSVAutoReaders`` (readers/CSVAutoReaders.scala:57),
``ParquetProductReader``, ``AvroReaders``; the reference types CSV columns via
an Avro schema — here an explicit {name: FeatureType} schema or pandas-based
inference (FeatureBuilder.infer_schema_from_pandas) plays that role.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple, Type

from ..features.feature import Feature
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import FeatureType
from .base import ChunkStream, DataFrameReader, Reader, window_gen


class _CountedRowsCache:
    """Exact-row-count memo for file readers whose ``estimate_rows`` is a
    heuristic (CSV/JSONL): host sharding's counting pre-pass is a full
    chunk iteration, and before this cache it re-ran on EVERY pod train
    over the same file — every resume, every repeated fit.  The count is
    keyed by (path, mtime_ns, size), so any rewrite of the file (even
    same-size, via mtime) invalidates it; a vanished file just misses.

    The memo lives on the READER INSTANCE (not a process global): two
    readers over the same path with different resilience configs can
    legitimately yield different counts (quarantined rows are absent),
    and an instance keeps one config for its lifetime.
    """

    def __init__(self):
        self._key: Optional[Tuple[str, int, int]] = None
        self._rows: Optional[int] = None

    @staticmethod
    def key_of(path: str) -> Optional[Tuple[str, int, int]]:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (path, int(st.st_mtime_ns), int(st.st_size))

    def get(self, path: str) -> Optional[int]:
        key = self.key_of(path)
        if key is None or key != self._key:
            return None
        return self._rows

    def put(self, path: str, rows: int) -> None:
        key = self.key_of(path)
        if key is None:
            return
        self._key = key
        self._rows = int(rows)


class _CountCacheMixin:
    """Readers mix this in to expose the counted-rows memo to
    ``distributed.hostshard.count_rows`` (duck-typed: the pre-pass calls
    these when present)."""

    @property
    def _count_cache(self) -> _CountedRowsCache:
        cache = getattr(self, "_count_cache_obj", None)
        if cache is None:
            cache = _CountedRowsCache()
            self._count_cache_obj = cache
        return cache

    def cached_row_count(self) -> Optional[int]:
        return self._count_cache.get(self.path)

    def cache_row_count(self, rows: int) -> None:
        self._count_cache.put(self.path, rows)


def _count_lines(path: str) -> int:
    """Newline count by raw 1MB blocks — the cheap line-count estimate
    (no parse, no decode).  A final line without a trailing newline still
    counts."""
    n = 0
    last = b"\n"
    with open(path, "rb") as fh:
        while True:
            block = fh.read(1 << 20)
            if not block:
                break
            n += block.count(b"\n")
            last = block[-1:]
    if last != b"\n":
        n += 1
    return n

__all__ = ["CSVReader", "CSVAutoReader", "ParquetReader", "JSONLinesReader",
           "DataReaders"]


def _text_dtype_overrides(raw_features: Sequence[Feature]) -> dict:
    """Pin text-typed raw columns to ``str`` for chunked CSV parses.

    Monolithic reads infer each column's dtype over the WHOLE file; a
    per-chunk parse would re-infer per chunk, so a text feature backed by
    numeric-looking cells could stringify differently chunk to chunk
    ("345" vs "345.0").  Parsing those columns as str makes chunked values
    deterministic (see docs/performance.md for the one residual caveat:
    a text feature over a numeric column WITH missing values stringifies
    as "1" chunked vs pandas' float repr "1.0" monolithic).
    """
    out = {}
    for f in raw_features:
        gen = f.origin_stage
        if (getattr(gen, "extract_fn", None) is None
                and f.ftype.storage == "text"):
            out[f.name] = str
    return out


class CSVReader(_CountCacheMixin, Reader):
    """CSV with explicit column names (header optional)."""

    def __init__(self, path: str, column_names: Optional[List[str]] = None,
                 has_header: bool = True, key_col: Optional[str] = None):
        self.path = path
        self.column_names = column_names
        self.has_header = has_header
        self.key_col = key_col

    def _bad_line_kwargs(self) -> dict:
        """Under the quarantine policy, malformed CSV lines route to the
        sidecar via pandas' ``on_bad_lines`` callback (python engine) with
        a deterministic per-file ordinal as the location; the default
        policy keeps pandas' stock ParserError fail-fast (C engine)."""
        cfg = self.resilience
        if cfg is None or not cfg.quarantines:
            return {}
        counter = {"n": 0}
        source = self.path

        def on_bad(fields):
            loc = f"bad-line#{counter['n']}"
            counter["n"] += 1
            cfg.handle_bad_record(source, loc,
                                  f"malformed CSV row ({len(fields)} fields)",
                                  record=list(map(str, fields)))
            return None  # drop the row

        return {"on_bad_lines": on_bad, "engine": "python"}

    def _load(self):
        import pandas as pd

        kwargs = self._bad_line_kwargs()
        if self.has_header:
            return pd.read_csv(self.path, **kwargs)
        return pd.read_csv(self.path, header=None, names=self.column_names,
                           **kwargs)

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        return DataFrameReader(self._load(), self.key_col).generate_dataset(raw_features)

    def estimate_rows(self) -> Optional[int]:
        """Line count minus the header — an ESTIMATE (quoted embedded
        newlines over-count; quarantined bad lines drop rows), so
        ``estimate_rows_exact`` stays False and host sharding runs its
        counting pre-pass instead of trusting this."""
        try:
            n = _count_lines(self.path)
        except OSError:
            return None
        return max(n - (1 if self.has_header else 0), 0)

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range=None) -> ChunkStream:
        """Streaming parse via pandas' chunked reader — the full CSV is
        never resident; bytes_read tracks the underlying file position.
        ``host_range`` windows the stream (rows past the window's stop
        are never parsed — the parse loop breaks early)."""
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        import pandas as pd

        dtype = _text_dtype_overrides(raw_features) or None
        fh = open(self.path, "rb")
        pos = {"bytes": 0}

        def gen():
            try:
                kwargs = dict(chunksize=chunk_rows, dtype=dtype,
                              **self._bad_line_kwargs())
                if not self.has_header:
                    kwargs.update(header=None, names=self.column_names)
                with pd.read_csv(fh, **kwargs) as it:
                    for df in it:
                        pos["bytes"] = fh.tell()
                        yield DataFrameReader(
                            df, self.key_col).generate_dataset(raw_features)
            finally:
                fh.close()

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g, bytes_fn=lambda: pos["bytes"])


class CSVAutoReader(CSVReader):
    """Schema-inferring CSV reader (CSVAutoReaders.scala:57)."""


class ParquetReader(Reader):
    def __init__(self, path: str, key_col: Optional[str] = None):
        self.path = path
        self.key_col = key_col

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        import pandas as pd

        df = pd.read_parquet(self.path)
        return DataFrameReader(df, self.key_col).generate_dataset(raw_features)

    def estimate_rows(self) -> Optional[int]:
        """Parquet footer metadata row count — exact without decoding."""
        try:
            import pyarrow.parquet as pq

            return int(pq.ParquetFile(self.path).metadata.num_rows)
        except Exception:
            return None

    def estimate_rows_exact(self) -> bool:
        return self.estimate_rows() is not None

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range=None) -> ChunkStream:
        """Arrow record-batch streaming (row groups decode incrementally);
        bytes_read counts decoded batch bytes.  Falls back to the
        slice-after-load base path when pyarrow is unavailable."""
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        try:
            import pyarrow.parquet as pq
        except ImportError:  # pragma: no cover - pyarrow is baked in
            return super().iter_chunks(raw_features, chunk_rows,
                                       host_range=host_range)
        pos = {"bytes": 0}

        def gen():
            pf = pq.ParquetFile(self.path)
            for batch in pf.iter_batches(batch_size=chunk_rows):
                pos["bytes"] += batch.nbytes
                yield DataFrameReader(
                    batch.to_pandas(),
                    self.key_col).generate_dataset(raw_features)

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g, bytes_fn=lambda: pos["bytes"])


class JSONLinesReader(_CountCacheMixin, Reader):
    def __init__(self, path: str, key_col: Optional[str] = None):
        self.path = path
        self.key_col = key_col

    def _parse_line(self, raw: bytes, line_no: int, offset: int):
        """One JSONL record, or None when the bad line was quarantined.
        Under the default ``fail`` policy a bad line raises a
        ``BadRecordError`` naming the line number and byte offset."""
        import json

        try:
            return json.loads(raw)
        except (ValueError, UnicodeDecodeError) as exc:
            cfg = self.resilience
            reason = f"invalid JSON: {exc}"
            location = f"line {line_no} (byte {offset})"
            if cfg is not None and cfg.quarantines:
                cfg.handle_bad_record(self.path, location, reason,
                                      record=raw.decode("utf-8", "replace"))
                return None
            from .resilience import BadRecordError

            raise BadRecordError(self.path, location, reason) from exc

    def generate_dataset(self, raw_features: Sequence[Feature]) -> ColumnarDataset:
        records = []
        offset = 0
        with open(self.path, "rb") as fh:
            for line_no, line in enumerate(fh, start=1):
                s = line.strip()
                if s:
                    rec = self._parse_line(s, line_no, offset)
                    if rec is not None:
                        records.append(rec)
                offset += len(line)
        from .base import RecordsReader

        return RecordsReader(records).generate_dataset(raw_features)

    def estimate_rows(self) -> Optional[int]:
        """Line count — an ESTIMATE (blank lines and quarantined bad
        lines both shrink the real yield), never trusted as exact."""
        try:
            return _count_lines(self.path)
        except OSError:
            return None

    def iter_chunks(self, raw_features: Sequence[Feature],
                    chunk_rows: int,
                    host_range=None) -> ChunkStream:
        """Line-streaming parse: at most ``chunk_rows`` decoded records are
        ever resident; bytes_read tracks raw line bytes consumed."""
        if chunk_rows <= 0:
            raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
        from .base import RecordsReader

        pos = {"bytes": 0}

        def gen():
            records, nbytes, line_no = [], 0, 0
            with open(self.path, "rb") as fh:
                for line in fh:
                    line_no += 1
                    s = line.strip()
                    if s:
                        rec = self._parse_line(s, line_no, nbytes)
                        if rec is not None:
                            records.append(rec)
                    nbytes += len(line)
                    if len(records) >= chunk_rows:
                        pos["bytes"] = nbytes
                        yield RecordsReader(records).generate_dataset(
                            raw_features)
                        records = []
                if records:
                    pos["bytes"] = nbytes
                    yield RecordsReader(records).generate_dataset(
                        raw_features)

        g = gen() if host_range is None else window_gen(gen(), host_range)
        return ChunkStream(g, bytes_fn=lambda: pos["bytes"])


class DataReaders:
    """Factory catalogue (DataReaders.scala:44-270)."""

    class Aggregate:
        @staticmethod
        def records(source, key_fn, time_fn, cutoff=None,
                    predictor_window_ms=None, response_window_ms=None):
            from .aggregates import AggregateDataReader

            return AggregateDataReader(source, key_fn, time_fn, cutoff,
                                       predictor_window_ms,
                                       response_window_ms)

        @staticmethod
        def avro(path, key_fn, time_fn, cutoff=None,
                 predictor_window_ms=None, response_window_ms=None):
            """Aggregate reader over Avro records (DataReaders.Aggregate.avro,
            DataReaders.scala:108-130).  The file decodes lazily at the
            first dataset generation, not at factory time."""
            from .aggregates import AggregateDataReader
            from .avro import AvroReader

            return AggregateDataReader(AvroReader(path), key_fn, time_fn,
                                       cutoff, predictor_window_ms,
                                       response_window_ms)

    class Conditional:
        @staticmethod
        def records(source, key_fn, time_fn, target_condition,
                    drop_if_no_target=True, predictor_window_ms=None,
                    response_window_ms=None):
            from .aggregates import ConditionalDataReader

            return ConditionalDataReader(source, key_fn, time_fn,
                                         target_condition,
                                         drop_if_no_target,
                                         predictor_window_ms,
                                         response_window_ms)

        @staticmethod
        def avro(path, key_fn, time_fn, target_condition,
                 drop_if_no_target=True, predictor_window_ms=None,
                 response_window_ms=None):
            """Conditional reader over Avro records
            (DataReaders.Conditional.avro, DataReaders.scala:214-248);
            decodes lazily at the first dataset generation."""
            from .aggregates import ConditionalDataReader
            from .avro import AvroReader

            return ConditionalDataReader(AvroReader(path), key_fn, time_fn,
                                         target_condition, drop_if_no_target,
                                         predictor_window_ms,
                                         response_window_ms)

    @staticmethod
    def dataframe(df, key_col: Optional[str] = None):
        """Wrap an in-memory pandas DataFrame (setInputDataset analogue,
        OpWorkflowCore.scala:147)."""
        from .base import DataFrameReader

        return DataFrameReader(df, key_col)

    class Simple:
        @staticmethod
        def csv(path: str, column_names: Optional[List[str]] = None,
                has_header: bool = True, key_col: Optional[str] = None) -> CSVReader:
            return CSVReader(path, column_names, has_header, key_col)

        @staticmethod
        def parquet(path: str, key_col: Optional[str] = None) -> ParquetReader:
            return ParquetReader(path, key_col)

        @staticmethod
        def json_lines(path: str, key_col: Optional[str] = None) -> JSONLinesReader:
            return JSONLinesReader(path, key_col)

        @staticmethod
        def avro(path: str, key_field: Optional[str] = None):
            """Simple Avro reader (DataReaders.Simple.avro,
            DataReaders.scala:75-88)."""
            from .avro import AvroReader

            return AvroReader(path, key_field)

        @staticmethod
        def csv_with_schema(csv_path: str, schema_path: str,
                            key_field: Optional[str] = None):
            """CSV typed via an Avro schema (CSVReaders.scala — the
            reference's canonical CSV path)."""
            from .avro import AvroSchemaCSVReader

            return AvroSchemaCSVReader(csv_path, schema_path, key_field)
