"""Ingestion resilience — retry/backoff for transient IO, bad-record
quarantine with a JSONL sidecar.

Reference: Spark gave the original TransmogrifAI task retries and the
``mode=DROPMALFORMED``/``badRecordsPath`` family on ingestion for free; the
TPU port reads files directly, so one flaky NFS read or one corrupt Avro
block killed an hour-long out-of-core fit.  This module restores both
behaviors as explicit, deterministic policy objects (docs/robustness.md):

* ``RetryPolicy`` — bounded exponential backoff with *deterministic* jitter
  (seeded RNG): only transient ``OSError``/``IOError`` retries; data
  corruption (``ValueError``/``EOFError``/decode errors) never does.
* ``BadRecordPolicy`` — ``fail`` (default: raise with an attributed
  location, byte-identical to the pre-resilience behavior) or
  ``quarantine`` (route the record to a JSONL sidecar with reason +
  location and keep going, failing fast past ``max_bad_records``).
* ``RetryingChunkStream`` — wraps a re-createable chunk stream; on a
  transient error it backs off, re-opens the stream, fast-skips the chunks
  already delivered, and resumes.  Chunking is deterministic (fixed
  ``chunk_rows``), so the skip is exact.

Wire-up: ``reader.with_resilience(...)`` attaches a ``ResilienceConfig``;
the out-of-core driver (workflow/streaming.py) wraps each reader pass in
the retrying stream and lands retry counts / backoff wall / quarantine
counts in ``utils/profiling.IngestProfiler``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

__all__ = ["RetryPolicy", "BadRecordPolicy", "QuarantineSink",
           "BadRecordError", "TooManyBadRecordsError", "ResilienceConfig",
           "RetryingChunkStream", "is_transient_io_error"]

#: OSError subclasses that retrying cannot fix — a missing file stays
#: missing; config errors should surface immediately
_NON_TRANSIENT_OS = (FileNotFoundError, PermissionError, IsADirectoryError,
                     NotADirectoryError)


def is_transient_io_error(exc: BaseException) -> bool:
    """The retry gate: transient ``OSError``/``IOError`` only.  Corruption
    (ValueError/EOFError) and programming errors are never retried."""
    return isinstance(exc, OSError) and not isinstance(exc, _NON_TRANSIENT_OS)


class BadRecordError(ValueError):
    """An unparseable record/row/block under the ``fail`` policy — carries
    the source + location so the operator can find the bytes."""

    def __init__(self, source: str, location: str, reason: str):
        super().__init__(f"{source}: bad record at {location}: {reason}")
        self.source = source
        self.location = location
        self.reason = reason


class TooManyBadRecordsError(BadRecordError):
    """Quarantine gave up: more than ``max_bad_records`` rows were bad —
    at that point the data is wrong, not merely dirty."""


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``backoff_s(attempt)`` = ``base_delay_s * 2**attempt`` capped at
    ``max_delay_s``, plus a jitter in ``[0, jitter * delay)`` drawn from a
    seeded RNG — two runs with the same seed sleep the same spans, so
    fault-injection tests are reproducible to the millisecond budget.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.1
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self._rng = np.random.default_rng(self.seed)

    def backoff_s(self, attempt: int) -> float:
        delay = min(self.base_delay_s * (2.0 ** attempt), self.max_delay_s)
        if self.jitter > 0:
            delay += float(self._rng.random()) * self.jitter * delay
        return delay


class QuarantineSink:
    """Append-only JSONL sidecar for quarantined records.

    One line per bad record: ``{"source", "location", "reason", "record"}``.
    Locations are deterministic (line number / block index + byte offset),
    and the sink de-duplicates on (source, location) — a retried stream
    that re-reads already-consumed chunks must not double-count, so the
    sidecar reconciles EXACTLY with the rows dropped from the dataset.
    """

    def __init__(self, path: str, max_bad_records: int = 1000):
        self.path = path
        self.max_bad_records = int(max_bad_records)
        self._lock = threading.Lock()
        self._seen: set = set()
        self.count = 0       # sidecar entries
        self.rows = 0        # data rows dropped (an Avro block entry is many)
        self._fh = None
        #: entries buffered on non-coordinator pod processes — the
        #: sidecar is a COORDINATOR-ONLY artifact (TM047): the pod train
        #: gathers these at the end and process 0 appends them
        self._pending: list = []

    def quarantine(self, source: str, location: str, reason: str,
                   record: Any = None, rows: int = 1) -> None:
        """Record one bad record (or a ``rows``-row bad block); raises
        TooManyBadRecordsError once more than ``max_bad_records`` ROWS are
        quarantined.  (source, location) pairs de-duplicate, so a retried
        re-read cannot double-count."""
        from ..distributed.runtime import current_pod

        key = (source, location)
        entry = {"source": source, "location": location,
                 "reason": reason, "rows": int(rows)}
        if record is not None:
            try:
                json.dumps(record)
                entry["record"] = record
            except (TypeError, ValueError):
                entry["record"] = repr(record)
        pod = current_pod()
        with self._lock:
            if key in self._seen:
                return
            self._seen.add(key)
            self.count += 1
            self.rows += int(rows)
            total_rows = self.rows
            if pod.active and not pod.is_coordinator():
                self._pending.append(entry)
            else:
                self._write_entry(entry)
        if total_rows > self.max_bad_records:
            raise TooManyBadRecordsError(
                source, location,
                f"exceeded max_bad_records={self.max_bad_records} "
                f"(quarantined {total_rows} rows; sidecar: {self.path})")

    def _write_entry(self, entry: dict) -> None:
        if self._fh is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        self._fh.write(json.dumps(entry) + "\n")
        self._fh.flush()

    def drain_pending(self) -> list:
        """Buffered entries (non-coordinator pod processes), cleared."""
        with self._lock:
            out, self._pending = self._pending, []
            return out

    def absorb(self, entries: list) -> None:
        """Coordinator-side: append another process's gathered entries
        (same (source, location) dedupe — pod processes read disjoint
        row ranges, so collisions only happen on shared sources)."""
        with self._lock:
            for entry in entries:
                key = (entry["source"], entry["location"])
                if key in self._seen:
                    continue
                self._seen.add(key)
                self.count += 1
                self.rows += int(entry.get("rows", 1))
                self._write_entry(entry)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


@dataclass
class BadRecordPolicy:
    """What ingestion does with an unparseable record."""

    FAIL = "fail"
    QUARANTINE = "quarantine"

    mode: str = FAIL
    quarantine_path: Optional[str] = None
    max_bad_records: int = 1000

    def __post_init__(self):
        if self.mode not in (self.FAIL, self.QUARANTINE):
            raise ValueError(f"bad-record mode must be 'fail' or "
                             f"'quarantine', got {self.mode!r}")
        if self.mode == self.QUARANTINE and not self.quarantine_path:
            raise ValueError("quarantine mode requires quarantine_path")


@dataclass
class ResilienceConfig:
    """Retry + bad-record policy attached to a Reader
    (``reader.with_resilience(...)``)."""

    retry: Optional[RetryPolicy] = None
    bad_records: BadRecordPolicy = field(default_factory=BadRecordPolicy)
    _sink: Optional[QuarantineSink] = field(default=None, repr=False)

    @property
    def quarantines(self) -> bool:
        return self.bad_records.mode == BadRecordPolicy.QUARANTINE

    def sink(self) -> Optional[QuarantineSink]:
        """The (lazily created, shared) quarantine sidecar writer; None
        under the ``fail`` policy."""
        if not self.quarantines:
            return None
        if self._sink is None:
            self._sink = QuarantineSink(self.bad_records.quarantine_path,
                                        self.bad_records.max_bad_records)
        return self._sink

    def handle_bad_record(self, source: str, location: str, reason: str,
                          record: Any = None, rows: int = 1) -> None:
        """Quarantine or raise, per policy.  Returns iff quarantined."""
        if self.quarantines:
            self.sink().quarantine(source, location, reason, record,
                                   rows=rows)
            return
        raise BadRecordError(source, location, reason)


class RetryingChunkStream:
    """Retry/backoff wrapper over a re-createable chunk stream.

    ``make_stream`` builds a fresh underlying ``ChunkStream``; after a
    transient IO error the wrapper sleeps the policy's backoff, rebuilds
    the stream, fast-skips the ``consumed`` chunks already delivered
    downstream, and resumes.  Attempts are budgeted PER CHUNK (a stream
    that fails on 10 distinct chunks is flaky, not dead), and exhausted
    budgets re-raise the last error with the retry history attached.

    Exposes ``bytes_read`` like the streams it wraps, so the ingest
    profiler's byte accounting is unchanged.
    """

    def __init__(self, make_stream: Callable[[], Iterator],
                 policy: RetryPolicy,
                 on_retry: Optional[Callable[[float], None]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._make = make_stream
        self._policy = policy
        self._on_retry = on_retry
        self._sleep = sleep
        self._stream = make_stream()
        self._consumed = 0
        self.retries = 0
        self.retry_wait_s = 0.0

    @property
    def bytes_read(self) -> int:
        return int(getattr(self._stream, "bytes_read", 0) or 0)

    def __iter__(self):
        return self

    def _reopen_and_skip(self) -> None:
        self._stream = self._make()
        for _ in range(self._consumed):
            next(self._stream)  # deterministic chunking: exact skip

    def __next__(self):
        attempt = 0
        need_reopen = False
        while True:
            try:
                if need_reopen:
                    # a generator that raised is dead: rebuild + exact skip
                    self._reopen_and_skip()
                    need_reopen = False
                chunk = next(self._stream)
            except StopIteration:
                raise
            except BaseException as exc:
                if (not is_transient_io_error(exc)
                        or attempt + 1 >= self._policy.max_attempts):
                    raise
                wait = self._policy.backoff_s(attempt)
                attempt += 1
                self.retries += 1
                self.retry_wait_s += wait
                if self._on_retry is not None:
                    self._on_retry(wait)
                self._sleep(wait)
                need_reopen = True
                continue
            self._consumed += 1
            return chunk
