"""Streaming readers — micro-batched scoring input.

Reference: ``StreamingReaders.Simple.avro`` (readers/StreamingReaders.scala:43-59)
builds a DStream of new files in a directory; ``OpWorkflowRunner.streamingScore``
(OpWorkflowRunner.scala:232-247) scores each micro-batch.

TPU redesign (SURVEY §2.12 streaming row): no Spark Streaming — a host-side
async batcher (background thread + bounded queue) prefetches and columnarizes
micro-batches while the device scores the previous one, keeping the compiled
score function fed.  Sources: any iterable of pandas DataFrames / record
lists, or a watched directory of CSV/parquet/json files (new-files-only like
the reference's ``FileStreamingAvroReader``).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

from ..features.feature import Feature
from ..types.columns import ColumnarDataset
from .base import DataFrameReader, Reader, RecordsReader

__all__ = ["StreamingReader", "IteratorStreamingReader",
           "FileStreamingReader", "AsyncBatcher", "StreamingReaders"]


class StreamingReader:
    """Yields ``ColumnarDataset`` micro-batches for raw features."""

    def stream(self, raw_features: Sequence[Feature]
               ) -> Iterator[ColumnarDataset]:
        raise NotImplementedError


class IteratorStreamingReader(StreamingReader):
    """Wraps any iterable of pandas DataFrames or record-lists."""

    def __init__(self, batches: Iterable[Any]):
        self.batches = batches

    def stream(self, raw_features):
        for batch in self.batches:
            if isinstance(batch, ColumnarDataset):
                yield batch
            elif isinstance(batch, (list, tuple)):
                yield RecordsReader(batch).generate_dataset(raw_features)
            else:
                yield DataFrameReader(batch).generate_dataset(raw_features)


class FileStreamingReader(StreamingReader):
    """Watch a directory, scoring each new data file as one micro-batch
    (FileStreamingAvroReader parity: path filter + newFilesOnly).

    ``poll_interval``/``max_polls`` bound the watch loop so batch jobs and
    tests terminate; a service would pass ``max_polls=None`` and cancel via
    ``stop()``.
    """

    def __init__(self, directory: str,
                 path_filter: Optional[Callable[[str], bool]] = None,
                 new_files_only: bool = False,
                 poll_interval: float = 1.0,
                 max_polls: Optional[int] = 1,
                 column_names: Optional[List[str]] = None):
        self.directory = directory
        self.path_filter = path_filter or (lambda p: not os.path.basename(
            p).startswith((".", "_")))
        self.new_files_only = new_files_only
        self.poll_interval = poll_interval
        self.max_polls = max_polls
        self.column_names = column_names
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def _list_files(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return []
        paths = [os.path.join(self.directory, n) for n in names]
        return [p for p in paths if os.path.isfile(p) and self.path_filter(p)]

    def _read_file(self, path: str, raw_features):
        import pandas as pd

        if path.endswith(".parquet"):
            df = pd.read_parquet(path)
        elif path.endswith((".json", ".jsonl")):
            df = pd.read_json(path, lines=path.endswith(".jsonl"))
        else:
            df = (pd.read_csv(path, header=None, names=self.column_names)
                  if self.column_names else pd.read_csv(path))
        return DataFrameReader(df).generate_dataset(raw_features)

    def stream(self, raw_features):
        seen = set(self._list_files()) if self.new_files_only else set()
        polls = 0
        while not self._stop.is_set():
            for path in self._list_files():
                if path in seen:
                    continue
                seen.add(path)
                yield self._read_file(path, raw_features)
            polls += 1
            if self.max_polls is not None and polls >= self.max_polls:
                return
            self._stop.wait(self.poll_interval)


class AsyncBatcher:
    """Bounded-queue prefetcher: a background thread columnarizes upcoming
    micro-batches while the device scores the current one — the host/device
    pipelining that replaces Spark Streaming's receiver.

    A proper iterator (``__iter__``/``__next__``): a producer-thread
    exception is captured and RE-RAISED from ``__next__`` after the items
    that preceded it have been consumed — the stream never ends silently
    on a mid-stream reader failure.  After exhaustion (or the re-raise)
    every further ``__next__`` raises ``StopIteration``.
    """

    _DONE = object()

    def __init__(self, source: Iterator[ColumnarDataset], depth: int = 2):
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._err: Optional[BaseException] = None
        self._closed = threading.Event()
        self._exhausted = False

        # the pump must not block forever on a full queue once the consumer
        # is gone (early break / scoring error), so puts poll the closed flag
        def pump():
            try:
                for item in source:
                    while not self._closed.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._closed.is_set():
                        return
            except BaseException as e:  # surfaced on the consumer side
                self._err = e
            finally:
                while not self._closed.is_set():
                    try:
                        self._q.put(self._DONE, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Release the pump thread; safe to call any time."""
        self._closed.set()

    def __iter__(self) -> "AsyncBatcher":
        return self

    def __next__(self) -> ColumnarDataset:
        if self._exhausted:
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._exhausted = True
            self.close()
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item


class StreamingReaders:
    """Factory catalogue (StreamingReaders.Simple parity)."""

    class Simple:
        @staticmethod
        def iterator(batches: Iterable[Any]) -> IteratorStreamingReader:
            return IteratorStreamingReader(batches)

        @staticmethod
        def files(directory: str, **kwargs) -> FileStreamingReader:
            return FileStreamingReader(directory, **kwargs)
