from .model_selector import (  # noqa: F401
    ModelSelector, SelectedModel, BinaryClassificationModelSelector,
    MultiClassificationModelSelector, RegressionModelSelector,
    DefaultSelectorParams, RandomParamBuilder, grid,
)
from .combiner import SelectedModelCombiner, SelectedCombinerModel  # noqa: F401
from .splitters import DataSplitter, DataBalancer, DataCutter  # noqa: F401
from .validators import OpCrossValidation, OpTrainValidationSplit  # noqa: F401
