"""Async sweep dispatch helpers — device-resident scoring and promotion.

ROADMAP item 1 ("kill the drain stall"): the sweep hot loop keeps every
candidate's fold metrics device-resident across units, dispatches the
next grid-group block while the previous one drains, and fetches only
the final reduced summary.  This module holds the pieces shared by the
queue scheduler (``selector.validators.SweepWorkQueue._run_all_async``)
and the halving scheduler (``tuning.halving``):

* :func:`sync_sweep_forced` — the ``TMOG_SYNC_SWEEP=1`` kill-switch,
  read at sweep time (not import time) so a process can toggle it
  between sweeps.  The switch restores the historical synchronous loop
  byte-identically (``_run_all_inner``).
* :func:`device_rung_scores` / :func:`device_promote` — a halving rung's
  elimination as an on-device finite-mean + ``lax.top_k`` reduction: the
  host fetches ``survivors_out`` int32 indices instead of the rung's
  full (C, F) metric matrix, so a rung advances without materializing
  per-candidate metrics.

Tie-breaking parity: ``lax.top_k`` returns the LOWER-index element first
among equals, which matches the host promotion's
``sorted(alive, key=lambda i: (sign * score[i], i))`` order, so the
device and host paths promote identical sets on ties (errored candidates
all carry the same worst sentinel and tie-break by index).  Device means
run in f32 where the host collect averages in f64 — candidates separated
by less than f32 epsilon may rank differently between the two paths; the
final winner is always re-selected from the host-precision ``collect``.
"""
from __future__ import annotations

import os
from typing import Any, List, Optional

__all__ = ["sync_sweep_forced", "device_rung_scores", "device_promote"]


def sync_sweep_forced() -> bool:
    """True when ``TMOG_SYNC_SWEEP=1``: run the historical synchronous
    sweep loop (per-unit materialization, host-side halving promotion)."""
    return os.environ.get("TMOG_SYNC_SWEEP", "") == "1"


_ROW_MEANS_JIT = None
_TOP_K_JIT = None


def _finite_mean_rows(M):
    """(C, F) device matrix -> (C,) f32 row means over FINITE entries
    (NaN when a row has none) — the device twin of ``collect``'s
    finite-fold averaging."""
    global _ROW_MEANS_JIT
    if _ROW_MEANS_JIT is None:
        import jax
        import jax.numpy as jnp

        def f(m):
            m = m.astype(jnp.float32)
            fin = jnp.isfinite(m)
            s = jnp.where(fin, m, 0.0).sum(axis=1)
            c = fin.sum(axis=1)
            return jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan)

        _ROW_MEANS_JIT = jax.jit(f)
    return _ROW_MEANS_JIT(M)


def device_rung_scores(all_vals: List[Any], errors: List[Optional[str]],
                       larger_better: bool):
    """A rung's per-candidate scores as ONE (C,) device vector.

    ``all_vals``/``errors`` are a deferred sweep's raw outputs
    (``SweepWorkQueue.run_all(..., defer=True)``): each entry is a
    ``_GroupRow`` marker into a device metric matrix, a list of device
    metric scalars, or host floats (restored / budget-skipped units).
    Grid-group matrices reduce with one ``_finite_mean_rows`` launch per
    matrix; nothing is fetched to the host here — the caller hands the
    vector to :func:`device_promote`.  Errored units score the worst
    sentinel for the metric direction (matching ``collect``)."""
    import jax.numpy as jnp

    from .validators import _GroupRow

    worst = float("-inf") if larger_better else float("inf")
    row_means: dict = {}
    cols = []
    for vals, err in zip(all_vals, errors):
        if isinstance(vals, _GroupRow):
            mid = id(vals.matrix)
            if mid not in row_means:
                row_means[mid] = _finite_mean_rows(vals.matrix)
            cols.append(row_means[mid][vals.row])
        elif err is not None or not len(vals):
            cols.append(jnp.float32(worst))
        else:
            v = jnp.stack([jnp.asarray(x, jnp.float32) for x in vals])
            cols.append(_finite_mean_rows(v[None, :])[0])
    return jnp.stack(cols)


def device_promote(scores, survivors_out: int, larger_better: bool
                   ) -> List[int]:
    """Top-``survivors_out`` positions of a (C,) device score vector,
    fetched as ``survivors_out`` int32s (the rung's ONLY host round-trip
    — booked as a genuine drain under ``halving.promote``: the next
    rung's candidate set depends on it, so nothing can overlap it).
    NaN scores (all-non-finite folds) rank worst, like ``collect``'s
    error promotion; returned positions are sorted ascending."""
    global _TOP_K_JIT
    import numpy as np

    from ..utils.profiling import fetch_timed

    if _TOP_K_JIT is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(1, 2))
        def f(s, k, larger):
            v = s if larger else -s
            v = jnp.where(jnp.isnan(v), -jnp.inf, v)
            _, idx = jax.lax.top_k(v, k)
            return idx

        _TOP_K_JIT = f
    idx = _TOP_K_JIT(scores, int(survivors_out), bool(larger_better))
    fetched = fetch_timed(idx, np.int64, tag="halving.promote")
    return sorted(int(i) for i in fetched)
