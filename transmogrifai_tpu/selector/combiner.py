"""SelectedModelCombiner — ensemble the predictions of two ModelSelectors.

Reference: ``SelectedModelCombiner`` / ``SelectedCombinerModel``
(core/.../impl/selector/SelectedModelCombiner.scala) with strategies from
``CombinationStrategy`` (features/.../impl/feature/CombinationStrategy.scala):

* ``best``     — all weight on the selector whose winning model validated
                 better (direction-aware, SelectedModelCombiner.scala:140-146);
* ``weighted`` — weights proportional to each selector's winning-model
                 metric.  Deviation from the reference, by design: for
                 minimize metrics (RMSE, LogLoss) the reference's
                 ``m1/(m1+m2)`` weighs the WORSE model higher
                 (SelectedModelCombiner.scala:147-148); here weights are
                 direction-corrected so the better model always dominates;
* ``equal``    — 0.5/0.5.

Metric resolution mirrors the reference (SelectedModelCombiner.scala:120-134):
same validation metric → each selector's winning validation value; different
metrics → overlap through the other selector's training metrics; no overlap
→ error.  The combined model transforms row predictions as
``raw = w1·raw1 + w2·raw2``, ``prob = w1·p1 + w2·p2``, prediction = argmax of
combined probabilities (weighted prediction when no probabilities exist,
SelectedModelCombiner.scala:230-237).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models.prediction import PredictionBatch
from ..stages.base import TernaryEstimator, TernaryModel
from ..types.columns import FeatureColumn
from ..types.feature_types import OPNumeric, Prediction

__all__ = ["SelectedModelCombiner", "SelectedCombinerModel"]

def _larger_better(metric: str) -> bool:
    from ..evaluators.metrics import MINIMIZE_METRICS
    return metric not in MINIMIZE_METRICS


def _as_batch(col: FeatureColumn) -> PredictionBatch:
    """Prediction column -> PredictionBatch (handles the row-dict form the
    local scorer and persistence paths produce)."""
    v = col.values
    if isinstance(v, PredictionBatch):
        return v
    rows = list(v)
    pred = np.asarray([0.0 if r is None else r.get("prediction", 0.0)
                       for r in rows], np.float64)

    def collect(prefix):
        ks: List[str] = sorted(
            {k for r in rows if r for k in r if k.startswith(prefix)},
            key=lambda k: int(k.rsplit("_", 1)[1]))
        if not ks:
            return None
        return np.asarray([[0.0 if r is None else r.get(k, 0.0) for k in ks]
                           for r in rows], np.float64)

    return PredictionBatch(prediction=pred,
                           raw_prediction=collect("rawPrediction_"),
                           probability=collect("probability_"))


class SelectedModelCombiner(TernaryEstimator):
    """Inputs: (label RealNN, prediction1, prediction2) where both prediction
    features come from ModelSelector stages (their fitted summaries supply
    the winning-model metrics that set the combination weights)."""

    input_types = (OPNumeric, Prediction, Prediction)
    label_input_positions = (0,)

    def __init__(self, combination_strategy: str = "best",
                 uid: Optional[str] = None):
        super().__init__(operation_name="combineModels",
                         output_type=Prediction, uid=uid)
        if combination_strategy not in ("best", "weighted", "equal"):
            raise ValueError(
                f"unknown combination_strategy {combination_strategy!r} "
                "(expected 'best', 'weighted' or 'equal')")
        self.combination_strategy = combination_strategy

    def output_is_response(self) -> bool:
        return False

    # -- summary plumbing ----------------------------------------------------

    def _selector_summaries(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        out = []
        for feat in self.input_features[1:3]:
            stage = feat.origin_stage
            summ = (stage.metadata or {}).get("model_selector_summary")
            if summ is None:
                raise RuntimeError(
                    "SelectedModelCombiner inputs must be predictions from "
                    f"fitted ModelSelectors; {feat.name!r} (stage "
                    f"{type(stage).__name__}) carries no "
                    "model_selector_summary")
            out.append(summ)
        return out[0], out[1]

    @staticmethod
    def _winning_metric(summ: Dict[str, Any]) -> Tuple[float, str]:
        """Validation metric value of the selector's winning model
        (SelectedModelCombiner.getWinningModelMetric)."""
        results = summ.get("validationResults") or []
        metric_name = results[0]["metricName"] if results else ""
        for r in results:
            if (r.get("modelType") == summ.get("bestModelType")
                    and r.get("params") == summ.get("bestModelParams")):
                return float(r["metricValue"]), metric_name
        vals = [float(r["metricValue"]) for r in results
                if np.isfinite(r["metricValue"])]
        if not vals:
            raise RuntimeError("selector summary has no finite validation "
                               "metric for the winning model")
        return (max(vals) if _larger_better(metric_name) else min(vals),
                metric_name)

    @staticmethod
    def _train_metric(summ: Dict[str, Any], name: str) -> Optional[float]:
        metrics = summ.get("trainEvaluationMetrics") or {}
        # exact key first: substring fallback alone would hit
        # RootMeanSquaredError when asked for MeanSquaredError
        if name in metrics and isinstance(metrics[name], (int, float)):
            return float(metrics[name])
        for k, v in metrics.items():
            if name and (name in k or k in name) and isinstance(
                    v, (int, float)):
                return float(v)
        return None

    def _resolve_metrics(self, s1, s2) -> Tuple[float, float, str]:
        m1, n1 = self._winning_metric(s1)
        m2, n2 = self._winning_metric(s2)
        if n1 == n2:
            return m1, m2, n1
        # different decision metrics: overlap through training metrics
        # (SelectedModelCombiner.scala:125-134)
        m2e1 = self._train_metric(s2, n1)
        if m2e1 is not None:
            t1 = self._train_metric(s1, n1)
            return (t1 if t1 is not None else m1), m2e1, n1
        m1e2 = self._train_metric(s1, n2)
        if m1e2 is not None:
            t2 = self._train_metric(s2, n2)
            return m1e2, (t2 if t2 is not None else m2), n2
        raise RuntimeError(
            "evaluation metrics for the two model selectors are "
            f"non-overlapping ({n1!r} vs {n2!r})")

    # -- fit -----------------------------------------------------------------

    def fit_columns(self, data, label_col: FeatureColumn,
                    p1_col: FeatureColumn, p2_col: FeatureColumn):
        s1, s2 = self._selector_summaries()
        if s1.get("problemType") not in (None, s2.get("problemType")):
            raise RuntimeError(
                "cannot combine selectors for different problem types: "
                f"{s1.get('problemType')} vs {s2.get('problemType')}")
        m1, m2, metric = self._resolve_metrics(s1, s2)
        strategy = self.combination_strategy
        lb = _larger_better(metric)
        if strategy == "best":
            first_wins = (m1 > m2) if lb else (m1 < m2)
            w1, w2 = (1.0, 0.0) if first_wins else (0.0, 1.0)
        elif strategy == "weighted":
            # maximize metrics can be negative (R2): clamp at 0 so weights
            # interpolate — a negative weight would extrapolate away from
            # the better model
            c1, c2 = max(m1, 0.0), max(m2, 0.0)
            tot = c1 + c2
            if tot <= 0 or not np.isfinite(tot):
                w1 = w2 = 0.5
            elif lb:
                w1, w2 = c1 / tot, c2 / tot
            else:  # minimize: better (smaller) metric gets the bigger weight
                w1, w2 = c2 / tot, c1 / tot
        else:
            w1 = w2 = 0.5

        if strategy == "best":
            # winner's summary verbatim (SelectedModelCombiner.scala:163-167)
            self.metadata["model_selector_summary"] = dict(
                s1 if w1 > 0.5 else s2)
        else:
            self.metadata["model_selector_summary"] = {
                "validationType": s1.get("validationType"),
                "bestModelType": f"{s1.get('bestModelType')} "
                                 f"{s2.get('bestModelType')}",
                "bestModelParams": {
                    **{f"{k}_1": v for k, v in
                       (s1.get("bestModelParams") or {}).items()},
                    **{f"{k}_2": v for k, v in
                       (s2.get("bestModelParams") or {}).items()}},
                "validationResults": list(s1.get("validationResults") or [])
                + list(s2.get("validationResults") or []),
                "holdoutMetrics": {},
                "trainEvaluationMetrics": {},
                "dataPrepResults": (s1.get("dataPrepResults")
                                    or s2.get("dataPrepResults")),
            }
        self.metadata["combiner"] = {
            "strategy": strategy, "metricName": metric,
            "metricValue1": m1, "metricValue2": m2,
            "weight1": w1, "weight2": w2,
        }
        model = SelectedCombinerModel(weight1=w1, weight2=w2,
                                      strategy=strategy, metric=metric)
        # rerun train evaluation on the COMBINED predictions for non-best
        # strategies (SelectedModelCombiner.scala:168-183)
        if strategy != "best" and label_col is not None:
            combined = model.transform_columns(label_col, p1_col, p2_col)
            self.metadata["model_selector_summary"][
                "trainEvaluationMetrics"] = _evaluate_combined(
                    label_col, combined.values)
        return model


def _evaluate_combined(label_col: FeatureColumn,
                       batch: PredictionBatch) -> Dict[str, float]:
    from ..evaluators.metrics import (
        binary_classification_metrics, multiclass_metrics,
        regression_metrics,
    )

    y = np.nan_to_num(np.asarray(label_col.values, np.float64))
    proba = batch.probability
    if proba is not None and proba.shape[1] == 2:
        return binary_classification_metrics(y, proba[:, 1])
    if proba is not None:
        pred = np.asarray(batch.prediction).astype(int)
        out = multiclass_metrics(y.astype(int), pred, proba.shape[1])
        out.pop("confusion", None)
        return out
    return regression_metrics(y, np.asarray(batch.prediction))


class SelectedCombinerModel(TernaryModel):
    """Row combiner: weighted raw/probability sums, argmax prediction
    (SelectedModelCombiner.scala transformFn :230-237)."""

    input_types = (OPNumeric, Prediction, Prediction)
    label_input_positions = (0,)

    def __init__(self, weight1: float, weight2: float, strategy: str = "best",
                 metric: str = "", uid: Optional[str] = None):
        super().__init__(operation_name="combineModels",
                         output_type=Prediction, uid=uid)
        self.weight1 = float(weight1)
        self.weight2 = float(weight2)
        self.strategy = strategy
        self.metric = metric

    def output_is_response(self) -> bool:
        return False

    def transform_columns(self, label_col, p1_col, p2_col) -> FeatureColumn:
        b1, b2 = _as_batch(p1_col), _as_batch(p2_col)
        w1, w2 = self.weight1, self.weight2

        def comb(a1, a2):
            if a1 is None or a2 is None:
                return None
            if np.shape(a1) != np.shape(a2):
                # two classification heads of different widths cannot be
                # blended; averaging their class INDICES instead would
                # produce a class neither model predicted
                raise ValueError(
                    "cannot combine predictions of different shapes "
                    f"{np.shape(a1)} vs {np.shape(a2)} (mismatched class "
                    "counts between the two selectors)")
            return w1 * np.asarray(a1, np.float64) + w2 * np.asarray(
                a2, np.float64)

        raw = comb(b1.raw_prediction, b2.raw_prediction)
        proba = comb(b1.probability, b2.probability)
        if proba is not None:
            pred = proba.argmax(axis=1).astype(np.float64)
        else:
            pred = w1 * np.asarray(b1.prediction) + w2 * np.asarray(
                b2.prediction)
        return FeatureColumn(Prediction, PredictionBatch(
            prediction=pred, raw_prediction=raw, probability=proba))
