"""Grid-batched candidate groups — the sweep's concurrency axis.

The reference runs its (model, fold) fits on a JVM thread pool
(``OpCrossValidation.scala:113-138``); the TPU equivalent is batching: a run
of candidates from the same estimator family fits as ONE XLA program over a
(folds, candidates) grid of traced hyperparameters, and the per-fold
validation metrics come back as one (C, F) device array.  ``_run_sweep``
consumes groups transparently — a group that declines (returns None) or
raises falls back to the per-candidate fitter path, which keeps the
reference's per-candidate failure isolation.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GridGroup", "LogRegGridGroup", "LinRegGridGroup",
           "make_grid_group"]


class GridGroup:
    """Base: one batched fit+score+metric program for C candidates.

    ``run(X, y, weight_ctxs)`` returns a device/host (C, F) metric matrix —
    row order matching the group's ``grid_points`` — or None to decline
    (callers then fit those candidates sequentially).
    """

    def __init__(self, proto, grid_points: Sequence[Dict[str, Any]],
                 metric: str):
        self.proto = proto
        self.grid_points = list(grid_points)
        self.metric = metric

    def run(self, X: np.ndarray, y: np.ndarray,
            weight_ctxs: Sequence[Tuple[np.ndarray, np.ndarray]]):
        raise NotImplementedError

    # -- helpers -------------------------------------------------------------

    def _param(self, params: Dict[str, Any], name: str):
        return params.get(name, getattr(self.proto, name))

    def _uniform(self, names: Sequence[str]) -> bool:
        """True when every candidate agrees on each of ``names`` (those
        params are static in the batched program)."""
        for n in names:
            vals = {self._param(p, n) for p in self.grid_points}
            if len(vals) > 1:
                return False
        return True

    @staticmethod
    def _stack_weights(weight_ctxs):
        W_tr = np.ascontiguousarray(
            np.stack([np.asarray(w, np.float32) for w, _ in weight_ctxs]))
        W_ev = np.ascontiguousarray(
            np.stack([np.asarray(w, np.float32) for _, w in weight_ctxs]))
        return W_tr, W_ev


class _LinearGridGroup(GridGroup):
    """Shared plumbing for the linear-family groups."""

    _batchable = ("reg_param", "elastic_net_param")
    _static = ("max_iter", "tol", "fit_intercept", "standardization")

    def _regs_alphas(self):
        import jax.numpy as jnp

        regs = jnp.asarray([float(self._param(p, "reg_param"))
                            for p in self.grid_points], jnp.float32)
        alphas = jnp.asarray([float(self._param(p, "elastic_net_param"))
                              for p in self.grid_points], jnp.float32)
        return regs, alphas

    def _batchable_params(self) -> bool:
        allowed = set(self._batchable) | set(self._static)
        if any(set(p) - allowed for p in self.grid_points):
            return False
        return self._uniform(self._static)

    def _metric_rows(self, y, scores, W_ev, binary: bool):
        """(F, C, N) device scores + (F, N) eval weights -> (C, F) device
        metrics (weights broadcast over candidates, never replicated), or
        None when the metric lacks a device kernel."""
        import jax.numpy as jnp

        from ..evaluators.metrics import (binary_metric_grid,
                                          regression_metric_grid)

        fn = binary_metric_grid if binary else regression_metric_grid
        m = fn(y, scores, jnp.asarray(W_ev), self.metric)
        if m is None:
            return None
        return m.T


class LogRegGridGroup(_LinearGridGroup):
    """All binary-LR (fold x candidate) fits in one majorization program
    (``linear.fit_logreg_grid``)."""

    def run(self, X, y, weight_ctxs):
        if not self._batchable_params():
            return None
        if len(y) and np.nanmax(y) > 1:          # binary device path only
            return None
        from ..models.linear import fit_logreg_grid
        from ..models.trees import _dev_f32

        W_tr, W_ev = self._stack_weights(weight_ctxs)
        regs, alphas = self._regs_alphas()
        max_iter = int(self._param(self.grid_points[0], "max_iter"))
        tol = float(self._param(self.grid_points[0], "tol"))
        scores, _ = fit_logreg_grid(
            _dev_f32(X), np.nan_to_num(np.asarray(y, np.float32)),
            _dev_f32(W_tr, tag="W_tr"), regs, alphas,
            # majorization steps are ~D^2/N cheaper than Newton steps;
            # give the solver a proportionally larger budget at a metric-
            # sufficient tolerance
            max_iter=max(150, 4 * max_iter), tol=max(tol, 1e-5),
            fit_intercept=bool(self._param(self.grid_points[0],
                                           "fit_intercept")),
            standardization=bool(self._param(self.grid_points[0],
                                             "standardization")))
        return self._metric_rows(y, scores, W_ev, binary=True)


class LinRegGridGroup(_LinearGridGroup):
    """All linear-regression (fold x candidate) fits in one Gram-sharing
    program (``linear.fit_linreg_grid``)."""

    def run(self, X, y, weight_ctxs):
        if not self._batchable_params():
            return None
        from ..models.linear import fit_linreg_grid
        from ..models.trees import _dev_f32

        W_tr, W_ev = self._stack_weights(weight_ctxs)
        regs, alphas = self._regs_alphas()
        preds = fit_linreg_grid(
            _dev_f32(X), np.nan_to_num(np.asarray(y, np.float32)),
            _dev_f32(W_tr, tag="W_tr"), regs, alphas,
            max_iter=int(self._param(self.grid_points[0], "max_iter")),
            tol=float(self._param(self.grid_points[0], "tol")),
            fit_intercept=bool(self._param(self.grid_points[0],
                                           "fit_intercept")),
            standardization=bool(self._param(self.grid_points[0],
                                             "standardization")))
        return self._metric_rows(y, preds, W_ev, binary=False)


def make_grid_group(proto, grid_points, problem_type: str,
                    metric: str) -> Optional[GridGroup]:
    """Group factory: returns a batched group when the estimator family,
    problem type, and metric support one — else None (sequential fits)."""
    if len(grid_points) == 0:
        return None
    from ..models.classification import OpLogisticRegression
    from ..models.regression import OpLinearRegression

    if problem_type == "binary" and type(proto) is OpLogisticRegression \
            and metric in ("AuPR", "AuROC"):
        return LogRegGridGroup(proto, grid_points, metric)
    if problem_type == "regression" and type(proto) is OpLinearRegression \
            and metric in ("RootMeanSquaredError", "MeanSquaredError",
                           "MeanAbsoluteError", "R2"):
        return LinRegGridGroup(proto, grid_points, metric)
    return None
