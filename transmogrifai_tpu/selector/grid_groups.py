"""Grid-batched candidate groups — the sweep's concurrency axis.

The reference runs its (model, fold) fits on a JVM thread pool
(``OpCrossValidation.scala:113-138``); the TPU equivalent is batching: a run
of candidates from the same estimator family fits as ONE XLA program over a
(folds, candidates) grid of traced hyperparameters, and the per-fold
validation metrics come back as one (C, F) device array.  ``_run_sweep``
consumes groups transparently — a group that declines (returns None) or
raises falls back to the per-candidate fitter path, which keeps the
reference's per-candidate failure isolation.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GridGroup", "LogRegGridGroup", "LinRegGridGroup",
           "SoftmaxGridGroup", "TreeGridGroup", "RFGridGroup",
           "GBTGridGroup", "make_grid_group"]


class GridGroup:
    """Base: one batched fit+score+metric program for C candidates.

    ``run(X, y, weight_ctxs)`` returns a device/host (C, F) metric matrix —
    row order matching the group's ``grid_points`` — or None to decline
    (callers then fit those candidates sequentially).

    With a ("data", "grid") sweep mesh attached (``with_mesh``), families
    that declare ``supports_mesh`` run the SAME batched program with rows
    sharded over the data axis and the candidate batch sharded over the
    grid axis (pjit/NamedSharding — GSPMD partitions the (F, C, N) solve
    and psums the per-shard Gram partials over ICI); families that don't
    decline, so their units fall back to sequential per-candidate fits
    whose estimators carry the mesh themselves.
    """

    #: whether this family's batched program partitions over a sweep mesh
    supports_mesh: bool = False

    def __init__(self, proto, grid_points: Sequence[Dict[str, Any]],
                 metric: str):
        self.proto = proto
        self.grid_points = list(grid_points)
        self.metric = metric
        self.mesh = None

    def with_mesh(self, mesh) -> "GridGroup":
        if mesh is not None:
            from ..parallel.mesh import has_grid_axis

            # fail at attach time, not three layers down in _place_sweep:
            # a (data, model) mesh here would shard candidate vectors over
            # feature lanes (the TM041 axis-confusion hazard at runtime)
            if not has_grid_axis(mesh):
                raise ValueError(
                    f"GridGroup needs a ('data', 'grid') sweep mesh; got "
                    f"axes {tuple(getattr(mesh, 'axis_names', ()))}")
        self.mesh = mesh
        return self

    def run(self, X: np.ndarray, y: np.ndarray,
            weight_ctxs: Sequence[Tuple[np.ndarray, np.ndarray]]):
        raise NotImplementedError

    def refit_model(self, row: int):
        """Fitted full-train model for candidate ``row``, or None.

        Groups that solve an appended full-train weight row alongside the
        folds hold every candidate's refit artifacts on device after
        ``run`` — the selector asks for the WINNER's here instead of paying
        a fresh sequential fit (the reference refits from scratch,
        ModelSelector.scala:145-209)."""
        return None

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _full_weights(weight_ctxs) -> np.ndarray:
        """Full-train weights from any one fold context: fold train + eval
        masks partition the selector's base weights, so w_tr + w_ev is the
        refit weighting for CV folds and TVS splits alike."""
        w_tr, w_ev = weight_ctxs[0]
        return (np.asarray(w_tr, np.float32)
                + np.asarray(w_ev, np.float32))

    def _param(self, params: Dict[str, Any], name: str):
        return params.get(name, getattr(self.proto, name))

    def _uniform(self, names: Sequence[str]) -> bool:
        """True when every candidate agrees on each of ``names`` (those
        params are static in the batched program)."""
        for n in names:
            vals = {self._param(p, n) for p in self.grid_points}
            if len(vals) > 1:
                return False
        return True

    @staticmethod
    def _stack_weights(weight_ctxs):
        W_tr = np.ascontiguousarray(
            np.stack([np.asarray(w, np.float32) for w, _ in weight_ctxs]))
        W_ev = np.ascontiguousarray(
            np.stack([np.asarray(w, np.float32) for _, w in weight_ctxs]))
        return W_tr, W_ev


class _LinearGridGroup(GridGroup):
    """Shared plumbing for the linear-family groups."""

    supports_mesh = True

    _batchable = ("reg_param", "elastic_net_param")
    _static = ("max_iter", "tol", "fit_intercept", "standardization")

    def _place_sweep(self, X, y_h: np.ndarray, W_solve: np.ndarray,
                     W_ev: np.ndarray, regs, alphas):
        """Device placement for the batched solve.

        Single chip (``mesh is None``): the memoized whole-array uploads.
        Sweep mesh: rows zero-pad to tile the data axis (pad rows carry
        zero weight in every fold row — inert through the weighted Grams,
        gradients and metrics, so results are invariant to pad amount),
        the matrix/fold-weights commit row-sharded, and the candidate
        vectors commit on the GRID axis (padded to tile it by repeating
        the last candidate) so GSPMD partitions the (F, C, N) solve over
        data x grid.  Returns ``(X_in, y_in, W_solve_in, W_ev_in, regs_in,
        alphas_in, strip)`` where ``strip`` trims candidate padding off an
        axis-1 candidate-batched array (None when no padding).
        """
        if self.mesh is None:
            from ..models.trees import _dev_f32
            return (_dev_f32(X), y_h, _dev_f32(W_solve, tag="W_tr"),
                    W_ev, regs, alphas, None)
        import jax
        import jax.numpy as jnp

        from ..models.trees import _dev_memo_sharded
        from ..parallel.mesh import (fold_weight_sharding, grid_sharding,
                                     pad_to_multiple, sweep_matrix_sharding)

        mesh = self.mesh
        ndata = mesh.shape[mesh.axis_names[0]]
        g = mesh.shape[mesh.axis_names[1]]
        if isinstance(X, jax.Array) and not isinstance(X, np.ndarray):
            # already committed row-sharded (the streaming→sharded ingest
            # hand-off); its rows are pre-padded to tile the data axis,
            # and the caller pre-padded y/weights to match
            X_dev = X
        else:
            Xp, _ = pad_to_multiple(np.asarray(X, np.float32), ndata,
                                    axis=0)
            X_dev = None
        yp, _ = pad_to_multiple(y_h, ndata)
        Wsp, _ = pad_to_multiple(np.ascontiguousarray(
            np.asarray(W_solve, np.float32)), ndata, axis=1)
        Wep, _ = pad_to_multiple(np.ascontiguousarray(
            np.asarray(W_ev, np.float32)), ndata, axis=1)
        C = int(regs.shape[0])
        c_pad = (-C) % g
        if c_pad:
            regs = jnp.concatenate([regs, jnp.repeat(regs[-1:], c_pad)])
            alphas = jnp.concatenate(
                [alphas, jnp.repeat(alphas[-1:], c_pad)])
        gs = grid_sharding(mesh)
        if X_dev is None:
            X_dev = _dev_memo_sharded(Xp, sweep_matrix_sharding(mesh),
                                      "sweep_X")
        Ws_dev = _dev_memo_sharded(Wsp, fold_weight_sharding(mesh),
                                   "sweep_Wtr")
        We_dev = _dev_memo_sharded(Wep, fold_weight_sharding(mesh),
                                   "sweep_Wev")
        strip = (lambda a: a[:, :C]) if c_pad else None
        return (X_dev, yp, Ws_dev, We_dev, jax.device_put(regs, gs),
                jax.device_put(alphas, gs), strip)

    def _regs_alphas(self):
        import jax.numpy as jnp

        regs = jnp.asarray([float(self._param(p, "reg_param"))
                            for p in self.grid_points], jnp.float32)
        alphas = jnp.asarray([float(self._param(p, "elastic_net_param"))
                              for p in self.grid_points], jnp.float32)
        return regs, alphas

    def _batchable_params(self) -> bool:
        allowed = set(self._batchable) | set(self._static)
        if any(set(p) - allowed for p in self.grid_points):
            return False
        return self._uniform(self._static)

    def _metric_rows(self, y, scores, W_ev, binary: bool):
        """(F, C, N) device scores + (F, N) eval weights -> (C, F) device
        metrics (weights broadcast over candidates, never replicated), or
        None when the metric lacks a device kernel."""
        import jax.numpy as jnp

        from ..evaluators.metrics import (binary_metric_grid,
                                          regression_metric_grid)

        fn = binary_metric_grid if binary else regression_metric_grid
        m = fn(y, scores, jnp.asarray(W_ev), self.metric)
        if m is None:
            return None
        return m.T


class LogRegGridGroup(_LinearGridGroup):
    """All binary-LR (fold x candidate) fits in one majorization program
    (``linear.fit_logreg_grid``)."""

    def run(self, X, y, weight_ctxs):
        if not self._batchable_params():
            return None
        if len(y) and np.nanmax(y) > 1:          # binary device path only
            return None
        from ..models.linear import fit_logreg_grid

        W_tr, W_ev = self._stack_weights(weight_ctxs)
        regs, alphas = self._regs_alphas()
        F = W_tr.shape[0]
        # appended full-train row: the winner's refit coefficients come out
        # of the SAME program (+1/F of the solve; saves the sequential
        # Newton refit over the full matrix)
        W_aug = np.ascontiguousarray(
            np.vstack([W_tr, self._full_weights(weight_ctxs)[None]]))
        max_iter = int(self._param(self.grid_points[0], "max_iter"))
        tol = float(self._param(self.grid_points[0], "tol"))
        X_in, y_in, W_in, W_ev_in, regs_in, alphas_in, strip = \
            self._place_sweep(X, np.nan_to_num(np.asarray(y, np.float32)),
                              W_aug, W_ev, regs, alphas)
        scores, _, coef, icpt = fit_logreg_grid(
            X_in, y_in, W_in, regs_in, alphas_in,
            # majorization steps are ~D^2/N cheaper than Newton steps;
            # give the solver a proportionally larger budget at a metric-
            # sufficient tolerance
            max_iter=max(150, 4 * max_iter), tol=max(tol, 1e-5),
            fit_intercept=bool(self._param(self.grid_points[0],
                                           "fit_intercept")),
            standardization=bool(self._param(self.grid_points[0],
                                             "standardization")))
        if strip is not None:
            scores, coef, icpt = strip(scores), strip(coef), strip(icpt)
        self._refit_coef, self._refit_icpt = coef[F], icpt[F]  # device (C, D)
        return self._metric_rows(y_in, scores[:F], W_ev_in, binary=True)

    def refit_model(self, row: int):
        if getattr(self, "_refit_coef", None) is None:
            return None
        from ..models.classification import LogisticRegressionModel

        return LogisticRegressionModel(
            coef=np.asarray(self._refit_coef[row]).tolist(),
            intercept=float(np.asarray(self._refit_icpt[row])))


class LinRegGridGroup(_LinearGridGroup):
    """All linear-regression (fold x candidate) fits in one Gram-sharing
    program (``linear.fit_linreg_grid``)."""

    def run(self, X, y, weight_ctxs):
        if not self._batchable_params():
            return None
        from ..models.linear import fit_linreg_grid

        W_tr, W_ev = self._stack_weights(weight_ctxs)
        regs, alphas = self._regs_alphas()
        F = W_tr.shape[0]
        W_aug = np.ascontiguousarray(
            np.vstack([W_tr, self._full_weights(weight_ctxs)[None]]))
        X_in, y_in, W_in, W_ev_in, regs_in, alphas_in, strip = \
            self._place_sweep(X, np.nan_to_num(np.asarray(y, np.float32)),
                              W_aug, W_ev, regs, alphas)
        preds, coef, icpt = fit_linreg_grid(
            X_in, y_in, W_in, regs_in, alphas_in,
            max_iter=int(self._param(self.grid_points[0], "max_iter")),
            tol=float(self._param(self.grid_points[0], "tol")),
            fit_intercept=bool(self._param(self.grid_points[0],
                                           "fit_intercept")),
            standardization=bool(self._param(self.grid_points[0],
                                             "standardization")))
        if strip is not None:
            preds, coef, icpt = strip(preds), strip(coef), strip(icpt)
        self._refit_coef, self._refit_icpt = coef[F], icpt[F]
        return self._metric_rows(y_in, preds[:F], W_ev_in, binary=False)

    def refit_model(self, row: int):
        if getattr(self, "_refit_coef", None) is None:
            return None
        from ..models.regression import LinearRegressionModel

        return LinearRegressionModel(
            coef=np.asarray(self._refit_coef[row]).tolist(),
            intercept=float(np.asarray(self._refit_icpt[row])))


class SoftmaxGridGroup(_LinearGridGroup):
    """All multiclass-LR (fold x candidate) fits in one Böhning-majorization
    program (``linear.fit_softmax_grid``); metrics via the argmax-label
    multiclass grid kernel."""

    #: decline above this many (F, C, K, N) logit elements — the solver
    #: holds ~3 such tensors transiently (16 GB HBM headroom)
    MAX_LOGIT_ELEMS = 2e8

    def __init__(self, proto, grid_points, metric, n_classes: int = 2):
        super().__init__(proto, grid_points, metric)
        self.n_classes = n_classes

    def run(self, X, y, weight_ctxs):
        if not self._batchable_params():
            return None
        n_classes = self.n_classes
        if len(y):
            n_classes = max(n_classes, int(np.nanmax(y)) + 1)
        F, C, n = len(weight_ctxs), len(self.grid_points), len(y)
        if F * C * n * n_classes > self.MAX_LOGIT_ELEMS:
            return None
        import jax.numpy as jnp

        from ..evaluators.metrics import multiclass_metric_grid
        from ..models.linear import fit_softmax_grid

        W_tr, W_ev = self._stack_weights(weight_ctxs)
        regs, alphas = self._regs_alphas()
        max_iter = int(self._param(self.grid_points[0], "max_iter"))
        tol = float(self._param(self.grid_points[0], "tol"))
        y_h = np.nan_to_num(np.asarray(y, np.float32))
        X_in, y_in, W_in, W_ev_in, regs_in, alphas_in, strip = \
            self._place_sweep(X, y_h, W_tr, W_ev, regs, alphas)
        yi = np.asarray(y_in).astype(np.int32)
        logits, _ = fit_softmax_grid(
            X_in, yi, n_classes, W_in, regs_in, alphas_in,
            max_iter=max(150, 4 * max_iter), tol=max(tol, 1e-5),
            fit_intercept=bool(self._param(self.grid_points[0],
                                           "fit_intercept")),
            standardization=bool(self._param(self.grid_points[0],
                                             "standardization")))
        if strip is not None:
            logits = strip(logits)
        preds = jnp.argmax(logits, axis=2)                 # (F, C, N)
        m = multiclass_metric_grid(yi, preds, jnp.asarray(W_ev_in),
                                   n_classes, self.metric)
        if m is None:
            return None
        return m.T


class TreeGridGroup(GridGroup):
    """Shared mesh plumbing for the TREE-family batched groups (RF tree
    streams, GBT lockstep chains): with a ("data", "grid") sweep mesh
    attached, the SAME batched programs run sharded — the binned int8
    matrix row-sharded ``P("data", None)``, per-candidate hyperparameter
    vectors (num_trees cap via bag masking, depth limit,
    min_child_weight, lambda, gate params) riding ``P("grid")`` with
    last-candidate padding stripped, and per-level histograms psum'd over
    the data axis (parallel/sharded.py ``grow_rf_grid_sharded`` /
    ``gbt_chain_rounds_sharded``).  Until PR 11 tree families declined
    the mesh and fell back to sequential mesh-sharded fits."""

    supports_mesh = True

    #: cost-model stage kind recorded per batched run (tuning/planner's
    #: ``advise_mesh`` and the straggler watchdog consult these)
    grid_stage_kind = ""

    def _mesh_axes(self):
        mesh = self.mesh
        return (int(mesh.shape[mesh.axis_names[0]]),
                int(mesh.shape[mesh.axis_names[1]]))

    def _sharded_matrix(self, binned, tag: str):
        """Row-pad a (device or host) binned matrix to tile the data axis
        and commit it ``P("data", None)`` — content-memoized like every
        other sweep upload."""
        from ..models.trees import _dev_memo_sharded
        from ..parallel.mesh import pad_to_multiple, sweep_matrix_sharding

        ndata, _ = self._mesh_axes()
        host, _pad = pad_to_multiple(np.asarray(binned), ndata, axis=0)
        return (_dev_memo_sharded(host, sweep_matrix_sharding(self.mesh),
                                  tag), host.shape[0])

    def _record_grid_observation(self, wall_s: float, rows: int,
                                 cols: int) -> None:
        """Append a ``<family>:fit-grid`` stage observation to the shared
        cost history so ``advise_mesh`` / the watchdog learn measured
        tree-grid scaling.  Best-effort — telemetry must not break a
        sweep."""
        if not self.grid_stage_kind or wall_s <= 0:
            return
        try:
            import time

            from ..parallel.elastic import mesh_device_count
            from ..tuning.costmodel import (StageObservation,
                                            append_observations,
                                            default_history_path)
            from ..utils.profiling import backend_name

            mesh_shape = ""
            if self.mesh is not None:
                mesh_shape = ",".join(
                    f"{a}={int(self.mesh.shape[a])}"
                    for a in self.mesh.axis_names)
            append_observations(default_history_path(), [StageObservation(
                stage_kind=self.grid_stage_kind, rows=int(rows),
                cols=max(int(cols), 1), dtype="float32",
                backend=backend_name(), wall_s=float(wall_s),
                t=int(time.time()),
                n_devices=mesh_device_count(self.mesh),
                mesh_shape=mesh_shape)])
        except Exception:
            pass


class RFGridGroup(TreeGridGroup):
    """Every (candidate x fold) random-forest fit as ONE chunked tree
    stream (``gbdt_kernels.grow_rf_grid``): per-tree traced
    (min_info_gain, min_instances, depth_limit) + fold-weight selection,
    identical randomness to the sequential per-candidate fits.  Covers
    binary, multiclass (one-hot targets, argmax scores against the
    multiclass metric grid) and regression sweeps.  On a sweep mesh the
    same pair stream runs sharded (``grow_rf_grid_sharded``) with
    PRE-GENERATED bags from the identical ``fold_in(seed, tree_id)``
    generator, so mesh and single-chip sweeps grow the same forests."""

    grid_stage_kind = "RandomForest:fit-grid"

    _batchable = ("max_depth", "min_info_gain", "min_instances_per_node")
    _static = ("num_trees", "max_bins", "subsample_rate",
               "feature_subset_strategy", "seed")

    def __init__(self, proto, grid_points, metric, n_classes: int = 2):
        super().__init__(proto, grid_points, metric)
        self.n_classes = n_classes

    def _batchable_params(self) -> bool:
        allowed = set(self._batchable) | set(self._static)
        if any(set(p) - allowed for p in self.grid_points):
            return False
        return self._uniform(self._static)

    def run(self, X, y, weight_ctxs):
        if not self._batchable_params():
            return None
        import time as _time

        import jax.numpy as jnp

        from ..evaluators.metrics import (_MULTI_GRID_METRICS,
                                          binary_metric_grid,
                                          multiclass_metric_grid,
                                          regression_metric_grid)
        from ..models.gbdt_kernels import grow_rf_grid
        from ..models.trees import (_dev_memo, _feature_subset_size,
                                    _prep_tree_inputs_sparse,
                                    _score_ensemble_jit)

        cls = self.proto._classification
        n_classes = self.n_classes
        if cls and len(y):
            n_classes = max(n_classes, int(np.nanmax(y)) + 1)
        multiclass = cls and n_classes > 2
        # decline BEFORE growing anything when the observed label space and
        # the metric family disagree (e.g. problem_type='binary'/AuPR with a
        # stray label > 1) — the forest sweep is the dominant cost
        if multiclass and self.metric not in _MULTI_GRID_METRICS:
            return None
        if cls and not multiclass and self.metric not in ("AuPR", "AuROC"):
            return None

        proto = self.proto
        y = np.nan_to_num(np.asarray(y, np.float32))
        # the CANDIDATES' max_bins (uniform across the grid — _static), not
        # the proto's: a grid overriding max_bins must bin with the value it
        # grows with, or bins past n_bins silently vanish from histograms
        mb = int(self._param(self.grid_points[0], "max_bins"))
        # sparse-aware prep: same sketch/memo keys as the GBT group and
        # the selector's prefetch thread, so one host sketch serves the
        # whole sweep (the CSR triple is unused here — RF histograms run
        # at feature-subset width).  Weight-aware: zero-total-weight rows
        # (mesh padding, balancer drops) never move the bin edges (TM024)
        from ..models.trees import _prep_tree_inputs_weighted

        edges, binned, _ = _prep_tree_inputs_weighted(
            X, mb, row_weight=self._full_weights(weight_ctxs))
        n, d = X.shape
        if cls:
            Y = np.eye(n_classes, dtype=np.float32)[y.astype(int)]
        else:
            Y = y[:, None].astype(np.float32)
        msub = _feature_subset_size(proto.feature_subset_strategy, d, cls)
        W_tr, W_ev = self._stack_weights(weight_ctxs)
        F = W_tr.shape[0]
        C = len(self.grid_points)
        T = int(self._param(self.grid_points[0], "num_trees"))

        # Depth-truncation sharing: candidates that differ ONLY in max_depth
        # share bags/folds by construction (bags key on tree id), and for
        # level-wise greedy growth a shallower candidate is exactly the
        # deeper tree truncated at its depth (splits at level l never depend
        # on deeper levels).  Grow ONE base forest per distinct
        # (min_info_gain, min_instances) group at that group's max depth and
        # read every shallower candidate off the base trees' leaf snapshots
        # — the r3 default grid (3 depths x 6 gate combos) grew 3x the
        # trees this needs.  The reference pays the full redundancy on its
        # thread pool (OpCrossValidation.scala:113-138).
        # clamp at 0: any non-positive requested depth IS a stump (and the
        # base_depth accumulator below starts at 0, so an unclamped -1
        # would read as "truncated below its base" and KeyError)
        cand_depth = [max(0, int(self._param(p, "max_depth")))
                      for p in self.grid_points]
        # depth <= 0 (stump) candidates get their OWN base: grow_rf_grid
        # filters non-positive levels out of its snapshot map (0 < v <
        # heap_depth), so truncation-sharing them off a deeper base would
        # KeyError in the scoring loop (ADVICE r4) — and a stump needs no
        # sharing anyway (depth_limit=0 grows it directly)
        cand_key = [(float(self._param(p, "min_info_gain")),
                     float(self._param(p, "min_instances_per_node")))
                    if cand_depth[i] > 0 else
                    (float(self._param(p, "min_info_gain")),
                     float(self._param(p, "min_instances_per_node")),
                     cand_depth[i])
                    for i, p in enumerate(self.grid_points)]
        # keys are (ig, inst) 2-tuples, or (ig, inst, depth) 3-tuples for
        # stump candidates — consumers below read k[0]/k[1] only
        base_keys: List[tuple] = []
        key2base: Dict[tuple, int] = {}
        for key in cand_key:
            if key not in key2base:
                key2base[key] = len(base_keys)
                base_keys.append(key)
        Cb = len(base_keys)
        base_depth = [0] * Cb
        for ci in range(C):
            bi = key2base[cand_key[ci]]
            base_depth[bi] = max(base_depth[bi], cand_depth[ci])
        leaf_levels = tuple(sorted({
            cand_depth[ci] for ci in range(C)
            if cand_depth[ci] < base_depth[key2base[cand_key[ci]]]}))

        # base pair p = bi * F + f
        pair_fold = np.tile(np.arange(F, dtype=np.int32), Cb)
        pair_ig = np.repeat([k[0] for k in base_keys], F)
        pair_inst = np.repeat([k[1] for k in base_keys], F)
        pair_depth = np.repeat(base_depth, F)
        t0 = _time.perf_counter()
        subsample = float(self._param(self.grid_points[0],
                                      "subsample_rate"))
        if self.mesh is not None:
            grown = self._grow_pairs_sharded(
                binned, Y, W_tr, seed=int(proto.seed), T=T,
                pair_fold=pair_fold, pair_ig=pair_ig, pair_inst=pair_inst,
                pair_depth=pair_depth, msub=msub, subsample=subsample,
                mb=mb, cls=cls, leaf_levels=leaf_levels)
        else:
            grown = grow_rf_grid(
                binned, _dev_memo(Y, "rf_Y"), _dev_memo(W_tr, "rf_Wtr"),
                seed=int(proto.seed), n_trees=T, pair_fold=pair_fold,
                pair_min_ig=pair_ig, pair_min_inst=pair_inst,
                pair_depth=pair_depth, msub=msub,
                subsample_rate=subsample,
                n_bins=int(self._param(self.grid_points[0], "max_bins")),
                onehot_targets=cls, leaf_levels=leaf_levels)
        self._record_grid_observation(_time.perf_counter() - t0, n, d)
        feats, threshs, leaves = grown[:3]
        snap_map = grown[3] if leaf_levels else {}
        heap_depth = int(np.log2(feats.shape[2] + 1))
        mode = "rf_cls" if cls else "rf_reg"
        ptype = ("multiclass" if multiclass
                 else "binary" if cls else "regression")

        # candidate-pair cp = c * F + f -> base pair + truncation depth
        cp_base = np.asarray(
            [key2base[cand_key[c]] * F + f
             for c in range(C) for f in range(F)], np.int32)
        cp_depth = np.repeat(cand_depth, F)
        cp_full = np.asarray(
            [cand_depth[c] == base_depth[key2base[cand_key[c]]]
             for c in range(C) for f in range(F)], bool)
        order: List[int] = []
        parts = []
        full_idx = np.where(cp_full)[0]
        if len(full_idx):
            sel = cp_base[full_idx]       # numpy: indexes device OR host
            parts.append(_score_pairs_jit(
                binned, feats[sel], threshs[sel], leaves[sel],
                heap_depth, mode, ptype))
            order.extend(full_idx.tolist())
        for dt in sorted(set(cp_depth[~cp_full].tolist())):
            idx = np.where(~cp_full & (cp_depth == dt))[0]
            sel = cp_base[idx]
            nd = 2 ** dt - 1
            # the base trees' first dt levels ARE the depth-dt candidate's
            # splits; its leaves are the level-dt histogram-total snapshot
            parts.append(_score_pairs_jit(
                binned, feats[sel][:, :, :nd], threshs[sel][:, :, :nd],
                snap_map[dt][sel], dt, mode, ptype))
            order.extend(idx.tolist())
        scores = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if order != list(range(C * F)):
            inv = np.empty(C * F, np.int32)
            inv[np.asarray(order, np.int32)] = np.arange(C * F, dtype=np.int32)
            scores = scores[jnp.asarray(inv)]
        scores = scores.reshape(C, F, n).transpose(1, 0, 2)  # (F, C, N)
        # release the grown forests and per-part score buffers before the
        # metric grid dispatches: at 1M-row sweeps the groups run back to
        # back and holding every phase's device intermediates to the end
        # of the sweep needlessly raises cumulative HBM pressure
        del grown, feats, threshs, leaves, snap_map, parts
        # context for refit_model: the winner's full-train forest grows as
        # ONE more base pair through the same (cached) grid program, with
        # identical randomness to a sequential full fit.  Single-chip
        # only: on a mesh the selector refits the winner sequentially
        # with its own mesh attached (the sharded grid program's chunk
        # shapes are sized for the whole pair stream, not one pair).
        if self.mesh is None:
            self._refit_ctx = dict(
                binned=binned, Y=Y, edges=edges, msub=msub, mb=mb, T=T,
                cls=cls, k=Y.shape[1], heap_depth=heap_depth,
                key2base=key2base, cand_key=cand_key,
                cand_depth=cand_depth,
                base_depth=base_depth, base_keys=base_keys,
                leaf_levels=leaf_levels,
                full_w=self._full_weights(weight_ctxs),
                seed=int(proto.seed), subsample=subsample)
        if multiclass:
            m = multiclass_metric_grid(y, scores, jnp.asarray(W_ev),
                                       n_classes, self.metric)
        else:
            fn = binary_metric_grid if cls else regression_metric_grid
            m = fn(y, scores, jnp.asarray(W_ev), self.metric)
        if m is None:
            return None
        return m.T

    def _grow_pairs_sharded(self, binned, Y, W_tr, *, seed: int, T: int,
                            pair_fold, pair_ig, pair_inst, pair_depth,
                            msub: int, subsample: float, mb: int,
                            cls: bool, leaf_levels):
        """The mesh leg of ``run``: rows padded + sharded over the data
        axis, the flat (pair x tree) stream over the grid axis, bags
        pre-generated from the SAME fold_in(seed, tree_id) stream as the
        on-device single-chip generator (``rf_bags_and_features``)."""
        from ..models.gbdt_kernels import (_resolve_compile_depth,
                                           rf_bags_and_features)
        from ..models.trees import _dev_memo_sharded
        from ..parallel.mesh import fold_weight_sharding, pad_to_multiple
        from ..parallel.sharded import grow_rf_grid_sharded

        mesh = self.mesh
        ndata, _g = self._mesh_axes()
        n = int(np.asarray(W_tr).shape[1])
        d = int(binned.shape[1])
        binned_dev, _n_pad = self._sharded_matrix(binned, "rf_grid_binned")
        Y_p, _ = pad_to_multiple(np.asarray(Y, np.float32), ndata, axis=0)
        Wtr_p, _ = pad_to_multiple(
            np.ascontiguousarray(np.asarray(W_tr, np.float32)), ndata,
            axis=1)
        BWr, feat_idx = rf_bags_and_features(seed, T, n, d, msub,
                                             subsample)
        BWr_p, _ = pad_to_multiple(np.asarray(BWr, np.float32), ndata,
                                   axis=1)
        from ..parallel.mesh import sweep_matrix_sharding

        Y_dev = _dev_memo_sharded(Y_p, sweep_matrix_sharding(mesh),
                                  "rf_grid_Y")
        fw = fold_weight_sharding(mesh)
        Wtr_dev = _dev_memo_sharded(Wtr_p, fw, "rf_grid_Wtr")
        BWr_dev = _dev_memo_sharded(BWr_p, fw, "rf_grid_BWr")
        heap_depth = _resolve_compile_depth(
            max(int(np.asarray(pair_depth).max()), 1))
        return grow_rf_grid_sharded(
            binned_dev, Y_dev, Wtr_dev, BWr_dev, feat_idx,
            pair_fold, pair_ig, pair_inst, pair_depth, mesh,
            n_trees=T, msub=msub, n_bins=mb, heap_depth=heap_depth,
            onehot_targets=cls, leaf_levels=leaf_levels)

    def refit_model(self, row: int):
        """Full-train refit of candidate ``row`` as ONE extra base pair.

        Reuses the sweep's compiled grid program (``compile_depth_hint``
        pins the sweep's heap depth), its binned-matrix/target memos, and
        the SAME per-tree randomness as a sequential full fit
        (``fold_in(seed, t)`` keys on tree id, not on fold) — so the
        deployed forest is what ``fit_raw`` on the full split would grow,
        at ~1/(bases x folds) of the sweep's cost instead of a fresh
        sequential fit + compile (ModelSelector.scala:145-209 refits from
        scratch).  Shallower-than-base winners come off the base pair's
        depth-truncation snapshot (exact for level-wise growth)."""
        ctx = getattr(self, "_refit_ctx", None)
        if ctx is None:
            return None
        import jax.numpy as jnp

        from ..models.gbdt_kernels import compile_depth_hint, grow_rf_grid
        from ..models.trees import TreeEnsembleModel, _dev_memo

        key = ctx["cand_key"][row]
        bi = ctx["key2base"][key]
        dt = ctx["cand_depth"][row]
        bd = ctx["base_depth"][bi]
        with compile_depth_hint(ctx["heap_depth"]):
            grown = grow_rf_grid(
                ctx["binned"], _dev_memo(ctx["Y"], "rf_Y"),
                _dev_memo(ctx["full_w"][None], "rf_Wfull"),
                seed=ctx["seed"], n_trees=ctx["T"],
                pair_fold=np.zeros(1, np.int32),
                pair_min_ig=np.asarray([key[0]], np.float32),
                pair_min_inst=np.asarray([key[1]], np.float32),
                pair_depth=np.asarray([bd], np.int32), msub=ctx["msub"],
                subsample_rate=ctx["subsample"], n_bins=ctx["mb"],
                onehot_targets=ctx["cls"], leaf_levels=ctx["leaf_levels"])
        feats, threshs, leaves = grown[:3]
        snap_map = grown[3] if ctx["leaf_levels"] else {}
        if dt < bd:
            nd = 2 ** dt - 1
            feat, thresh, leaf = (feats[0][:, :nd], threshs[0][:, :nd],
                                  snap_map[dt][0])
        else:
            feat, thresh, leaf = feats[0], threshs[0], leaves[0]
        return TreeEnsembleModel(
            mode="rf_cls" if ctx["cls"] else "rf_reg", edges=ctx["edges"],
            feat=feat, thresh=thresh, leaf=leaf,
            n_classes=ctx["k"] if ctx["cls"] else 2)


def _score_pairs_jit(binned, feats, threshs, leaves, heap_depth: int,
                     mode: str, ptype: str):
    """Pair validation scores in memory-bounded vmapped launches (12
    separate predict+transform launches measured ~8 s at 200k x 500; a
    single unbounded vmap OOMs on the (pairs, trees, rows) leaf gathers)."""
    import functools

    import jax
    import jax.numpy as jnp

    from ..models.trees import _score_ensemble_jit

    fn = functools.partial(_score_ensemble_jit, depth=heap_depth, mode=mode,
                           problem_type=ptype)
    P, T = feats.shape[0], feats.shape[1]
    n = binned.shape[0]
    k = leaves.shape[-1]
    per_pair = T * n * k * 4
    chunk = int(max(1, min(P, (64 << 20) // max(per_pair, 1))))
    parts = []
    for s in range(0, P, chunk):
        parts.append(jax.vmap(lambda f, t, lf: fn(binned, f, t, lf,
                                                  jnp.float32(0.0)))(
            feats[s:s + chunk], threshs[s:s + chunk], leaves[s:s + chunk]))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


class GBTGridGroup(TreeGridGroup):
    """Every (candidate x fold) boosting chain advanced in lockstep.

    Each round grows ALL chains' trees in one vmapped launch — the
    (rows, bins*features) one-hot that dominates wide-data histogram cost
    is chain-invariant, so XLA builds it once per row block and every
    chain's dots share it (measured ~1.5x over sequential chains at 6
    chains, plus the removal of per-chain Python dispatch).  Per-chain
    hyperparameters (depth limit, eta, lambda, min_child_weight, gamma)
    are traced per-tree vectors; early stopping replays the reference's
    patience logic per chain from chunked metric fetches
    (OpXGBoostClassifier.scala:47 ES semantics).

    The tree fast path composes here: EFB shrinks the shared histogram
    width before any launch (splits unbundle before scoring), GOSS
    engages for all-deep single-chip grids, and on a sweep mesh the SAME
    lockstep rounds run sharded (``gbt_chain_rounds_sharded`` — chains
    over the grid axis, rows over data, psum'd histograms).
    """

    grid_stage_kind = "GBT:fit-grid"

    def _chains(self):
        """Resolved per-candidate estimator copies (attribute-level params,
        robust to ctor-name aliases like XGB's eta -> step_size)."""
        return [self.proto.copy(**p) for p in self.grid_points]

    def run(self, X, y, weight_ctxs):
        import time as _time

        import jax
        import jax.numpy as jnp

        from ..evaluators.metrics import (_aupr_dev, binary_metric_grid,
                                          regression_metric_grid)
        from ..models.gbdt_kernels import predict_ensemble, predict_tree
        from ..models.trees import _dev_memo, _prep_tree_inputs_sparse
        from ..utils.profiling import count_launch

        ests = self._chains()
        e0 = ests[0]
        obj = e0._objective
        if obj not in ("binary", "regression"):
            return None
        if obj == "binary" and len(y) and np.nanmax(y) > 1:
            return None
        # static across chains; decline otherwise (sequential fallback)
        for attr in ("max_iter", "max_bins", "early_stopping_rounds",
                     "validation_fraction", "seed", "subsample_rate",
                     "colsample", "hist_precision",
                     "sparse_default_direction"):
            if len({getattr(e, attr) for e in ests}) > 1:
                return None
        if e0.subsample_rate < 1.0 or e0.colsample < 1.0:
            return None                     # per-round host RNG: sequential

        y = np.nan_to_num(np.asarray(y, np.float32))
        n = len(y)
        t0 = _time.perf_counter()
        # weight-aware sketch: zero-total-weight rows (mesh padding under
        # the TM024 contract, balancer drops) must not move the bin edges
        from ..models.trees import _prep_tree_inputs_weighted

        edges, binned, csr = _prep_tree_inputs_weighted(
            X, e0.max_bins, row_weight=self._full_weights(weight_ctxs))
        # EFB: pack the mutually exclusive one-hot/picklist columns into
        # shared histogram columns BEFORE any launch (both the single-chip
        # and the sharded path grow in bundled space; splits unbundle
        # before scoring, which routes on the original matrix)
        binned_orig = binned
        bundles = None
        bend = None
        if csr is None:
            from ..models.trees import (_as_f32, _content_hash,
                                        _efb_enabled, _maybe_bundle)

            if _efb_enabled():
                eb = _maybe_bundle(_content_hash(_as_f32(X)), edges,
                                   binned, int(e0.max_bins))
                if eb is not None:
                    bundles, binned, bend = eb
        d_hist = int(binned.shape[1])
        W_tr, W_ev = self._stack_weights(weight_ctxs)
        F = W_tr.shape[0]
        C = len(ests)
        # No appended full-train refit chains here, deliberately: measured
        # per-round cost is ~(shared one-hot + per-chain histogram dots),
        # so +C chains cost ~C/(C·F) of the whole sweep UNCONDITIONALLY,
        # while the sequential refit they would replace is paid only when
        # a GBT candidate actually wins — negative expected value for the
        # default grid (LR groups, whose extra row is ~free, do reuse).
        S_val = C * F
        S = S_val
        chain_fold = np.tile(np.arange(F, dtype=np.int32), C)
        chain_est = np.repeat(np.arange(C), F)

        def vec(attr, dtype=np.float32):
            return jnp.asarray(
                np.asarray([getattr(ests[c], attr) for c in chain_est],
                           dtype))
        depth_lim = vec("max_depth", np.int32)
        lams = vec("reg_lambda")
        mcws = vec("min_child_weight")
        migs = vec("min_info_gain")
        mins_ = jnp.asarray(np.asarray(
            [float(ests[c].min_instances_per_node) for c in chain_est],
            np.float32))
        lrs = vec("step_size")
        mgrs = vec("min_split_gain_raw")
        # heap shapes sized to THIS group's deepest chain — never an outer
        # sweep-wide hint (a depth-12 RF grid elsewhere in the sweep would
        # inflate these depth-6 chains' compacted-slot histograms ~20x)
        heap_depth = int(max(e.max_depth for e in ests))

        use_es = e0.early_stopping_rounds > 0
        rng = np.random.default_rng(e0.seed)
        val = (rng.random(n) < e0.validation_fraction) if use_es \
            else np.zeros(n, bool)
        # per-chain weights: full fold weights for the base score, ES-train
        # weights for gradients (sequential fit_raw parity)
        W_full = W_tr[chain_fold]                         # (S, N) host
        W_train = W_full * (~val)[None, :]
        if obj == "binary":
            pos = (W_full * y[None, :]).sum(axis=1)
            tot = np.maximum(W_full.sum(axis=1), 1e-9)
            p0 = np.clip(pos / tot, 1e-6, 1 - 1e-6)
            base = np.log(p0 / (1 - p0)).astype(np.float32)
        else:
            base = ((W_full @ y) / np.maximum(W_full.sum(axis=1), 1e-9)
                    ).astype(np.float32)

        base_j = jnp.asarray(base)
        if self.mesh is None:
            yj = _dev_memo(y, "gbt_y")
            Wj = _dev_memo(W_train, "gbt_Wtr")
            Fm = jnp.broadcast_to(base_j[:, None],
                                  (S, n)).astype(jnp.float32)
        else:
            yj = Wj = Fm = None            # placed sharded below
        vi = (jnp.asarray(np.where(val)[0], jnp.int32)
              if use_es and val.any() else None)

        lagged: list = []
        best_metric = np.full(S, -np.inf)
        best_len = np.zeros(S, np.int32)
        stall = np.zeros(S, np.int32)
        stopped = np.zeros(S, bool)
        es_chunk = max(1, min(8, e0.early_stopping_rounds or 8))
        from ..models.gbdt_kernels import (_gbt_chain_rounds_jit,
                                           default_dir_mask, gbt_chain_chunk,
                                           goss_plan, hist_accum_bf16,
                                           seg_hist_auto)

        # default-direction splits only on features whose bin 0 is a real
        # missing/zero bucket (sparse-aware pinned edge); bundle columns
        # never learn a default direction (no single-feature map-back)
        dd_host = (default_dir_mask(edges)
                   if e0.sparse_default_direction else None)
        if bundles is not None and dd_host is not None:
            dd_host = bundles.bundled_dd_mask(dd_host)
        dd = jnp.asarray(dd_host) if dd_host is not None else None

        # GOSS for all-deep single-chip grids (the sharded path keeps all
        # rows — a distributed |grad| top-k is not worth the collectives)
        goss = (goss_plan(n, min(int(e.max_depth) for e in ests))
                if self.mesh is None else None)
        acc = hist_accum_bf16()

        # segmented histograms at headline row counts (statically resolved
        # so it keys the jit cache).  Chain count matters: dense shares its
        # bins one-hot across vmapped chains, so seg only wins when the
        # HBM budget (or the grid) leaves <= SEG_MAX_CHAINS per launch
        chunk_dense = gbt_chain_chunk(S, heap_depth, d_hist,
                                      int(e0.max_bins), n)
        seg = seg_hist_auto(n, n_chains=min(chunk_dense, S))
        chunk = (gbt_chain_chunk(S, heap_depth, d_hist,
                                 int(e0.max_bins), n, seg_hist=True)
                 if seg else chunk_dense)
        if goss is not None:
            csr, seg = None, False
            chunk = chunk_dense
        run_es = use_es and vi is not None
        vi_arr = vi if vi is not None else jnp.zeros(1, jnp.int32)
        bf16 = e0._hist_bf16()   # backend-resolved: part of the jit key
        # count channel inert under pure XGB gating -> 2-channel
        # histograms; integer fold/train weights only (the count channel
        # is weighted — fractional weights could make 'CL >= 1' bite)
        skip_counts = (all(float(e.min_instances_per_node) <= 1
                           and float(e.min_info_gain) == 0.0 for e in ests)
                       and bool((W_train == np.floor(W_train)).all()))
        # es_chunk rounds per LAUNCH (lax.scan over rounds): through a
        # remote tunnel the per-round dispatch dominated device compute
        # (measured ~390 ms vs ~120 ms per round at 100k x 500).  Chunks
        # always run full length — the ≤ es_chunk-1 overshoot rounds past
        # max_iter or past a chain's stop are masked out of the final
        # scoring, exactly like the ES trim; patience replay only ever sees
        # rounds ≤ max_iter, so selection matches the per-round loop.
        if self.mesh is not None:
            # sweep-mesh placement: binned P("data", None), per-chain
            # row state P("grid", "data"), hyperparameter vectors
            # P("grid") padded by repeating the last chain (stripped
            # from every consumer below).  Chains are NOT sub-chunked on
            # the mesh path: per-device histogram memory is already
            # divided by the data axis, and a chain slice would have to
            # re-tile the grid axis per block.
            from ..parallel.mesh import (chain_sharding, data_sharding,
                                         pad_to_multiple)
            from ..parallel.sharded import gbt_chain_rounds_sharded
            from ..models.trees import _dev_memo_sharded

            mesh = self.mesh
            ndata, g_ax = self._mesh_axes()
            c_pad = (-S) % g_ax

            def padc(a):
                a = np.asarray(a)
                if not c_pad:
                    return a
                return np.concatenate([a, np.repeat(a[-1:], c_pad,
                                                    axis=0)])

            binned_sh, n_pad = self._sharded_matrix(binned,
                                                    "gbt_grid_binned")
            y_p, _ = pad_to_multiple(y, ndata)
            y_sh = _dev_memo_sharded(y_p, data_sharding(mesh),
                                     "gbt_grid_y")
            Wp, _ = pad_to_multiple(
                np.ascontiguousarray(padc(W_train)), ndata, axis=1)
            cs = chain_sharding(mesh)
            Wj = _dev_memo_sharded(Wp, cs, "gbt_grid_W")
            Fm = jax.device_put(np.ascontiguousarray(np.broadcast_to(
                padc(base)[:, None], Wp.shape).astype(np.float32)), cs)
            from ..parallel.mesh import grid_sharding

            gs = grid_sharding(mesh)

            def gvec(a):
                return jax.device_put(
                    np.ascontiguousarray(padc(np.asarray(a))), gs)

            vecs_sh = tuple(gvec(v) for v in (depth_lim, lams, mcws,
                                              migs, mins_, lrs, mgrs))
            yv_dev = (jnp.asarray(y[np.asarray(vi)]) if run_es
                      else jnp.zeros(1, jnp.float32))
        feats_b, threshs_b, leaves_b = [], [], []
        n_rounds = 0
        for ci in range(-(-e0.max_iter // es_chunk)):
            if self.mesh is not None:
                count_launch("gbt_chain_rounds_sharded")
                Fm, fs, ts, lfs, ms = gbt_chain_rounds_sharded(
                    binned_sh, y_sh, Wj, Fm, yv_dev, vi_arr, *vecs_sh,
                    self.mesh, n_rounds=es_chunk, max_depth=heap_depth,
                    n_bins=int(e0.max_bins), obj=obj, hist_bf16=bf16,
                    use_es=run_es, skip_counts=skip_counts,
                    bundle_end=(bundles.end_bin if bundles is not None
                                else None), acc_bf16=acc)
            elif chunk >= S:
                count_launch("gbt_chain_rounds")
                Fm, fs, ts, lfs, ms = _gbt_chain_rounds_jit(
                    binned, yj, Wj, Fm, vi_arr, depth_lim, lams, mcws, migs,
                    mins_, lrs, mgrs, es_chunk, heap_depth,
                    int(e0.max_bins), obj, bf16, run_es, csr=csr,
                    skip_counts=skip_counts, seg_hist=seg,
                    default_dir=e0.sparse_default_direction, dd_mask=dd,
                    bundle_end=bend, acc_bf16=acc, goss=goss,
                    goss_seed=jnp.int32(e0.seed),
                    chain_ids=jnp.arange(S, dtype=jnp.int32),
                    round_offset=jnp.int32(n_rounds))
            else:
                parts = []
                for s0 in range(0, S, chunk):
                    s1 = min(s0 + chunk, S)
                    count_launch("gbt_chain_rounds")
                    parts.append(_gbt_chain_rounds_jit(
                        binned, yj, Wj[s0:s1], Fm[s0:s1], vi_arr,
                        depth_lim[s0:s1], lams[s0:s1], mcws[s0:s1],
                        migs[s0:s1], mins_[s0:s1], lrs[s0:s1],
                        mgrs[s0:s1], es_chunk, heap_depth,
                        int(e0.max_bins), obj, bf16, run_es, csr=csr,
                        skip_counts=skip_counts, seg_hist=seg,
                        default_dir=e0.sparse_default_direction,
                        dd_mask=dd, bundle_end=bend, acc_bf16=acc,
                        goss=goss, goss_seed=jnp.int32(e0.seed),
                        chain_ids=jnp.arange(s0, s1, dtype=jnp.int32),
                        round_offset=jnp.int32(n_rounds)))
                Fm = jnp.concatenate([p[0] for p in parts])
                fs = jnp.concatenate([p[1] for p in parts], axis=1)
                ts = jnp.concatenate([p[2] for p in parts], axis=1)
                lfs = jnp.concatenate([p[3] for p in parts], axis=1)
                ms = jnp.concatenate([p[4] for p in parts], axis=1)
            feats_b.append(fs)
            threshs_b.append(ts)
            leaves_b.append(lfs)
            start = n_rounds
            n_rounds += es_chunk
            if run_es:
                # LAGGED fetch: replay the chunk enqueued ONE launch ago
                # (its device values are long since finished, so the sync
                # is ~free); decisions lag one chunk, the extra rounds are
                # trimmed by the masked scoring below.
                pending = [(start + j + 1, ms[j][:S])
                           for j in range(es_chunk)
                           if start + j + 1 <= e0.max_iter]
                if _replay_es(lagged, stopped, best_metric, best_len,
                              stall, e0.early_stopping_rounds,
                              overlapped=True):
                    break
                lagged = pending
        if run_es and not stopped.all():
            # drain the in-flight chunk so the final best_len is exact
            _replay_es(lagged, stopped, best_metric, best_len, stall,
                       e0.early_stopping_rounds)
        if not use_es:
            best_len[:] = e0.max_iter
        else:
            best_len[best_len == 0] = min(n_rounds, e0.max_iter)

        # final per-chain scores over ALL rows: ONE (rounds, chains) restack
        # + per-chain masked-leaf predicts.  Trimming by zeroing the leaves
        # of rounds >= best_len keeps every chain on the SAME (R, nodes)
        # shapes — per-chain trimmed stacks meant up to S distinct
        # predict_ensemble compiles plus R*S per-round device slices
        R = n_rounds
        if self.mesh is not None or bundles is not None:
            # host tree stacks: grid-sharded chain axes gather to host
            # (bounded — trees are tens of MB), and EFB splits unbundle
            # back to ORIGINAL columns so the scoring predicts route on
            # the original binned matrix
            feats_all = np.concatenate(
                [np.asarray(f) for f in feats_b]).transpose(1, 0, 2)[:S_val]
            threshs_all = np.concatenate(
                [np.asarray(t) for t in threshs_b]
            ).transpose(1, 0, 2)[:S_val]
            leaves_all = np.concatenate(
                [np.asarray(lv) for lv in leaves_b]
            ).transpose(1, 0, 2, 3)[:S_val]
            if bundles is not None:
                from ..models.gbdt_kernels import unbundle_ensemble

                feats_all, threshs_all = unbundle_ensemble(
                    bundles, feats_all, threshs_all)
            keep = np.arange(R)[None, :] < best_len[:S_val, None]
            leaves_m = leaves_all * keep[:, :, None, None]
            binned_sc = binned_orig
        else:
            feats_all = jnp.concatenate(feats_b).transpose(1, 0, 2)
            threshs_all = jnp.concatenate(threshs_b).transpose(1, 0, 2)
            leaves_all = jnp.concatenate(leaves_b).transpose(1, 0, 2, 3)
            keep = (jnp.arange(R)[None, :]
                    < jnp.asarray(best_len)[:, None])           # (S, R)
            leaves_m = leaves_all * keep[:, :, None, None]
            binned_sc = binned
        scores = []
        for s in range(S_val):
            count_launch("gbt_chain_score")
            raw = predict_ensemble(binned_sc, feats_all[s], threshs_all[s],
                                   leaves_m[s], heap_depth)[:, 0]
            z = raw + base_j[s]
            scores.append(jax.nn.sigmoid(z) if obj == "binary" else z)
        scores = jnp.stack(scores).reshape(C, F, n).transpose(1, 0, 2)
        self._record_grid_observation(_time.perf_counter() - t0, n,
                                      int(X.shape[1]))
        # release the per-round tree stacks, margins and masked leaves
        # before the metric grid runs (see RFGridGroup.run note); the last
        # chunk's loop locals pin device buffers too
        del feats_all, threshs_all, leaves_all, leaves_m, keep, Fm
        del feats_b, threshs_b, leaves_b
        fs = ts = lfs = ms = None  # noqa: F841 — drop last chunk's buffers
        fn = binary_metric_grid if obj == "binary" else regression_metric_grid
        m = fn(y, scores, jnp.asarray(W_ev), self.metric)
        if m is None:
            return None
        return m.T


def _replay_es(chunk_rows, stopped, best_metric, best_len, stall,
               patience: int, overlapped: bool = False) -> bool:
    """Replay one fetched chunk of per-chain ES metrics against the
    host-side patience state (in place); True when every chain stopped.
    The rule itself is ``trees.es_patience_vec`` — the same code the
    sequential single-chain fits run.  ``overlapped=True`` at the lagged
    call site (the next chunk's launch is already enqueued, so this wait
    books as overlap, not drain — utils/profiling.py)."""
    if not chunk_rows:
        return bool(stopped.all())
    from ..models.trees import _materialize_es, es_patience_vec

    return es_patience_vec(_materialize_es(chunk_rows,
                                           overlapped=overlapped),
                           stopped,
                           best_metric, best_len, stall, patience)


def make_grid_group(proto, grid_points, problem_type: str,
                    metric: str, n_classes: int = 2,
                    mesh=None) -> Optional[GridGroup]:
    """Group factory: returns a batched group when the estimator family,
    problem type, and metric support one — else None (sequential fits).
    ``n_classes`` is the selector's fit-time-captured class-space size
    (multiclass groups take the max of it and the observed labels).
    ``mesh`` (a ("data", "grid") sweep mesh) runs mesh-capable families'
    batched programs sharded — rows over data, candidates over grid."""
    if len(grid_points) == 0:
        return None
    group = _make_grid_group(proto, grid_points, problem_type, metric,
                             n_classes)
    if group is not None and mesh is not None:
        group.with_mesh(mesh)
    return group


def _make_grid_group(proto, grid_points, problem_type: str,
                     metric: str, n_classes: int = 2
                     ) -> Optional[GridGroup]:
    from ..evaluators.metrics import _MULTI_GRID_METRICS
    from ..models.classification import OpLogisticRegression
    from ..models.regression import OpLinearRegression

    from ..models.trees import (OpRandomForestClassifier,
                                OpRandomForestRegressor)

    _REG_METRICS = ("RootMeanSquaredError", "MeanSquaredError",
                    "MeanAbsoluteError", "R2")
    if problem_type == "binary" and type(proto) is OpLogisticRegression \
            and metric in ("AuPR", "AuROC"):
        return LogRegGridGroup(proto, grid_points, metric)
    if problem_type == "multiclass" \
            and type(proto) is OpLogisticRegression \
            and metric in _MULTI_GRID_METRICS:
        return SoftmaxGridGroup(proto, grid_points, metric,
                                n_classes=n_classes)
    if problem_type == "regression" and type(proto) is OpLinearRegression \
            and metric in _REG_METRICS:
        return LinRegGridGroup(proto, grid_points, metric)
    if problem_type in ("binary", "multiclass") \
            and type(proto) is OpRandomForestClassifier \
            and metric in (("AuPR", "AuROC") if problem_type == "binary"
                           else _MULTI_GRID_METRICS):
        return RFGridGroup(proto, grid_points, metric, n_classes=n_classes)
    if problem_type == "regression" \
            and type(proto) is OpRandomForestRegressor \
            and metric in _REG_METRICS:
        return RFGridGroup(proto, grid_points, metric)
    from ..models.trees import _GBTBase

    if isinstance(proto, _GBTBase):
        if problem_type == "binary" and proto._objective == "binary" \
                and metric in ("AuPR", "AuROC"):
            return GBTGridGroup(proto, grid_points, metric)
        if problem_type == "regression" \
                and proto._objective == "regression" \
                and metric in _REG_METRICS:
            return GBTGridGroup(proto, grid_points, metric)
    return None
