"""ModelSelector — automated model selection with validation.

Reference: ``ModelSelector`` estimator (core/.../impl/selector/ModelSelector.scala:72,
fit :145-209), ``ModelSelectorSummary`` (impl/selector/ModelSelectorSummary.scala),
factories ``BinaryClassificationModelSelector``
(impl/classification/BinaryClassificationModelSelector.scala:49,54-108,260-266),
``MultiClassificationModelSelector`` (:49,231-235),
``RegressionModelSelector`` (impl/regression/RegressionModelSelector.scala:49,237-242),
grid values ``DefaultSelectorParams`` (impl/selector/DefaultSelectorParams.scala:36-75),
``ModelSelectorFactory``, ``RandomParamBuilder``
(impl/selector/RandomParamBuilder.scala:52,169), ``SelectedModelCombiner``.

Flow (ModelSelector.fit parity): splitter reserves a holdout and computes
training weights -> validator scores every (model, params) candidate on CV
folds (weight-masked, single resident matrix) -> best estimator refit on the
full training split -> holdout + training metrics evaluated -> everything
recorded as ``model_selector_summary`` metadata.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..evaluators.metrics import (
    aupr, auroc, multiclass_metrics, regression_metrics,
    binary_classification_metrics,
)
from ..models.prediction import (
    PredictionBatch, PredictorEstimator, PredictorModel,
)
from ..types.columns import ColumnarDataset, FeatureColumn
from .splitters import DataBalancer, DataCutter, DataSplitter
from .validators import (
    OpCrossValidation, OpTrainValidationSplit, ValidationResult,
)

__all__ = [
    "ModelSelector", "SelectedModel", "ModelSelectorSummary",
    "BinaryClassificationModelSelector", "MultiClassificationModelSelector",
    "RegressionModelSelector", "DefaultSelectorParams", "RandomParamBuilder",
]


class DefaultSelectorParams:
    """Default grid values (DefaultSelectorParams.scala:36-75)."""

    MAX_DEPTH = [3, 6, 12]
    MAX_BIN = [32]
    MIN_INSTANCES_PER_NODE = [10, 100]
    MIN_INFO_GAIN = [0.001, 0.01, 0.1]
    REGULARIZATION = [0.001, 0.01, 0.1, 0.2]
    MAX_ITER_LIN = [50]
    MAX_ITER_TREE = [20]
    STEP_SIZE = [0.1]
    ELASTIC_NET = [0.1, 0.5]
    MAX_TREES = [50]
    TOL = [1e-6]
    NB_SMOOTHING = [1.0]
    NUM_ROUND_XGB = [200]
    ETA_XGB = [0.02]
    MIN_CHILD_WEIGHT_XGB = [1.0, 10.0]
    MAX_DEPTH_XGB = [10]
    EARLY_STOPPING_XGB = [20]
    GAMMA_XGB = [0.8]


def grid(**axes) -> List[Dict[str, Any]]:
    """Cartesian parameter grid."""
    keys = list(axes)
    out = []
    for combo in itertools.product(*(axes[k] for k in keys)):
        out.append(dict(zip(keys, combo)))
    return out


class ModelSelectorSummary:
    """Validation results + best model + metrics (ModelSelectorSummary parity)."""

    def __init__(self, validation_results: List[ValidationResult],
                 best_model_name: str, best_params: Dict[str, Any],
                 validation_type: str, holdout_metrics: Dict[str, float],
                 train_metrics: Dict[str, float],
                 splitter_summary: Optional[dict],
                 problem_type: Optional[str] = None):
        self.validation_results = validation_results
        self.best_model_name = best_model_name
        self.best_params = best_params
        self.validation_type = validation_type
        self.holdout_metrics = holdout_metrics
        self.train_metrics = train_metrics
        self.splitter_summary = splitter_summary
        self.problem_type = problem_type

    def to_json(self):
        return {
            "validationType": self.validation_type,
            "problemType": self.problem_type,
            "validationResults": [r.to_json() for r in self.validation_results],
            "bestModelType": self.best_model_name,
            "bestModelParams": self.best_params,
            "holdoutMetrics": self.holdout_metrics,
            "trainEvaluationMetrics": self.train_metrics,
            "dataPrepResults": self.splitter_summary,
        }


class ModelSelector(PredictorEstimator):
    """Generic selector over (estimator prototype, param grid) candidates.

    ``problem_type``: 'binary' | 'multiclass' | 'regression' — drives the
    validation score extraction and default metrics.
    """

    def __init__(self,
                 models_and_params: Sequence[Tuple[PredictorEstimator,
                                                   List[Dict[str, Any]]]],
                 problem_type: str,
                 validator=None,
                 splitter=None,
                 validation_metric: Optional[str] = None,
                 holdout_evaluators: Sequence = (),
                 uid: Optional[str] = None,
                 strategy: str = "full",
                 halving=None,
                 parallel=None,
                 watchdog: Optional[float] = None):
        super().__init__(operation_name="modelSelector", uid=uid)
        self.models_and_params = list(models_and_params)
        self.problem_type = problem_type
        self.validator = validator or OpCrossValidation(
            num_folds=3, stratify=problem_type != "regression")
        self.splitter = splitter
        self.validation_metric = validation_metric or {
            "binary": "AuPR", "multiclass": "F1",
            "regression": "RootMeanSquaredError"}[problem_type]
        self.holdout_evaluators = list(holdout_evaluators)
        # sweep scheduling: "full" fits every grid candidate to completion
        # (the historical path, byte-identical); "halving" runs successive
        # halving over the candidate grid (tuning/halving.py) — subsampled
        # rows/rounds for early rungs, full-data final rung.  ``halving``
        # takes a tuning.HalvingConfig.
        if strategy not in ("full", "halving"):
            raise ValueError(
                f"unknown selector strategy {strategy!r}; expected "
                f"'full' or 'halving'")
        self.strategy = strategy
        self.halving = halving
        # set by find_best_estimator (workflow-level CV): when present,
        # fit_columns skips validation and refits this winner directly
        # (reference BestEstimator, ModelSelector.scala:116-145)
        self.best_estimator: Optional[Tuple[str, Dict[str, Any],
                                            List[ValidationResult]]] = None
        self.mesh = None
        # pod-scale dispatch (ROADMAP item 1): None = single chip unless
        # with_mesh was called; an int = that many devices on an
        # auto-shaped ("data", "grid") sweep mesh; "auto" = let the cost
        # planner (tuning/planner.advise_mesh) decide from measured
        # scaling history; a jax Mesh = use it directly.
        self.parallel = parallel
        self.sweep_checkpoint_dir: Optional[str] = None
        self.sweep_checkpoint_every: int = 1
        # elastic execution (parallel/elastic.py): device-loss recovery is
        # always on — a classified backend loss shrinks the mesh and
        # retries the unit within this budget before quarantining the
        # candidate.  The straggler watchdog is OPT-IN: ``watchdog`` is
        # the deadline factor over the cost model's per-unit prediction
        # (None = off; also off while the cost-model tier is cold).
        self.watchdog = watchdog
        self.watchdog_cost_model = None   # test seam (with_watchdog)
        self.elastic_max_retries: int = 2

    def with_mesh(self, mesh) -> "ModelSelector":
        """Multi-chip selection.  With a ("data", "grid") sweep mesh
        (``parallel.make_sweep_mesh``), runs of same-family candidates
        batch as ONE pjit/NamedSharding program — rows sharded over the
        data axis, the candidate batch over the grid axis — and the
        remaining families fall back to sequential fits that are
        themselves mesh-sharded (each estimator's own ``with_mesh`` path).
        With a legacy ("data", "model") mesh every candidate fit runs
        mesh-sharded sequentially.  The single-chip device-resident sweep
        shortcut (``fit_device``) is bypassed either way — its programs
        are compiled for one chip's memory space."""
        self.mesh = mesh
        return self

    def with_watchdog(self, factor: float,
                      cost_model=None) -> "ModelSelector":
        """Arm the straggler watchdog: each sweep unit gets a deadline of
        ``factor x (CostModel.predict(sweep kind) / queue width)``.  A
        unit that overruns escalates timeout -> degraded re-run (mesh
        shrunk, deadline doubled) -> quarantine as ``failed: straggler``.
        Only engages when the cost model's tier for the sweep's stage
        kind is FITTED — a cold tier's analytic guess would produce
        garbage deadlines (``cost_model`` overrides the history-fitted
        model; a test seam)."""
        self.watchdog = float(factor)
        self.watchdog_cost_model = cost_model
        return self

    def with_sweep_checkpoint(self, directory: str,
                              every_units: int = 1) -> "ModelSelector":
        """Mid-sweep checkpoint/resume: completed sweep units' fold
        metrics (and the halving rung state) persist atomically under
        ``directory`` as the sweep advances, and a re-run against the
        same directory resumes at the cursor instead of refitting every
        candidate (workflow/checkpoint.SweepCheckpointManager)."""
        self.sweep_checkpoint_dir = directory
        self.sweep_checkpoint_every = int(every_units)
        return self

    def _resolve_parallel(self, n_rows: int, n_cols: int,
                          queue_width: int):
        """Resolve ``parallel`` into a sweep mesh for THIS fit (an
        explicit ``with_mesh`` wins; None means single-chip)."""
        if self.mesh is not None or self.parallel is None:
            return self.mesh
        import jax

        from ..parallel.mesh import make_sweep_mesh

        p = self.parallel
        if hasattr(p, "axis_names"):          # a prebuilt Mesh
            return p
        n_avail = len(jax.devices())
        if p == "auto":
            from ..tuning.planner import advise_mesh

            adv = advise_mesh(n_rows, n_cols, queue_width=queue_width,
                              devices_available=n_avail)
            self.metadata["mesh_advice"] = adv.to_json()
            if adv.n_devices <= 1:
                return None
            return make_sweep_mesh(queue_width, n_devices=adv.n_devices,
                                   grid_parallelism=adv.grid_axis)
        n = min(int(p), n_avail)
        if n <= 1:
            return None
        return make_sweep_mesh(queue_width, n_devices=n)

    # -- elastic execution ---------------------------------------------------

    def _elastic_context(self, n_rows: int, n_cols: int, queue_width: int):
        """The per-fit elastic policy (parallel/elastic.py): a shrink
        hook that re-points this stage's LIVE ``mesh`` attribute at a
        smaller sweep mesh built from surviving devices (the unit fitters
        read it per fit, so the retried unit lands on the shrunk mesh —
        ultimately ``None``, the single-device CPU-fallback path), plus
        the opt-in watchdog deadline."""
        from ..parallel.elastic import ElasticContext, shrink_mesh

        def shrink() -> bool:
            # the tree-prep prefetch thread must not outlive the mesh it
            # may be uploading against: cancel + join BEFORE re-pointing
            # the live mesh at the shrunk one (ISSUE 11 satellite — an
            # aborting sweep used to leave the daemon running)
            self._drain_tree_prefetch()
            new = shrink_mesh(self.mesh)
            changed = (new is not self.mesh
                       and (new is None or self.mesh is None
                            or new.shape != self.mesh.shape))
            self.mesh = new
            return changed

        ctx = ElasticContext(shrink=shrink,
                             max_unit_retries=self.elastic_max_retries,
                             unit_deadline_s=self._watchdog_deadline(
                                 n_rows, n_cols, queue_width))
        # live-mesh peek for the sweep spans (obs/): unit spans record the
        # mesh each attempt actually ran on, which a shrink re-points
        ctx.mesh_provider = lambda: self.mesh
        return ctx

    def _watchdog_deadline(self, n_rows: int, n_cols: int,
                           queue_width: int) -> Optional[float]:
        """``factor x predicted sweep wall / queue width``, or None when
        the watchdog is unarmed or the cost-model tier is cold (an
        analytic cold-start guess would quarantine healthy units)."""
        if not self.watchdog:
            return None
        from ..utils.profiling import backend_name

        cm = self.watchdog_cost_model
        if cm is None:
            from ..tuning.costmodel import CostModel

            cm = CostModel.from_history()
        kind = ("ModelSelector:fit-halving" if self.strategy == "halving"
                else "ModelSelector:fit")
        backend = backend_name()
        # tree grid units record their own stage kinds (RandomForest:
        # fit-grid / GBT:fit-grid) — when those tiers are warm the
        # watchdog sees tree grid units even before the selector-level
        # tier is; deadlines sum over whichever kinds are fitted
        kinds = [kind] + [k for k in self._tree_grid_kinds()]
        fitted = [k for k in kinds if cm.source(k, backend) == "fitted"]
        if not fitted:
            return None               # all tiers cold: watchdog stays off
        from ..parallel.elastic import mesh_device_count

        total = sum(cm.predict(k, n_rows, n_cols, backend=backend,
                               n_devices=mesh_device_count(self.mesh))
                    for k in fitted)
        return max(float(self.watchdog) * total / max(queue_width, 1),
                   1e-3)

    def _tree_grid_kinds(self) -> List[str]:
        """The tree-grid cost-model stage kinds present in this grid."""
        from ..models.trees import _GBTBase, _RandomForestBase

        kinds = []
        for proto, _pts in self.models_and_params:
            if isinstance(proto, _RandomForestBase):
                kinds.append("RandomForest:fit-grid")
            elif isinstance(proto, _GBTBase):
                kinds.append("GBT:fit-grid")
        return sorted(set(kinds))

    # -- validation plumbing -------------------------------------------------

    def _score_fn(self, model: PredictorModel, X: np.ndarray):
        dev = model.score_device(X, self.problem_type)
        if dev is not None:
            return dev                     # device array; metric stays lazy
        batch = model.predict_batch(X)
        if self.problem_type == "binary":
            if batch.probability is not None:
                return np.asarray(batch.probability)[:, 1]
            return np.asarray(batch.raw_prediction)[:, 1]
        return np.asarray(batch.prediction)

    def _metric(self, y, scores, w):
        """Fold metric; returns a DEVICE scalar when scores are device-
        resident and the metric has a device kernel (validators fetch all
        fold scalars in one stacked transfer), else a host float."""
        import jax

        m = self.validation_metric
        if isinstance(scores, jax.Array):
            dev = self._metric_device(y, scores, w, m)
            if dev is not None:
                return dev
            scores = np.asarray(scores)
        if self.problem_type == "binary":
            if m == "AuPR":
                return float(aupr(y, scores, w))
            if m == "AuROC":
                return float(auroc(y, scores, w))
            return binary_classification_metrics(y, scores, w)[m]
        if self.problem_type == "multiclass":
            n_classes = self._class_count(y, scores)
            return multiclass_metrics(y.astype(int), scores.astype(int),
                                      n_classes, w)[m]
        return regression_metrics(y, scores, w)[m]

    def _capture_class_space(self, y) -> None:
        """Record the class space from the FULL labels before any split —
        validation folds missing the top class must not shrink it."""
        if self.problem_type == "multiclass" and len(y):
            self._n_classes = max(int(np.nanmax(y)) + 1, 2)

    def _class_count(self, y, pred=None) -> int:
        """Class space size: the FULL-training-label count captured at fit
        time wins — a validation fold missing the top class must not shrink
        the class space (the reference reads it from the label indexer
        metadata; here fit captures it before any split)."""
        n = getattr(self, "_n_classes", 0)
        if y is not None and len(y):
            n = max(n, int(np.nanmax(y)) + 1)
        if pred is not None and len(pred):
            n = max(n, int(np.nanmax(np.asarray(pred))) + 1)
        return max(n, 2)

    def _metric_device(self, y, scores, w, m: str):
        import jax.numpy as jnp

        from ..evaluators.metrics import _aupr_dev, _auroc_dev

        if self.problem_type == "binary":
            if m == "AuPR":
                return _aupr_dev(y, scores, w)
            if m == "AuROC":
                return _auroc_dev(y, scores, w)
            return None
        if self.problem_type == "regression":
            if m not in ("RootMeanSquaredError", "MeanSquaredError",
                         "MeanAbsoluteError", "R2"):
                return None
            from ..evaluators.metrics import _regression_metric_dev

            yj = jnp.asarray(y, jnp.float32)
            wj = (jnp.ones_like(yj) if w is None
                  else jnp.asarray(w, jnp.float32))
            return _regression_metric_dev(yj, scores, wj, m)
        if self.problem_type == "multiclass":
            from ..evaluators.metrics import _multiclass_core

            n_classes = self._class_count(y)
            res = _multiclass_core(np.asarray(y, np.int32), scores,
                                   n_classes, w)
            return res.get(m)
        return None

    @property
    def larger_better(self) -> bool:
        from ..evaluators.metrics import MINIMIZE_METRICS
        return self.validation_metric not in MINIMIZE_METRICS

    def _candidates(self, with_groups: bool = True):
        from ..models.gbdt_kernels import compile_depth_hint
        from ..parallel.mesh import has_grid_axis
        from .grid_groups import make_grid_group

        grid_mesh = has_grid_axis(self.mesh)
        out = []
        for proto, grid_points in self.models_and_params:
            # one batched program for the whole (folds x grid) product when
            # the family supports it.  Single chip by default; on a
            # ("data", "grid") sweep mesh the mesh-capable families run
            # the SAME batched program sharded (rows over data, candidate
            # batch over grid), while a legacy ("data", "model") mesh
            # keeps the historical per-candidate sharded fits.
            # ``with_groups=False`` is the halving scheduler's path: rung
            # subsets fit per-candidate (a group always computes its WHOLE
            # family grid, which would pay for eliminated candidates) —
            # the sharded halving sweep re-batches each rung's survivors
            # via ``_make_rung_regroup`` instead.
            group = (make_grid_group(proto, grid_points, self.problem_type,
                                     self.validation_metric,
                                     n_classes=self._class_count(None),
                                     mesh=self.mesh if grid_mesh else None)
                     if ((self.mesh is None or grid_mesh) and with_groups)
                     else None)
            fam_depth = self._family_depth(proto, grid_points)
            for params in grid_points:
                def fitter(X, y, w, p, proto=proto, fam_depth=fam_depth):
                    # heap shapes sized to THIS family's deepest candidate —
                    # a sweep-wide hint made shallow families (XGB depth 6)
                    # pay the deep family's (RF depth 12) compacted-slot
                    # histogram cost, ~20x on the default grid
                    with compile_depth_hint(fam_depth):
                        est = proto.copy(**p)
                        if self.mesh is not None:
                            if hasattr(est, "with_mesh"):
                                est.with_mesh(self.mesh)
                        else:
                            dev_score = est.fit_device(X, y, w,
                                                       self.problem_type)
                            if dev_score is not None:
                                return dev_score  # device fit+score, no sync
                        model = est.fit_raw(X, y, w)
                    return lambda Xe: self._score_fn(model, Xe)
                out.append((type(proto).__name__, params, fitter, group))
        return out

    def _resolved_splitter(self):
        if self.splitter is not None:
            return self.splitter
        return {"binary": DataBalancer(),
                "multiclass": DataCutter(),
                "regression": DataSplitter()}[self.problem_type]

    def _sweep_checkpoint(self, candidates, n_rows: int, elastic=None):
        """Mid-sweep cursor manager for this fit, or None.  Primed from
        disk (resume); a checkpoint for a LOGICALLY different sweep
        raises CheckpointMismatchError instead of blending runs, while a
        mesh-shape change resumes — the remaining units re-batch onto
        this process's mesh, and the re-pack/shrink lands on the elastic
        counters."""
        if self.sweep_checkpoint_dir is None:
            return None
        from ..workflow.checkpoint import (SweepCheckpointManager,
                                           mesh_record, sweep_fingerprint)

        v = self.validator
        vdesc = (f"{type(v).__name__}("
                 f"folds={getattr(v, 'num_folds', None)},"
                 f"ratio={getattr(v, 'train_ratio', None)},"
                 f"seed={getattr(v, 'seed', None)},"
                 f"stratify={getattr(v, 'stratify', None)})")
        fp = sweep_fingerprint(candidates, self.validation_metric, vdesc,
                               mesh=self.mesh, strategy=self.strategy,
                               n_rows=n_rows)
        manager = SweepCheckpointManager(
            self.sweep_checkpoint_dir, fp,
            every_units=self.sweep_checkpoint_every)
        if manager.load() and manager.mesh_changed and elastic is not None:
            elastic.note_resumed_mesh(manager.resumed_mesh,
                                      mesh_record(self.mesh))
        return manager

    def _make_rung_regroup(self, candidates):
        """Per-rung grid-group factory for the SHARDED halving sweep: a
        rung's surviving same-family candidates re-batch (at their
        rung-scaled fit params) into one mesh-sharded program packed onto
        the grid axis.  None on single-chip / legacy meshes — the rungs
        keep their per-candidate fits."""
        from ..parallel.mesh import has_grid_axis

        if not has_grid_axis(self.mesh):
            return None
        from .grid_groups import make_grid_group

        protos = [proto for proto, pts in self.models_and_params
                  for _ in pts]

        def regroup(indices, fit_params_list):
            out = []
            pos = 0
            while pos < len(indices):
                proto = protos[indices[pos]]
                end = pos
                while end < len(indices) and protos[indices[end]] is proto:
                    end += 1
                pts = [dict(fit_params_list[p]) for p in range(pos, end)]
                group = make_grid_group(
                    proto, pts, self.problem_type, self.validation_metric,
                    n_classes=self._class_count(None), mesh=self.mesh)
                for p in range(pos, end):
                    name, _params, fitter, *_ = candidates[indices[p]]
                    out.append((name, fit_params_list[p], fitter, group))
                pos = end
            return out

        return regroup

    @staticmethod
    def _family_depth(proto, grid_points):
        """Deepest tree depth within ONE estimator family's grid: that
        family's sequential fits then share ONE compiled tree-growth
        program, each candidate's true max_depth applied as a traced depth
        limit (gbdt_kernels.compile_depth_hint).  Per FAMILY, not sweep-
        wide: families never share growth programs, so a global hint only
        inflates the shallow family's heap shapes."""
        proto_d = getattr(proto, "max_depth", None)
        depths = [int(params.get("max_depth", proto_d))
                  for params in grid_points
                  if params.get("max_depth", proto_d) is not None]
        return max(depths) if depths else None

    def find_best_estimator(self, data: ColumnarDataset,
                            during_dag) -> Tuple[str, Dict[str, Any]]:
        """Workflow-level CV (ModelSelector.findBestEstimator
        ModelSelector.scala:116): validate candidates with the
        feature-engineering ``during_dag`` refit inside every fold, and
        remember the winner so the subsequent ``fit`` skips validation."""
        label_name = self.label_feature.name
        if label_name not in data:
            raise RuntimeError(
                f"label column {label_name!r} not materialized before the "
                f"CV cut — it must be produced by the before-DAG")
        y = np.nan_to_num(np.asarray(data[label_name].values,
                                     dtype=np.float32))
        n = len(y)
        self._capture_class_space(y)
        splitter = self._resolved_splitter()
        train_idx, _ = splitter.split_indices(n, y)
        train_mask = np.zeros(n, dtype=bool)
        train_mask[train_idx] = True
        base_w = splitter.train_weights(y, train_mask)

        sub = data.take(train_idx)
        candidates = self._candidates()
        best_i, results = self.validator.validate_with_dag(
            candidates, sub, during_dag,
            label_name=label_name,
            features_name=self.features_feature.name,
            y=y[train_idx], base_weights=base_w[train_idx],
            eval_fn=self._metric, metric_name=self.validation_metric,
            larger_better=self.larger_better)
        best_name, best_params, *_ = candidates[best_i]
        self.best_estimator = (best_name, best_params, results)
        # introspectable record of the fold-refit validation (survives the
        # consume-on-fit of best_estimator)
        self.metadata["workflow_cv_results"] = [r.to_json() for r in results]
        return best_name, best_params

    def find_best_estimator_prefold(self, per_fold, y=None,
                                    n_rows: int = 0
                                    ) -> Tuple[str, Dict[str, Any]]:
        """Workflow-level CV over PRE-BUILT fold matrices — the streaming
        path's ``find_best_estimator`` (workflow/streaming_cv.py builds
        the matrices from merged fold-tagged monoid states).  Same
        contract: the winner is remembered so the subsequent ``fit``
        skips validation; the fold-validated results land in
        ``metadata["workflow_cv_results"]``.

        Unlike the in-core DAG variant this one runs through the full
        sweep machinery: ``parallel=``/mesh resolution, the mid-sweep
        checkpoint cursor (``with_sweep_checkpoint`` — a SIGKILLed CV
        sweep resumes at its unit cursor, on whatever mesh the resuming
        process has), and the elastic device-loss ladder with its
        counters in ``metadata["workflow_cv_elastic"]``.
        """
        if y is not None:
            self._capture_class_space(np.asarray(y, np.float32))
        n_cols = int(per_fold[0][0].shape[1]) if per_fold else 0
        queue_width = sum(len(g) for _, g in self.models_and_params)
        prev_mesh = self.mesh
        self.mesh = self._resolve_parallel(n_rows, n_cols, queue_width)
        try:
            elastic = self._elastic_context(n_rows, n_cols, queue_width)
            # per-fold matrices differ per context, so family grid
            # groups (which batch over ONE shared matrix) don't apply
            candidates = self._candidates(with_groups=False)
            ckpt = self._sweep_checkpoint(candidates, n_rows,
                                          elastic=elastic)
            best_i, results = self.validator.validate_prefold(
                candidates, per_fold, eval_fn=self._metric,
                metric_name=self.validation_metric,
                larger_better=self.larger_better,
                checkpoint=ckpt, elastic=elastic)
            if ckpt is not None:
                ckpt.finish()
            self.metadata["workflow_cv_elastic"] = (
                elastic.counters.to_json())
        finally:
            self._drain_tree_prefetch()
            self.mesh = prev_mesh
        best_name, best_params, *_ = candidates[best_i]
        self.best_estimator = (best_name, best_params, results)
        self.metadata["workflow_cv_results"] = [r.to_json() for r in results]
        return best_name, best_params

    # -- fit -----------------------------------------------------------------

    def _grid_has_linear(self) -> bool:
        """True when a candidate will consume the full-precision device
        matrix (the binary-LR / linear-regression device fit paths)."""
        from ..models.classification import OpLogisticRegression
        from ..models.regression import OpLinearRegression

        if self.problem_type == "binary":
            return any(isinstance(p, OpLogisticRegression)
                       for p, _ in self.models_and_params)
        if self.problem_type == "regression":
            return any(isinstance(p, OpLinearRegression)
                       for p, _ in self.models_and_params)
        return False

    def _prepare_matrix(self, values) -> np.ndarray:
        """One C-contiguous f32 matrix for the whole sweep (every candidate
        probes the upload/binning memos with this same object), plus the
        shared device upload up front when a linear-family candidate will
        consume the full matrix — tree candidates then quantize on device
        from it instead of a host binning pass.  Large matrices upload as
        bf16 (see ``trees._dev_f32``; TMOG_MATRIX_PRECISION=f32 forces
        exact uploads at ~2x the tunnel cost).

        A mesh-sharded ``jax.Array`` (the streaming→sharded ingest
        hand-off, ``parallel.ingest``) is kept device-resident when a
        mesh sweep will consume it; single-chip fits pull it to host."""
        import jax

        from ..models.trees import _as_f32, _dev_f32

        if isinstance(values, jax.Array) and not isinstance(values,
                                                            np.ndarray):
            if self.mesh is not None:
                return values             # committed row-sharded already
            values = np.asarray(values)
        X = _as_f32(np.asarray(values))
        if self.mesh is None and self._grid_has_linear() and X.size > (1 << 24):
            _dev_f32(X)
        return X

    #: below this element count prefetching the tree prep in a thread buys
    #: nothing (the sketch is sub-second)
    _PREFETCH_MIN_ELEMS = 1 << 24

    def _start_tree_prep_prefetch(self, X: np.ndarray):
        """Overlap the host quantile sketch / binning with the sweep's
        queued device work (VERDICT r3 Missing #5): the linear groups
        dispatch async and only sync at the stacked metric fetch, so a
        daemon thread can run the tree families' ~seconds of host prep in
        that shadow.  The memo's in-flight dedup (trees._memo) hands the
        result to the tree group — or blocks it until ready — so there is
        no duplicated sketch work."""
        import threading
        import time as _time

        from ..models.trees import _prep_tree_inputs_sparse

        if self.mesh is not None or X.size < self._PREFETCH_MIN_ELEMS:
            return None
        bins = sorted({int(getattr(p, "max_bins", 0))
                       for p, _ in self.models_and_params
                       if getattr(p, "max_bins", None)})
        if not bins:
            return None

        from ..utils.profiling import current_collector
        coll = current_collector()   # collector is thread-local: capture now
        cancel = threading.Event()

        def work():
            t0 = _time.perf_counter()
            for mb in bins:
                if cancel.is_set():   # elastic teardown: stop between bins
                    return
                try:
                    _prep_tree_inputs_sparse(X, mb)
                except Exception:   # prep errors surface on the sweep path
                    return
            if coll is not None:
                coll.metrics.custom_tags["prefetchTreePrepSecs"] = round(
                    _time.perf_counter() - t0, 3)

        t = threading.Thread(target=work, name="tree-prep-prefetch",
                             daemon=True)
        # retained so the elastic teardown / end-of-fit paths can join it:
        # a daemon prep thread must never outlive a shrunk mesh (its
        # device work would land on dead devices) or the fit itself
        self._prep_thread = t
        self._prep_cancel = cancel
        t.start()
        return t

    def _drain_tree_prefetch(self, timeout_s: float = 30.0) -> None:
        """Cancel + join the tree-prep prefetch thread (no-op when none
        is running).  Called from the elastic shrink hook BEFORE the mesh
        is re-pointed and from the fit's teardown, so no daemon prep work
        outlives the sweep that started it.  The join wait is booked into
        the transfer ledger (``tree_prefetch.join`` drain) — it used to
        disappear into fit wall, making prefetch stalls unattributable."""
        t = getattr(self, "_prep_thread", None)
        if t is None:
            return
        cancel = getattr(self, "_prep_cancel", None)
        if cancel is not None:
            cancel.set()
        if t.is_alive():
            import time as _time

            from ..utils.profiling import count_drain

            t0 = _time.perf_counter()
            t.join(timeout_s)
            count_drain(_time.perf_counter() - t0,
                        tag="tree_prefetch.join")
        self._prep_thread = None
        self._prep_cancel = None

    def fit_columns(self, data: ColumnarDataset, label_col: FeatureColumn,
                    features_col: FeatureColumn):
        # cost-model bucket refinement (workflow/plan.py reads it): a
        # halving sweep's wall follows a different law than a full sweep's
        self._cost_kind = ("fit-halving" if self.strategy == "halving"
                           else None)
        X = self._prepare_matrix(features_col.values)
        y = np.nan_to_num(np.asarray(label_col.values, dtype=np.float32))
        n = len(y)
        self._capture_class_space(y)
        splitter = self._resolved_splitter()
        train_idx, holdout_idx = splitter.split_indices(n, y)
        train_mask = np.zeros(n, dtype=bool)
        train_mask[train_idx] = True
        base_w = splitter.train_weights(y, train_mask)

        # ``parallel=`` dispatch: resolve an int/"auto" request into a
        # ("data", "grid") sweep mesh for THIS fit only (with_mesh wins,
        # and the attribute is restored on the way out — the same scoping
        # contract the workflow applies to with_mesh)
        queue_width = sum(len(g) for _, g in self.models_and_params)
        prev_mesh = self.mesh
        self.mesh = self._resolve_parallel(n, int(X.shape[1]), queue_width)
        try:
            return self._fit_columns_inner(
                X, y, n, splitter, train_mask, holdout_idx, base_w)
        finally:
            # join the tree-prep prefetch daemon whether the sweep
            # finished or aborted (device loss, checkpoint mismatch,
            # every-candidate failure): no prep work may outlive the fit
            self._drain_tree_prefetch()
            self.mesh = prev_mesh

    def _fit_columns_inner(self, X, y, n, splitter, train_mask,
                           holdout_idx, base_w):
        # a mesh-padded device matrix (the streaming→sharded ingest
        # hand-off) carries pad rows: labels/weights pad with ZEROS so the
        # pad rows are inert through every weighted fit and metric
        n_x = int(X.shape[0])
        if n_x != n:
            y_v = np.pad(y, (0, n_x - n))
            base_w_v = np.pad(base_w, (0, n_x - n))
        else:
            y_v, base_w_v = y, base_w

        # elastic execution context for this fit: device-loss recovery
        # (shrink + bounded retry + quarantine) always armed, watchdog
        # per configuration.  The counters land in metadata["elastic"]
        # whether or not anything fired, so the numbers are always there
        # to read (and always zero on a healthy sweep).
        queue_width = sum(len(g) for _, g in self.models_and_params)
        elastic = self._elastic_context(n, int(X.shape[1]), queue_width)

        best_group = None
        if self.best_estimator is not None:
            # consume the workflow-CV winner: a later fit on new data must
            # validate afresh, not reuse a stale selection
            best_name, best_params, results = self.best_estimator
            self.best_estimator = None
        elif self.strategy == "halving":
            # successive halving (tuning/halving.py): early rungs rank
            # candidates on stratified row subsamples + scaled rounds,
            # only survivors pay full-data fits.  No WHOLE-grid groups (a
            # group batches its whole family — eliminated candidates
            # would still be paid for): on a sweep mesh each rung's
            # survivors re-batch onto the grid axis via the regroup
            # callback instead.  No tree-prep prefetch (sized for the
            # full matrix, not the rungs).
            from ..tuning.halving import halving_validate

            candidates = self._candidates(with_groups=False)
            ckpt = self._sweep_checkpoint(candidates, n, elastic=elastic)
            best_i, results, schedule = halving_validate(
                self.validator, candidates, X, y_v, base_w_v,
                eval_fn=self._metric, metric_name=self.validation_metric,
                larger_better=self.larger_better, config=self.halving,
                stratify=self.problem_type != "regression",
                checkpoint=ckpt,
                regroup=self._make_rung_regroup(candidates),
                elastic=elastic)
            if ckpt is not None:
                ckpt.finish()
            self.metadata["halving_schedule"] = schedule
            best_name, best_params, *_ = candidates[best_i]
        else:
            # host tree-prep (sketch/binning/CSR) overlaps the linear
            # groups' async device work in a daemon thread
            self._start_tree_prep_prefetch(X)
            candidates = self._candidates()
            ckpt = self._sweep_checkpoint(candidates, n, elastic=elastic)
            best_i, results = self.validator.validate(
                candidates, X, y_v, base_w_v,
                eval_fn=self._metric, metric_name=self.validation_metric,
                larger_better=self.larger_better, checkpoint=ckpt,
                elastic=elastic)
            if ckpt is not None:
                ckpt.finish()
            best_name, best_params, *rest = candidates[best_i]
            best_group = rest[1] if len(rest) > 1 else None
        self.metadata["elastic"] = elastic.counters.to_json()

        # refit best on the full training split (ModelSelector.fit :180).
        # Grid groups that solved an appended full-train weight row hold the
        # winner's refit model already (refit_model) — sweep artifacts are
        # reused instead of paying a fresh sequential fit (the reference
        # refits from scratch, ModelSelector.scala:145-209).  Known
        # divergence (ADVICE r4, intentional): for the LINEAR groups the
        # deployed coefficients come from the batched majorization/prox
        # solver's full-train row, which agrees with a sequential
        # Newton/IRLS refit to METRIC level (~2e-3 AuPR; parity-tested in
        # test_lr_group_refit_matches_sequential) but not per-coefficient —
        # tighten the solver tol if exact reference refit-from-scratch
        # coefficient parity is ever required.  Fallback: a
        # sequential fit at the winner's OWN depth (family hints live in
        # the fitters; nothing outside the winner's family shares its
        # growth program).
        best_model = None
        if best_group is not None and not elastic.groups_invalid:
            # (a mid-sweep mesh shrink invalidates group refit artifacts —
            # their device arrays target the dead mesh; refit sequentially)
            try:
                row = best_group.grid_points.index(best_params)
            except ValueError:
                row = None
            if row is not None:
                best_model = best_group.refit_model(row)
        if best_model is None:
            best_proto = next(p for p, _ in self.models_and_params
                              if type(p).__name__ == best_name)
            best_est = best_proto.copy(**best_params)
            if self.mesh is not None and hasattr(best_est, "with_mesh"):
                best_est.with_mesh(self.mesh)
            best_model = best_est.fit_raw(X, y_v, base_w_v)

        # ONE batched predict over the full matrix (hits the sweep's binning
        # and upload memos) — slicing rows first would re-bin and re-upload
        # a fresh holdout matrix per metric set
        full_batch = best_model.predict_batch(X)
        train_metrics = self._full_metrics(full_batch, y, train_mask)
        holdout_metrics = (
            self._full_metrics(full_batch, y, ~train_mask)
            if len(holdout_idx) else {})

        summary = ModelSelectorSummary(
            validation_results=results, best_model_name=best_name,
            best_params=best_params,
            validation_type=type(self.validator).__name__,
            holdout_metrics=holdout_metrics, train_metrics=train_metrics,
            splitter_summary=(splitter.summary.to_json()
                              if splitter.summary else None),
            problem_type=self.problem_type)
        self.metadata["model_selector_summary"] = summary.to_json()
        selected = SelectedModel(inner=best_model, best_name=best_name,
                                 best_params=best_params)
        return selected

    def _full_metrics(self, full_batch: PredictionBatch, y,
                      mask: np.ndarray) -> Dict[str, float]:
        """Metrics over the masked rows of a full-matrix prediction batch."""
        idx = np.where(mask)[0]
        if not len(idx):
            return {}
        yy = y[idx]
        if self.problem_type == "binary":
            score = (np.asarray(full_batch.probability)[idx, 1]
                     if full_batch.probability is not None
                     else np.asarray(full_batch.prediction)[idx])
            return binary_classification_metrics(yy, score)
        if self.problem_type == "multiclass":
            pred = np.asarray(full_batch.prediction)[idx].astype(int)
            n_classes = self._class_count(yy, pred)
            out = multiclass_metrics(yy.astype(int), pred, n_classes)
            out.pop("confusion", None)
            return out
        return regression_metrics(yy, np.asarray(full_batch.prediction)[idx])


class SelectedModel(PredictorModel):
    """The winning fitted model (SelectedModel parity)."""

    def __init__(self, inner: PredictorModel, best_name: str = "",
                 best_params: Optional[Dict[str, Any]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="modelSelector", uid=uid)
        self.inner = inner
        self.best_name = best_name
        self.best_params = best_params or {}

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        return self.inner.predict_batch(X)

    def aot_scoring_spec(self):
        return self.inner.aot_scoring_spec()


# ---------------------------------------------------------------------------
# Factories with default model grids
# ---------------------------------------------------------------------------

def _binary_defaults() -> List[Tuple[PredictorEstimator, List[Dict[str, Any]]]]:
    """Default binary models: LR + RF (+ GBT/XGB-equivalent when enabled)
    (BinaryClassificationModelSelector.scala:54-108)."""
    from ..models.classification import OpLogisticRegression
    from ..models.trees import OpGBTClassifier, OpRandomForestClassifier

    D = DefaultSelectorParams
    return [
        (OpLogisticRegression(), grid(
            reg_param=D.REGULARIZATION, elastic_net_param=D.ELASTIC_NET,
            max_iter=D.MAX_ITER_LIN)),
        (OpRandomForestClassifier(), grid(
            max_depth=D.MAX_DEPTH, min_instances_per_node=D.MIN_INSTANCES_PER_NODE,
            min_info_gain=D.MIN_INFO_GAIN, num_trees=D.MAX_TREES)),
    ]


def _multiclass_defaults():
    from ..models.classification import OpLogisticRegression
    from ..models.trees import OpRandomForestClassifier

    D = DefaultSelectorParams
    return [
        (OpLogisticRegression(), grid(
            reg_param=D.REGULARIZATION, elastic_net_param=D.ELASTIC_NET,
            max_iter=D.MAX_ITER_LIN)),
        (OpRandomForestClassifier(), grid(
            max_depth=D.MAX_DEPTH, min_instances_per_node=D.MIN_INSTANCES_PER_NODE,
            min_info_gain=D.MIN_INFO_GAIN, num_trees=D.MAX_TREES)),
    ]


def _regression_defaults():
    from ..models.regression import OpLinearRegression
    from ..models.trees import OpGBTRegressor, OpRandomForestRegressor

    D = DefaultSelectorParams
    return [
        (OpLinearRegression(), grid(
            reg_param=D.REGULARIZATION, elastic_net_param=D.ELASTIC_NET,
            max_iter=[200])),
        (OpRandomForestRegressor(), grid(
            max_depth=D.MAX_DEPTH, min_instances_per_node=D.MIN_INSTANCES_PER_NODE,
            min_info_gain=D.MIN_INFO_GAIN, num_trees=D.MAX_TREES)),
        (OpGBTRegressor(), grid(
            max_depth=D.MAX_DEPTH, max_iter=D.MAX_ITER_TREE,
            step_size=D.STEP_SIZE)),
    ]


class BinaryClassificationModelSelector:
    @staticmethod
    def with_cross_validation(
        num_folds: int = 3, validation_metric: str = "AuPR",
        splitter=None, seed: int = 42,
        models_and_parameters=None, parallelism: int = 8,
        max_wait: Optional[float] = None,
        strategy: str = "full", halving=None, parallel=None,
        watchdog: Optional[float] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_params=models_and_parameters or _binary_defaults(),
            problem_type="binary",
            validator=OpCrossValidation(num_folds=num_folds, seed=seed,
                                        stratify=True,
                                        parallelism=parallelism,
                                        max_wait=max_wait),
            splitter=splitter if splitter is not None else DataBalancer(seed=seed),
            validation_metric=validation_metric,
            strategy=strategy, halving=halving, parallel=parallel,
            watchdog=watchdog)

    @staticmethod
    def with_train_validation_split(
        train_ratio: float = 0.75, validation_metric: str = "AuPR",
        splitter=None, seed: int = 42, models_and_parameters=None,
        parallelism: int = 8,
        max_wait: Optional[float] = None,
        strategy: str = "full", halving=None, parallel=None,
        watchdog: Optional[float] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_params=models_and_parameters or _binary_defaults(),
            problem_type="binary",
            validator=OpTrainValidationSplit(train_ratio=train_ratio,
                                             seed=seed, stratify=True,
                                             parallelism=parallelism,
                                             max_wait=max_wait),
            splitter=splitter if splitter is not None else DataBalancer(seed=seed),
            validation_metric=validation_metric,
            strategy=strategy, halving=halving, parallel=parallel,
            watchdog=watchdog)


class MultiClassificationModelSelector:
    @staticmethod
    def with_cross_validation(
        num_folds: int = 3, validation_metric: str = "F1",
        splitter=None, seed: int = 42, models_and_parameters=None,
        parallelism: int = 8,
        max_wait: Optional[float] = None,
        strategy: str = "full", halving=None, parallel=None,
        watchdog: Optional[float] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_params=models_and_parameters or _multiclass_defaults(),
            problem_type="multiclass",
            validator=OpCrossValidation(num_folds=num_folds, seed=seed,
                                        stratify=True,
                                        parallelism=parallelism,
                                        max_wait=max_wait),
            splitter=splitter if splitter is not None else DataCutter(seed=seed),
            validation_metric=validation_metric,
            strategy=strategy, halving=halving, parallel=parallel,
            watchdog=watchdog)

    @staticmethod
    def with_train_validation_split(
        train_ratio: float = 0.75, validation_metric: str = "F1",
        splitter=None, seed: int = 42, models_and_parameters=None,
        parallelism: int = 8,
        max_wait: Optional[float] = None,
        strategy: str = "full", halving=None, parallel=None,
        watchdog: Optional[float] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_params=models_and_parameters or _multiclass_defaults(),
            problem_type="multiclass",
            validator=OpTrainValidationSplit(train_ratio=train_ratio,
                                             seed=seed, stratify=True,
                                             parallelism=parallelism,
                                             max_wait=max_wait),
            splitter=splitter if splitter is not None else DataCutter(seed=seed),
            validation_metric=validation_metric,
            strategy=strategy, halving=halving, parallel=parallel,
            watchdog=watchdog)


class RegressionModelSelector:
    @staticmethod
    def with_cross_validation(
        num_folds: int = 3, validation_metric: str = "RootMeanSquaredError",
        splitter=None, seed: int = 42, models_and_parameters=None,
        parallelism: int = 8,
        max_wait: Optional[float] = None,
        strategy: str = "full", halving=None, parallel=None,
        watchdog: Optional[float] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_params=models_and_parameters or _regression_defaults(),
            problem_type="regression",
            validator=OpCrossValidation(num_folds=num_folds, seed=seed,
                                        parallelism=parallelism,
                                        max_wait=max_wait),
            splitter=splitter if splitter is not None else DataSplitter(seed=seed),
            validation_metric=validation_metric,
            strategy=strategy, halving=halving, parallel=parallel,
            watchdog=watchdog)

    @staticmethod
    def with_train_validation_split(
        train_ratio: float = 0.75,
        validation_metric: str = "RootMeanSquaredError",
        splitter=None, seed: int = 42, models_and_parameters=None,
        parallelism: int = 8,
        max_wait: Optional[float] = None,
        strategy: str = "full", halving=None, parallel=None,
        watchdog: Optional[float] = None,
    ) -> ModelSelector:
        return ModelSelector(
            models_and_params=models_and_parameters or _regression_defaults(),
            problem_type="regression",
            validator=OpTrainValidationSplit(train_ratio=train_ratio,
                                             seed=seed,
                                             parallelism=parallelism,
                                             max_wait=max_wait),
            splitter=splitter if splitter is not None else DataSplitter(seed=seed),
            validation_metric=validation_metric,
            strategy=strategy, halving=halving, parallel=parallel,
            watchdog=watchdog)


class RandomParamBuilder:
    """Random-search grids (RandomParamBuilder.scala:52,169)."""

    def __init__(self, seed: int = 42):
        self._rng = np.random.default_rng(seed)
        self._axes: Dict[str, Callable[[], Any]] = {}

    def uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        self._axes[name] = lambda: float(self._rng.uniform(lo, hi))
        return self

    def log_uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        self._axes[name] = lambda: float(np.exp(
            self._rng.uniform(np.log(lo), np.log(hi))))
        return self

    def choice(self, name: str, options: Sequence[Any]) -> "RandomParamBuilder":
        opts = list(options)
        self._axes[name] = lambda: opts[int(self._rng.integers(len(opts)))]
        return self

    def build(self, n: int) -> List[Dict[str, Any]]:
        return [{k: fn() for k, fn in self._axes.items()} for _ in range(n)]
