"""Data splitters — holdout, class rebalancing, rare-label cutting.

Reference: ``Splitter``/``DataSplitter``/``DataBalancer``/``DataCutter``
(core/.../impl/tuning/Splitter.scala, DataBalancer.scala:73,208-320,
DataCutter.scala:78,200), each persisting a ``*Summary``.

TPU design note: DataBalancer expresses up/down-sampling as *sample weights*
over the resident feature matrix instead of materializing resampled copies —
shapes stay static, HBM stays put, and the trainers all accept weights.  A
``materialize`` escape hatch reproduces the reference's literal resampling.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["SplitterSummary", "DataSplitter", "DataBalancer", "DataCutter"]


@dataclasses.dataclass
class SplitterSummary:
    splitter: str
    details: Dict

    def to_json(self):
        return {"splitter": self.splitter, **self.details}


class DataSplitter:
    """Random train/holdout split (DataSplitter parity)."""

    def __init__(self, reserve_test_fraction: float = 0.1, seed: int = 42):
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed
        self.summary: Optional[SplitterSummary] = None

    def split_indices(self, n: int, y: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        holdout = rng.random(n) < self.reserve_test_fraction
        self.summary = SplitterSummary("DataSplitter", {
            "reserveTestFraction": self.reserve_test_fraction,
            "trainCount": int((~holdout).sum()),
            "testCount": int(holdout.sum()),
        })
        return np.where(~holdout)[0], np.where(holdout)[0]

    def train_weights(self, y: np.ndarray, train_mask: np.ndarray) -> np.ndarray:
        return train_mask.astype(np.float32)


class DataBalancer(DataSplitter):
    """Binary-class rebalance toward ``sample_fraction`` positives
    (DataBalancer.scala:73): implemented as per-class sample weights."""

    def __init__(self, sample_fraction: float = 0.1, max_training_sample: int = 1_000_000,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample

    def train_weights(self, y: np.ndarray, train_mask: np.ndarray) -> np.ndarray:
        w = train_mask.astype(np.float32).copy()
        yt = y[train_mask.astype(bool)]
        n = len(yt)
        pos = float((yt == 1).sum())
        neg = float(n - pos)
        if n == 0 or pos == 0 or neg == 0:
            return w
        frac = pos / n
        target = self.sample_fraction
        details = {"positiveCount": pos, "negativeCount": neg,
                   "desiredFraction": target, "originalFraction": frac}
        minority_is_pos = pos <= neg
        minority_frac = frac if minority_is_pos else 1.0 - frac
        if minority_frac < target:
            # up-weight the minority class so its weighted fraction hits the
            # target (weight-space analogue of DataBalancer's up-sampling);
            # an already-balanced dataset is left untouched, matching the
            # reference's "already balanced" no-op path (DataBalancer.scala:208)
            mcount, ocount = ((pos, neg) if minority_is_pos else (neg, pos))
            scale = target * ocount / ((1.0 - target) * mcount)
            cls = 1 if minority_is_pos else 0
            w[(y == cls) & train_mask.astype(bool)] *= scale
            details["upSamplingFraction"] = scale
        else:
            details["alreadyBalanced"] = True
        self.summary = SplitterSummary("DataBalancer", details)
        return w


class DataCutter(DataSplitter):
    """Multiclass rare-label dropping (DataCutter.scala:78): labels kept if
    above ``min_label_fraction`` and within ``max_label_categories``."""

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction
        self.labels_kept: Optional[np.ndarray] = None

    def train_weights(self, y: np.ndarray, train_mask: np.ndarray) -> np.ndarray:
        w = train_mask.astype(np.float32).copy()
        yt = y[train_mask.astype(bool)]
        labels, counts = np.unique(yt, return_counts=True)
        frac = counts / max(len(yt), 1)
        order = np.argsort(-counts)
        keep = []
        for i in order[: self.max_label_categories]:
            if frac[i] >= self.min_label_fraction:
                keep.append(labels[i])
        self.labels_kept = np.asarray(sorted(keep))
        dropped = [float(l) for l in labels if l not in set(keep)]
        w[~np.isin(y, self.labels_kept)] = 0.0
        self.summary = SplitterSummary("DataCutter", {
            "labelsKept": [float(l) for l in self.labels_kept],
            "labelsDropped": dropped,
            "minLabelFraction": self.min_label_fraction,
        })
        return w
