"""Hyperparameter validators — cross-validation and train/validation split.

Reference: ``OpValidator`` (impl/tuning/OpValidator.scala:94,214,363),
``OpCrossValidation`` (OpCrossValidation.scala:87-148, stratified folds
:158-200), ``OpTrainValidationSplit``.

TPU redesign of the reference's folds×models JVM thread pool: every fold is a
0/1 *weight mask* over the single device-resident matrix (no per-fold copies),
so one XLA-compiled trainer program serves all folds × all hyperparameter
points; candidates with identical structure are additionally batched with
``vmap`` (grid axis) by trainers that support it (SURVEY §2.12 row 2).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ValidationResult", "OpCrossValidation", "OpTrainValidationSplit",
           "make_folds"]


@dataclasses.dataclass
class ValidationResult:
    model_name: str
    params: Dict[str, Any]
    metric_name: str
    metric_value: float
    fold_values: List[float]

    def to_json(self):
        return {"modelType": self.model_name, "params": self.params,
                "metricName": self.metric_name,
                "metricValue": self.metric_value,
                "foldValues": self.fold_values}


def make_folds(n: int, num_folds: int, y: Optional[np.ndarray] = None,
               stratify: bool = False, seed: int = 42) -> np.ndarray:
    """Fold id per row; stratified assignment keeps label ratios per fold
    (OpCrossValidation stratified folds :158-200)."""
    rng = np.random.default_rng(seed)
    fold = np.zeros(n, dtype=np.int32)
    if stratify and y is not None:
        for lbl in np.unique(y):
            idx = np.where(y == lbl)[0]
            perm = rng.permutation(len(idx))
            fold[idx[perm]] = np.arange(len(idx)) % num_folds
    else:
        perm = rng.permutation(n)
        fold[perm] = np.arange(n) % num_folds
    return fold


class _ValidatorBase:
    """fit_fn(X, y, w_train, params) -> predict_fn(X) -> scores;
    eval_fn(y, scores, w_eval) -> float metric."""

    larger_better: bool = True

    def validate(
        self,
        candidates: Sequence[Tuple[str, Dict[str, Any],
                                   Callable[..., Callable]]],
        X: np.ndarray,
        y: np.ndarray,
        base_weights: np.ndarray,
        eval_fn: Callable[[np.ndarray, Any, np.ndarray], float],
        metric_name: str,
        larger_better: bool = True,
    ) -> Tuple[int, List[ValidationResult]]:
        raise NotImplementedError


class OpCrossValidation(_ValidatorBase):
    def __init__(self, num_folds: int = 3, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8):
        self.num_folds = num_folds
        self.seed = seed
        self.stratify = stratify
        # parallelism is accepted for API parity; on TPU the folds×grid loop
        # runs as sequential launches of one cached compiled program (or
        # vmapped where the trainer supports it) — no thread pool needed.
        self.parallelism = parallelism

    def validate(self, candidates, X, y, base_weights, eval_fn, metric_name,
                 larger_better=True):
        n = X.shape[0]
        folds = make_folds(n, self.num_folds, y=y, stratify=self.stratify,
                           seed=self.seed)
        results: List[ValidationResult] = []
        for name, params, fitter in candidates:
            fold_vals: List[float] = []
            for k in range(self.num_folds):
                w_train = base_weights * (folds != k)
                w_eval = base_weights * (folds == k)
                if w_train.sum() == 0 or w_eval.sum() == 0:
                    continue
                predict = fitter(X, y, w_train, params)
                scores = predict(X)
                fold_vals.append(float(eval_fn(y, scores, w_eval)))
            mean = float(np.mean(fold_vals)) if fold_vals else float("-inf")
            results.append(ValidationResult(name, params, metric_name, mean,
                                            fold_vals))
        best = _argbest([r.metric_value for r in results], larger_better)
        return best, results


class OpTrainValidationSplit(_ValidatorBase):
    def __init__(self, train_ratio: float = 0.75, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8):
        self.train_ratio = train_ratio
        self.seed = seed
        self.stratify = stratify
        self.parallelism = parallelism

    def validate(self, candidates, X, y, base_weights, eval_fn, metric_name,
                 larger_better=True):
        n = X.shape[0]
        rng = np.random.default_rng(self.seed)
        if self.stratify:
            # per-class permutation keeps label ratios on both sides, so an
            # imbalanced eval slice can't end up without positives
            in_train = np.zeros(n, bool)
            for cls in np.unique(y[np.isfinite(y)]):
                idx = np.where(y == cls)[0]
                perm = rng.permutation(idx)
                in_train[perm[: max(1, int(round(
                    len(idx) * self.train_ratio)))]] = True
        else:
            in_train = rng.random(n) < self.train_ratio
        results: List[ValidationResult] = []
        for name, params, fitter in candidates:
            w_train = base_weights * in_train
            w_eval = base_weights * (~in_train)
            predict = fitter(X, y, w_train, params)
            scores = predict(X)
            val = float(eval_fn(y, scores, w_eval))
            results.append(ValidationResult(name, params, metric_name, val,
                                            [val]))
        best = _argbest([r.metric_value for r in results], larger_better)
        return best, results


def _argbest(vals: List[float], larger_better: bool) -> int:
    arr = np.asarray(vals, np.float64)
    if not larger_better:
        arr = -arr
    arr = np.where(np.isnan(arr), -np.inf, arr)
    return int(np.argmax(arr))
