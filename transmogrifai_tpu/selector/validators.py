"""Hyperparameter validators — cross-validation and train/validation split.

Reference: ``OpValidator`` (impl/tuning/OpValidator.scala:94,214,363),
``OpCrossValidation`` (OpCrossValidation.scala:87-148, stratified folds
:158-200), ``OpTrainValidationSplit``.

TPU redesign of the reference's folds×models JVM thread pool: every fold is a
0/1 *weight mask* over the single device-resident matrix (no per-fold copies),
so one XLA-compiled trainer program serves all folds × all hyperparameter
points; runs of same-family candidates additionally fit as ONE batched
program over the (folds, candidates) grid via ``selector.grid_groups``
(LR majorization grid, RF tree streams, GBT lockstep chains — SURVEY
§2.12 row 2), with transparent per-candidate fallback.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ValidationResult", "OpCrossValidation", "OpTrainValidationSplit",
           "make_folds", "SweepUnit", "SweepWorkQueue"]


@dataclasses.dataclass
class ValidationResult:
    model_name: str
    params: Dict[str, Any]
    metric_name: str
    metric_value: float
    fold_values: List[float]
    #: fit/eval failure or budget-skip note; a failed candidate scores -inf
    #: instead of aborting the sweep (OpValidator.scala:94-214 isolates
    #: candidates in Futures bounded by maxWait)
    error: Optional[str] = None

    def to_json(self):
        out = {"modelType": self.model_name, "params": self.params,
               "metricName": self.metric_name,
               "metricValue": self.metric_value,
               "foldValues": self.fold_values}
        if self.error is not None:
            out["error"] = self.error
        return out

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ValidationResult":
        """Inverse of ``to_json`` (sweep checkpoint resume)."""
        return ValidationResult(
            model_name=str(d.get("modelType", "")),
            params=dict(d.get("params", {})),
            metric_name=str(d.get("metricName", "")),
            metric_value=float(d.get("metricValue", float("-inf"))),
            fold_values=list(d.get("foldValues", [])),
            error=d.get("error"))


def make_folds(n: int, num_folds: int, y: Optional[np.ndarray] = None,
               stratify: bool = False, seed: int = 42) -> np.ndarray:
    """Fold id per row; stratified assignment keeps label ratios per fold
    (OpCrossValidation stratified folds :158-200)."""
    rng = np.random.default_rng(seed)
    fold = np.zeros(n, dtype=np.int32)
    if stratify and y is not None:
        for lbl in np.unique(y):
            idx = np.where(y == lbl)[0]
            perm = rng.permutation(len(idx))
            fold[idx[perm]] = np.arange(len(idx)) % num_folds
    else:
        perm = rng.permutation(n)
        fold[perm] = np.arange(n) % num_folds
    return fold


class _ValidatorBase:
    """fit_fn(X, y, w_train, params) -> predict_fn(X) -> scores;
    eval_fn(y, scores, w_eval) -> float metric."""

    larger_better: bool = True
    #: this validator's sweep runs through SweepWorkQueue and honors
    #: ``validate(..., defer=True)`` (raw deferred results instead of a
    #: collected ranking) — the halving scheduler checks this before
    #: deferring a rung's materialization to its on-device promotion
    supports_defer: bool = True

    def validate(
        self,
        candidates: Sequence[Tuple[str, Dict[str, Any],
                                   Callable[..., Callable]]],
        X: np.ndarray,
        y: np.ndarray,
        base_weights: np.ndarray,
        eval_fn: Callable[[np.ndarray, Any, np.ndarray], float],
        metric_name: str,
        larger_better: bool = True,
        checkpoint=None,
        elastic=None,
        defer: bool = False,
    ) -> Tuple[int, List[ValidationResult]]:
        raise NotImplementedError

    def validate_with_dag(
        self,
        candidates,
        data,
        during_dag,
        label_name: str,
        features_name: str,
        y: np.ndarray,
        base_weights: np.ndarray,
        eval_fn,
        metric_name: str,
        larger_better: bool = True,
    ) -> Tuple[int, List[ValidationResult]]:
        """Workflow-level CV (OpValidator.applyDAG OpValidator.scala:250):
        the feature-engineering ``during_dag`` is refit on every fold's train
        split and applied to its eval split, so label-aware estimators
        (SanityChecker, supervised bucketizers) cannot leak fold labels."""
        raise NotImplementedError

    def validate_prefold(
        self,
        candidates,
        per_fold: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray, np.ndarray, np.ndarray]],
        eval_fn,
        metric_name: str,
        larger_better: bool = True,
        checkpoint=None,
        elastic=None,
        defer: bool = False,
    ) -> Tuple[int, List[ValidationResult]]:
        """Validate candidates over PRE-BUILT fold matrices — each context
        a ``(X_tr, y_tr, w_tr, X_ev, y_ev, w_ev)`` tuple.  The streaming
        workflow-CV path (workflow/streaming_cv.py) builds these from
        merged fold-tagged monoid states instead of refitting the during
        DAG per fold; the candidate fits and metric extraction are
        byte-for-byte the ``validate_with_dag`` bodies, and the sweep
        runs through the same work queue (mid-sweep checkpoint cursor +
        elastic device-loss ladder both compose)."""

        def run_fold(fitter, params, ctx):
            X_tr, y_tr, w_tr, X_ev, y_ev, w_ev = ctx
            predict = fitter(X_tr, y_tr, w_tr, params)
            return eval_fn(y_ev, predict(X_ev), w_ev)

        return _run_sweep(candidates, list(per_fold), run_fold, metric_name,
                          larger_better, getattr(self, "max_wait", None),
                          checkpoint=checkpoint, elastic=elastic, defer=defer)

    @staticmethod
    def _fold_matrices(data, during_dag, label_name, features_name,
                       tr_idx: np.ndarray, ev_idx: np.ndarray):
        """Refit during_dag on the fold's train rows, apply to eval rows,
        and extract the (X, y) matrices for both sides.

        The keep-set names exactly what this function reads afterwards, so
        the DAG's memoized ExecutionPlan (derived once, reused by every
        fold — plan_for caches on the dag object) liveness-prunes all other
        intermediates per fold, and the eval side rides the lazy
        plan-driven ``apply_to`` pass.  The per-fold row gather is also
        plan-bounded: only columns the during-DAG actually reads are
        ``take``-copied, instead of fancy-indexing every raw/intermediate
        column (object columns cost ~µs/row to gather) twice per fold."""
        from ..workflow.dag import (fit_and_transform_dag,
                                    sequential_executor_forced)
        from ..workflow.plan import plan_for

        if sequential_executor_forced():
            # pre-plan behavior: gather every column, refit sequentially
            train_ds = data.take(tr_idx)
            eval_ds = data.take(ev_idx)
            _, train_t, eval_t = fit_and_transform_dag(
                during_dag, train_ds, apply_to=eval_ds, sequential=True)
        else:
            keep = [features_name, label_name]
            plan = plan_for(during_dag, keep=keep)
            req = plan.required_input_columns()
            base = data.select([n for n in data.names() if n in req])
            train_ds = base.take(tr_idx)
            eval_ds = base.take(ev_idx)
            _, train_t, eval_t = fit_and_transform_dag(
                during_dag, train_ds, apply_to=eval_ds, keep=keep)
        X_tr = np.ascontiguousarray(
            np.asarray(train_t[features_name].values, dtype=np.float32))
        X_ev = np.ascontiguousarray(
            np.asarray(eval_t[features_name].values, dtype=np.float32))
        y_tr = np.nan_to_num(
            np.asarray(train_t[label_name].values, dtype=np.float32))
        y_ev = np.nan_to_num(
            np.asarray(eval_t[label_name].values, dtype=np.float32))
        return X_tr, y_tr, X_ev, y_ev


class OpCrossValidation(_ValidatorBase):
    def __init__(self, num_folds: int = 3, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8,
                 max_wait: Optional[float] = None):
        self.num_folds = num_folds
        self.seed = seed
        self.stratify = stratify
        # parallelism is accepted for API parity; on TPU the folds×grid loop
        # runs as sequential launches of one cached compiled program (or
        # vmapped where the trainer supports it) — no thread pool needed.
        self.parallelism = parallelism
        # wall-clock sweep budget in seconds (reference maxWait,
        # OpValidator.scala:108): candidates not yet started when the budget
        # runs out are skipped with a recorded error instead of hanging the
        # train. None = unbounded.
        self.max_wait = max_wait

    def validate(self, candidates, X, y, base_weights, eval_fn, metric_name,
                 larger_better=True, checkpoint=None, elastic=None,
                 defer=False):
        n = X.shape[0]
        folds = make_folds(n, self.num_folds, y=y, stratify=self.stratify,
                           seed=self.seed)
        fold_ctxs = []
        for k in range(self.num_folds):
            w_train = base_weights * (folds != k)
            w_eval = base_weights * (folds == k)
            if w_train.sum() == 0 or w_eval.sum() == 0:
                continue
            fold_ctxs.append((w_train, w_eval))

        def run_fold(fitter, params, ctx):
            w_train, w_eval = ctx
            predict = fitter(X, y, w_train, params)
            return eval_fn(y, predict(X), w_eval)

        def run_group(group):
            return group.run(X, y, fold_ctxs)

        return _run_sweep(candidates, fold_ctxs, run_fold, metric_name,
                          larger_better, self.max_wait, run_group=run_group,
                          checkpoint=checkpoint, elastic=elastic, defer=defer)

    def validate_with_dag(self, candidates, data, during_dag, label_name,
                          features_name, y, base_weights, eval_fn,
                          metric_name, larger_better=True):
        n = len(y)
        folds = make_folds(n, self.num_folds, y=y, stratify=self.stratify,
                           seed=self.seed)
        # one DAG refit per fold, shared across every candidate (the
        # reference refits per fold too — OpCrossValidation.scala:87-148)
        per_fold = []
        for k in range(self.num_folds):
            tr_idx = np.where(folds != k)[0]
            ev_idx = np.where(folds == k)[0]
            if not len(tr_idx) or not len(ev_idx):
                continue
            X_tr, y_tr, X_ev, y_ev = self._fold_matrices(
                data, during_dag, label_name, features_name, tr_idx, ev_idx)
            w_tr = base_weights[tr_idx]
            w_ev = base_weights[ev_idx]
            if w_tr.sum() == 0 or w_ev.sum() == 0:
                continue
            per_fold.append((X_tr, y_tr, w_tr, X_ev, y_ev, w_ev))

        def run_fold(fitter, params, ctx):
            X_tr, y_tr, w_tr, X_ev, y_ev, w_ev = ctx
            predict = fitter(X_tr, y_tr, w_tr, params)
            return eval_fn(y_ev, predict(X_ev), w_ev)

        return _run_sweep(candidates, per_fold, run_fold, metric_name,
                          larger_better, self.max_wait)


class OpTrainValidationSplit(_ValidatorBase):
    def __init__(self, train_ratio: float = 0.75, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8,
                 max_wait: Optional[float] = None):
        self.train_ratio = train_ratio
        self.seed = seed
        self.stratify = stratify
        self.parallelism = parallelism
        self.max_wait = max_wait

    def _split_mask(self, n: int, y: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        if self.stratify:
            # per-class permutation keeps label ratios on both sides, so an
            # imbalanced eval slice can't end up without positives
            in_train = np.zeros(n, bool)
            for cls in np.unique(y[np.isfinite(y)]):
                idx = np.where(y == cls)[0]
                perm = rng.permutation(idx)
                in_train[perm[: max(1, int(round(
                    len(idx) * self.train_ratio)))]] = True
        else:
            in_train = rng.random(n) < self.train_ratio
        return in_train

    def validate(self, candidates, X, y, base_weights, eval_fn, metric_name,
                 larger_better=True, checkpoint=None, elastic=None,
                 defer=False):
        n = X.shape[0]
        in_train = self._split_mask(n, y)
        w_train = base_weights * in_train
        w_eval = base_weights * (~in_train)

        def run_fold(fitter, params, ctx):
            predict = fitter(X, y, w_train, params)
            return eval_fn(y, predict(X), w_eval)

        def run_group(group):
            return group.run(X, y, [(w_train, w_eval)])

        return _run_sweep(candidates, [None], run_fold, metric_name,
                          larger_better, self.max_wait, run_group=run_group,
                          checkpoint=checkpoint, elastic=elastic, defer=defer)

    def validate_with_dag(self, candidates, data, during_dag, label_name,
                          features_name, y, base_weights, eval_fn,
                          metric_name, larger_better=True):
        n = len(y)
        in_train = self._split_mask(n, y)
        tr_idx = np.where(in_train)[0]
        ev_idx = np.where(~in_train)[0]
        X_tr, y_tr, X_ev, y_ev = self._fold_matrices(
            data, during_dag, label_name, features_name, tr_idx, ev_idx)
        w_tr, w_ev = base_weights[tr_idx], base_weights[ev_idx]

        def run_fold(fitter, params, ctx):
            predict = fitter(X_tr, y_tr, w_tr, params)
            return eval_fn(y_ev, predict(X_ev), w_ev)

        return _run_sweep(candidates, [None], run_fold, metric_name,
                          larger_better, self.max_wait)


def _mesh_attr(elastic) -> str:
    """The mesh a sweep attempt runs on, as a span attribute ("" = single
    device / unknown) — read through the elastic context's live-mesh peek
    so shrink ladders show the mesh each RETRY actually landed on."""
    provider = getattr(elastic, "mesh_provider", None)
    if provider is None:
        return ""
    try:
        from ..utils.profiling import mesh_desc

        return mesh_desc(provider())[1]
    except Exception:
        return ""


@dataclasses.dataclass
class SweepUnit:
    """One schedulable unit of sweep work: a candidate's (folds x fit)
    execution.  ``fit_params`` lets a scheduler run the unit with
    different resources than the candidate's identity (successive-halving
    rung scaling, tuning/halving.py) — results always report ``params``.
    """

    index: int                   # position in the original candidate list
    name: str
    params: Dict[str, Any]
    fitter: Any
    group: Any = None            # shared GridGroup for batched device fits
    fit_params: Optional[Dict[str, Any]] = None

    @property
    def run_params(self) -> Dict[str, Any]:
        return self.fit_params if self.fit_params is not None else self.params


class SweepWorkQueue:
    """The selector sweep as an explicitly schedulable work queue.

    The candidates×folds loop used to be a closed ``while`` inside
    ``_run_sweep``; it is now a queue of :class:`SweepUnit` whose
    execution, failure isolation, ``max_wait`` budgeting and grid-group
    batching live HERE, while schedulers decide which units run — the
    default full sweep (``run_all``), successive halving
    (tuning/halving.py, which schedules rung-sized subsets through fresh
    queues), and the coming sharded-sweep scheduler (ROADMAP item 1) all
    drive the same unit semantics.

    Semantics (reference parity, OpValidator.scala:94-214): each unit's
    fits are isolated — an exception scores the unit worst and records the
    error; the wall-clock budget is checked before each dispatch (an
    already-dispatched XLA program cannot be interrupted, but the queue
    stops enqueuing); a run of consecutive units sharing a ``GridGroup``
    fits as ONE batched device program with transparent per-unit fallback.
    """

    def __init__(self, candidates, fold_ctxs, run_fold, run_group=None):
        self.units = [
            SweepUnit(i, c[0], c[1], c[2],
                      group=(c[3] if len(c) >= 4 else None),
                      fit_params=(c[4] if len(c) >= 5 else None))
            for i, c in enumerate(tuple(c) for c in candidates)]
        self.fold_ctxs = fold_ctxs
        self._run_fold = run_fold
        self._run_group = run_group

    # -- unit execution ------------------------------------------------------

    def _unit_attempt(self, unit: SweepUnit) -> List[Any]:
        """One execution attempt of a unit's (folds x fit) body.  The
        ``unit.slow`` / ``device.loss`` fault points fire here — once per
        ATTEMPT, keyed by the unit's queue index — so the elastic
        escalation ladder (retry on a shrunk mesh, then quarantine) is
        seed-deterministically testable."""
        from ..utils import faults

        faults.fire("unit.slow", index=unit.index, tag=unit.name)
        faults.fire("device.loss", index=unit.index, tag=unit.name)
        fold_vals: List[Any] = []
        for ctx in self.fold_ctxs:
            fold_vals.append(
                self._run_fold(unit.fitter, unit.run_params, ctx))
        return fold_vals

    def run_unit(self, unit: SweepUnit,
                 elastic=None) -> Tuple[List[Any], Optional[str]]:
        """One candidate across every fold context, failure-isolated.

        With an :class:`~transmogrifai_tpu.parallel.elastic.
        ElasticContext` attached, two degradation ladders wrap the
        attempt: classified DEVICE LOSSES re-run the unit (the context
        shrinks the owner's mesh between attempts, ultimately to the
        single-device path) within a bounded retry budget before
        quarantining the candidate as ``failed: device_loss``; and the
        opt-in STRAGGLER WATCHDOG bounds each attempt at the context's
        deadline (escalating timeout -> degraded re-run at 2x the
        deadline -> ``failed: straggler`` quarantine).  Workload failures
        keep the historical behavior: score worst, record the error."""
        from ..obs.trace import begin_span, end_span

        loss_attempt = 0
        slow_attempt = 0
        sp = begin_span(f"sweep.unit[{unit.index}]", cat="sweep",
                        candidate=unit.name, index=unit.index,
                        mesh=_mesh_attr(elastic))
        try:
            while True:
                try:
                    deadline = (elastic.unit_deadline_s
                                if elastic is not None else None)
                    if deadline is None:
                        return self._unit_attempt(unit), None
                    from ..parallel.elastic import run_with_deadline

                    fold_vals, timed_out = run_with_deadline(
                        lambda: self._unit_attempt(unit),
                        deadline * (2 ** slow_attempt),
                        abandoned=elastic.abandoned)
                    if not timed_out:
                        return fold_vals, None
                    if elastic.on_watchdog_timeout(unit.index,
                                                   slow_attempt):
                        slow_attempt += 1
                        continue   # degraded re-run on the shrunk mesh
                    return [], (f"failed: straggler (unit exceeded its "
                                f"{deadline:.3f}s watchdog deadline "
                                f"{slow_attempt + 1}x)")
                except Exception as e:  # noqa: BLE001 - candidate
                    # isolation, routed through the shared device-loss
                    # classifier
                    if elastic is not None and elastic.classify(e):
                        if elastic.on_device_loss(unit.index, e,
                                                  loss_attempt):
                            loss_attempt += 1
                            continue   # re-run on the shrunk mesh
                        return [], (f"failed: device_loss "
                                    f"({type(e).__name__}: {e})")
                    return [], f"{type(e).__name__}: {e}"
        finally:
            end_span(sp, retries=loss_attempt,
                     watchdog_retries=slow_attempt,
                     mesh_after=_mesh_attr(elastic))

    def group_span(self, i: int) -> int:
        """End index (exclusive) of the run of units sharing units[i]'s
        group."""
        group = self.units[i].group
        j = i
        while j < len(self.units) and self.units[j].group is group:
            j += 1
        return j

    def group_start(self, i: int) -> int:
        """Start index of the run of units sharing units[i]'s group — a
        checkpoint resume can enter a group MID-SPAN (earlier members
        restored from the cursor), and the group's metric-matrix rows are
        indexed from the group's first unit, not from the resume point."""
        group = self.units[i].group
        j = i
        while j > 0 and self.units[j - 1].group is group:
            j -= 1
        return j

    def run_group_block(self, i: int, j: int, elastic=None):
        """Batched fit for units[i:j] (one shared GridGroup): the group's
        (C_g, F) metric matrix, or None when the group declines/fails —
        in which case the units are stripped to the sequential path.  A
        failure the shared classifier recognizes as a DEVICE LOSS
        additionally shrinks the mesh (the stripped members then refit
        sequentially on the surviving devices)."""
        from ..obs.trace import span as _span

        group = self.units[i].group
        try:
            # the per-unit fault points fire for every member, so a fault
            # plan written against unit indices keeps working when those
            # units pack into ONE batched block (since PR 11 the tree
            # families batch too — a grouped sweep may run no
            # per-unit attempts at all)
            from ..utils import faults

            for k in range(i, j):
                faults.fire("device.loss", index=self.units[k].index,
                            tag=self.units[k].name)
            with _span(f"sweep.group[{i}:{j}]", cat="sweep",
                       group=type(group).__name__, units=j - i,
                       mesh=_mesh_attr(elastic)):
                return self._run_group(group)
        except Exception as e:  # noqa: BLE001 - fall back per-candidate,
            # routed through the shared device-loss classifier
            if elastic is not None and elastic.classify(e):
                elastic.on_group_device_loss(e)
            import warnings
            warnings.warn(
                f"grid group {type(group).__name__} failed "
                f"({type(e).__name__}: {e}); falling back to "
                f"sequential candidate fits", RuntimeWarning)
            return None

    def strip_groups(self, i: int, j: int) -> None:
        for k in range(i, j):
            self.units[k].group = None

    # -- the default scheduler: full sweep in stable order -------------------

    def run_all(self, metric_name: str, larger_better: bool,
                max_wait: Optional[float], checkpoint=None, elastic=None,
                defer: bool = False
                ) -> Tuple[int, List[ValidationResult]]:
        """Every unit in stable order — the classic full sweep.

        The default scheduler is ASYNC (``_run_all_async``): group blocks
        and unit programs dispatch back-to-back with no device sync
        between them, checkpoint flushes lag one dispatch behind the
        queue head (the flushed block's drain overlaps the block just
        enqueued), and per-candidate metrics stay device-resident until
        one end-of-sweep fetch in ``collect``.  ``TMOG_SYNC_SWEEP=1``
        (read here, at sweep time) restores the historical synchronous
        loop ``_run_all_inner`` byte-identically.

        ``checkpoint`` (a workflow.checkpoint.SweepCheckpointManager view)
        enables the mid-sweep cursor: units whose fold metrics are already
        durable are restored instead of re-run, and each finished unit's
        metrics persist as the sweep advances — an 8-chip sweep killed
        mid-flight resumes at its cursor, ON WHATEVER MESH the resuming
        process has (restored records are host fold metrics; the
        remaining units were re-batched when this queue was built).
        On the sync path checkpointing materializes each unit's device
        metrics at completion; on the async path the flush is LAGGED one
        dispatch (booked as an overlapped wait, not a drain) — at most
        the final in-flight block's durability is lost to a kill, and a
        resume re-runs exactly that block.

        ``elastic`` (parallel.elastic.ElasticContext) arms device-loss
        retry/quarantine and the straggler watchdog — see ``run_unit``.

        ``defer=True`` (async only — the halving scheduler) returns the
        RAW ``(all_vals, errors)`` with device values still deferred,
        skipping ``collect``: the caller ranks on device and materializes
        once at end of sweep.

        Raises only when EVERY candidate failed — there is no model to
        select otherwise."""
        import time

        from ..obs.trace import begin_span, end_span
        from .async_dispatch import sync_sweep_forced

        if elastic is not None:
            elastic.checkpoint = checkpoint
        sync = sync_sweep_forced() and not defer
        sweep_span = begin_span(
            "sweep.run", cat="sweep", units=len(self.units),
            folds=len(self.fold_ctxs), mesh=_mesh_attr(elastic),
            mode=("sync" if sync else "async"))
        try:
            if sync:
                return self._run_all_inner(metric_name, larger_better,
                                           max_wait, checkpoint, elastic)
            return self._run_all_async(metric_name, larger_better,
                                       max_wait, checkpoint, elastic,
                                       defer=defer)
        finally:
            end_span(sweep_span,
                     elastic=(elastic.counters.to_json()
                              if elastic is not None else None))

    def _run_all_inner(self, metric_name: str, larger_better: bool,
                       max_wait: Optional[float], checkpoint=None,
                       elastic=None
                       ) -> Tuple[int, List[ValidationResult]]:
        import time

        t0 = time.monotonic()
        all_vals: List[Any] = []
        errors: List[Optional[str]] = []
        i = 0
        while i < len(self.units):
            unit = self.units[i]
            if checkpoint is not None:
                rec = checkpoint.restore(unit.index)
                # a restored record must match THIS sweep's fold geometry
                # (the fingerprint pins candidates/validator, but a
                # hand-edited or truncated cursor could still desync);
                # mismatched records are re-run instead of misaligning
                # the metric means silently
                if rec is not None and (
                        rec[1] is not None
                        or len(rec[0]) == len(self.fold_ctxs)):
                    all_vals.append(rec[0])
                    errors.append(rec[1])
                    i += 1
                    continue
            elapsed = time.monotonic() - t0
            if max_wait is not None and elapsed > max_wait and all_vals:
                all_vals.append([])
                errors.append(
                    f"skipped: validation budget max_wait={max_wait}s "
                    f"exceeded after {elapsed:.1f}s")
                i += 1
                continue
            if unit.group is not None and self._run_group is not None:
                j = self.group_span(i)
                if elastic is not None and elastic.groups_invalid:
                    # a mesh shrink invalidated the remaining batched
                    # programs (compiled for the dead mesh): strip to
                    # sequential fits on the surviving devices
                    self.strip_groups(i, j)
                    continue
                # row offset into the group's (C_g, F) metric matrix: the
                # block may start mid-group after a checkpoint restore
                base = i - self.group_start(i)
                M = self.run_group_block(i, j, elastic=elastic)
                if M is not None:
                    if checkpoint is not None:
                        # the sync path's per-block durability sync — the
                        # async scheduler books the same flush lagged;
                        # this loop IS the kill-switch baseline
                        rows = _materialize(  # tmog: disable=TM042
                            [_GroupRow(M, base + r) for r in range(j - i)])
                        for r, vals in enumerate(rows):
                            all_vals.append(vals)
                            errors.append(None)
                            checkpoint.record_unit(self.units[i + r].index,
                                                   vals, None)
                        i = j
                        continue
                    for r in range(j - i):
                        # deferred row marker: fetched once per group
                        # matrix in _materialize (no per-row device
                        # slicing launches)
                        all_vals.append(_GroupRow(M, base + r))
                        errors.append(None)
                    i = j
                    continue
                # declined/failed: strip so members fit sequentially
                self.strip_groups(i, j)
                continue
            fold_vals, err = self.run_unit(unit, elastic=elastic)
            if checkpoint is not None:
                fold_vals = _materialize([fold_vals])[0]  # tmog: disable=TM042
                checkpoint.record_unit(unit.index, fold_vals, err)
            all_vals.append(fold_vals)
            errors.append(err)
            i += 1
        if elastic is not None:
            # watchdog-abandoned workers must not outlive the sweep (a
            # straggler finishing into interpreter teardown crashes XLA)
            elastic.drain()
        return self.collect(all_vals, errors, metric_name, larger_better)

    def _run_all_async(self, metric_name: str, larger_better: bool,
                       max_wait: Optional[float], checkpoint=None,
                       elastic=None, defer: bool = False):
        """The double-buffered scheduler: same unit semantics as
        ``_run_all_inner`` (restore cursor, budget skip, group batching
        with sequential fallback, elastic ladders), but NO device sync
        inside the dispatch loop.  Group metric matrices and per-fold
        device scalars accumulate as deferred values; a checkpointed
        sweep flushes the PREVIOUS block's records right after the next
        block is enqueued, so the flush's ``block_until_ready`` overlaps
        live device work (booked into ``overlapSecs``, tag
        ``sweep.checkpoint``) instead of stalling the accelerator.  The
        one genuine drain is the end-of-sweep fetch in ``collect``
        (``overlap_tail=True``: only the LAST deferred value's wait is a
        stall — everything fetched before it drains behind still-enqueued
        later blocks)."""
        import time

        from ..obs.trace import span as _span

        t0 = time.monotonic()
        all_vals: List[Any] = []
        errors: List[Optional[str]] = []
        #: queue positions (== unit positions) dispatched but not yet
        #: durable — the lagged checkpoint window, at most one block deep
        pending: List[int] = []

        def flush_pending(overlapped: bool) -> None:
            if checkpoint is None or not pending:
                return
            with _span("sweep.checkpoint.flush", cat="sweep",
                       units=len(pending), overlapped=overlapped):
                rows = _materialize([all_vals[p] for p in pending],
                                    tag="sweep.checkpoint",
                                    overlapped=overlapped)
                for p, vals in zip(pending, rows):
                    all_vals[p] = vals
                    checkpoint.record_unit(self.units[p].index, vals,
                                           errors[p])
            pending.clear()

        i = 0
        while i < len(self.units):
            unit = self.units[i]
            if checkpoint is not None:
                rec = checkpoint.restore(unit.index)
                # geometry check as in the sync loop: a restored record
                # must match THIS sweep's fold count or it re-runs
                if rec is not None and (
                        rec[1] is not None
                        or len(rec[0]) == len(self.fold_ctxs)):
                    all_vals.append(rec[0])
                    errors.append(rec[1])
                    i += 1
                    continue
            elapsed = time.monotonic() - t0
            if max_wait is not None and elapsed > max_wait and all_vals:
                all_vals.append([])
                errors.append(
                    f"skipped: validation budget max_wait={max_wait}s "
                    f"exceeded after {elapsed:.1f}s")
                i += 1
                continue
            if unit.group is not None and self._run_group is not None:
                j = self.group_span(i)
                if elastic is not None and elastic.groups_invalid:
                    self.strip_groups(i, j)
                    continue
                base = i - self.group_start(i)
                M = self.run_group_block(i, j, elastic=elastic)
                if M is not None:
                    block = []
                    for r in range(j - i):
                        block.append(len(all_vals))
                        all_vals.append(_GroupRow(M, base + r))
                        errors.append(None)
                    # this block is now ENQUEUED: the previous block's
                    # flush drains behind it (overlapped), then this
                    # block becomes the lagged window
                    flush_pending(overlapped=True)
                    pending.extend(block)
                    i = j
                    continue
                self.strip_groups(i, j)
                continue
            fold_vals, err = self.run_unit(unit, elastic=elastic)
            pos = len(all_vals)
            all_vals.append(fold_vals)
            errors.append(err)
            flush_pending(overlapped=True)
            pending.append(pos)
            i += 1
        # the final in-flight block: nothing is enqueued behind it, so
        # its flush is a genuine (booked) drain — the explicit durability
        # sync point.  On a pod the sync is barrier-fenced: the cursor
        # write is the coordinator's (TM047), and non-coordinators must
        # not run past the sweep's last durable write before it lands
        flush_pending(overlapped=False)
        if checkpoint is not None:
            sync = getattr(checkpoint, "sync_durability", None)
            if sync is not None:
                sync()
        if elastic is not None:
            elastic.drain()
        if defer:
            return all_vals, errors
        with _span("sweep.drain", cat="sweep", units=len(all_vals)):
            return self.collect(all_vals, errors, metric_name,
                                larger_better, overlap_tail=True)

    # -- result assembly -----------------------------------------------------

    def collect(self, all_vals, errors, metric_name: str,
                larger_better: bool, overlap_tail: bool = False
                ) -> Tuple[int, List[ValidationResult]]:
        # the losing sentinel depends on the metric direction: -inf only
        # loses when larger is better; minimize metrics (RMSE, LogLoss)
        # need +inf
        worst = float("-inf") if larger_better else float("inf")
        results: List[ValidationResult] = []
        host_vals = _materialize(
            all_vals, tag="sweep.final" if overlap_tail else None,
            overlap_tail=overlap_tail)
        for unit, fold_vals, err in zip(self.units, host_vals, errors):
            # mean over FINITE folds only: a single faulted fold (NaN from
            # the per-value _materialize fallback) should not zero out the
            # folds that did complete — the reference likewise averages
            # whichever fold Futures finished
            finite = [v for v in fold_vals if np.isfinite(v)]
            if fold_vals and not finite and err is None:
                err = "all fold metrics non-finite"
            mean = float(np.mean(finite)) if finite and err is None else worst
            results.append(ValidationResult(unit.name, unit.params,
                                            metric_name, mean,
                                            fold_vals, error=err))
        if all(r.error is not None for r in results):
            raise RuntimeError(
                "model selection failed: every candidate errored; "
                f"first error: {results[0].error}")
        best = _argbest([r.metric_value if r.error is None else worst
                         for r in results], larger_better)
        return best, results


def _run_sweep(candidates, fold_ctxs, run_fold, metric_name: str,
               larger_better: bool, max_wait: Optional[float],
               run_group=None, checkpoint=None, elastic=None,
               defer: bool = False
               ) -> Tuple[int, List[ValidationResult]]:
    """The full-sweep scheduler over the work queue (see SweepWorkQueue
    for the execution semantics — this wrapper is the historical entry
    point every validator calls).  ``defer=True`` skips ``collect`` and
    returns ``(queue, all_vals, errors)`` with device values deferred —
    the halving scheduler's on-device rung promotion consumes these."""
    queue = SweepWorkQueue(candidates, fold_ctxs, run_fold,
                           run_group=run_group)
    out = queue.run_all(metric_name, larger_better, max_wait,
                        checkpoint=checkpoint, elastic=elastic, defer=defer)
    if defer:
        all_vals, errors = out
        return queue, all_vals, errors
    return out


def _argbest(vals: List[float], larger_better: bool) -> int:
    arr = np.asarray(vals, np.float64)
    if not larger_better:
        arr = -arr
    arr = np.where(np.isnan(arr), -np.inf, arr)
    return int(np.argmax(arr))


class _GroupRow:
    """Deferred row of a grid group's (C, F) metric matrix — resolved in
    ``_materialize`` with one fetch per matrix."""

    __slots__ = ("matrix", "row")

    def __init__(self, matrix, row: int):
        self.matrix = matrix
        self.row = row


def _materialize(nested: List[Any], tag: Optional[str] = None,
                 overlapped: bool = False, overlap_tail: bool = False
                 ) -> List[List[float]]:
    """Fetch all fold metric values in ONE device transfer.

    ``eval_fn`` returns device scalars on the device-resident sweep path
    (ModelSelector._metric); through a remote-TPU tunnel every host sync is a
    ~0.6 s round trip, so the whole candidates×folds sweep is dispatched
    async and this single stacked fetch replaces per-fold ``float()`` calls.
    Grid-group rows (``_GroupRow``) resolve with one fetch per group matrix.

    Ledger attribution: ``tag`` names the call site in ``drain_tags``;
    ``overlapped=True`` books EVERY wait here as overlapped (the async
    scheduler's lagged checkpoint flush — later work is already enqueued
    behind these values); ``overlap_tail=True`` is the end-of-sweep mode:
    waits are overlapped while LATER deferred values still have enqueued
    programs draining behind them, and only the final wait (the last
    group matrix, or the stacked scalar fetch when there is one) is a
    genuine drain — the accelerator is busy until that last value lands."""
    # resolve group matrices first (one transfer each, NaN rows on failure);
    # fetch_timed books queue-drain separately from the byte transfer
    from ..utils.profiling import fetch_timed

    try:
        import jax
        has_scalar_tail = any(
            not isinstance(vals, _GroupRow)
            and any(isinstance(v, jax.Array) for v in vals)
            for vals in nested)
    except Exception:  # pragma: no cover
        has_scalar_tail = False
    mat_ids = []
    for v in nested:
        if isinstance(v, _GroupRow) and id(v.matrix) not in mat_ids:
            mat_ids.append(id(v.matrix))
    mats: dict = {}
    for v in nested:
        if isinstance(v, _GroupRow) and id(v.matrix) not in mats:
            # in tail mode a matrix wait overlaps the still-enqueued
            # fetches behind it; the LAST one (with no scalar fetch to
            # follow) is the sweep's terminal stall
            is_last = (id(v.matrix) == mat_ids[-1]) and not has_scalar_tail
            ovl = overlapped or (overlap_tail and not is_last)
            try:
                mats[id(v.matrix)] = fetch_timed(
                    v.matrix, np.float64, tag=tag, overlapped=ovl)
            except Exception as e:  # async device fault in the group program
                import warnings
                warnings.warn(
                    f"group metric fetch failed ({type(e).__name__}: "
                    f"{str(e)[:300]}); recording NaN rows", RuntimeWarning)
                mats[id(v.matrix)] = None
    if mats:
        resolved: List[Any] = []
        for v in nested:
            if not isinstance(v, _GroupRow):
                resolved.append(v)
            elif mats[id(v.matrix)] is None:
                resolved.append([float("nan")] * int(v.matrix.shape[1]))
            else:
                resolved.append([float(x) for x in mats[id(v.matrix)][v.row]])
        nested = resolved
    try:
        import jax
        import jax.numpy as jnp
        dev = [v for vals in nested for v in vals
               if isinstance(v, jax.Array)]
    except Exception:  # pragma: no cover
        dev = []
    if not dev:
        return [[float(v) for v in vals] for vals in nested]
    # jitted stack: un-jitted jnp.stack dispatches one expand_dims per
    # scalar (~30 ms tunnel dispatch each); jitted it is ONE launch
    try:
        stacked = _stack_jit(*dev)
        fetched = fetch_timed(stacked, np.float64, tag=tag,
                              overlapped=overlapped)
        host = iter(fetched)
        return [[float(next(host)) if isinstance(v, jax.Array) else float(v)
                 for v in vals] for vals in nested]
    except Exception:
        # an async device error (e.g. a diverging candidate whose metric
        # program faults at execution time) poisons the stacked fetch;
        # fall back to per-value fetches so only the faulty values go NaN
        def fetch(v):
            try:
                return float(np.asarray(v)) if isinstance(v, jax.Array) \
                    else float(v)
            except Exception:
                return float("nan")
        return [[fetch(v) for v in vals] for vals in nested]


def _stack_jit(*xs):
    # module-level jit so the executable caches per arity (a fresh lambda
    # per call would re-trace and re-compile every validate)
    global _STACK_JIT
    if _STACK_JIT is None:
        import jax
        import jax.numpy as jnp
        _STACK_JIT = jax.jit(lambda *ys: jnp.stack(ys))
    return _STACK_JIT(*xs)


_STACK_JIT = None
