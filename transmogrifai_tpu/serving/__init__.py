"""Online model serving — micro-batched, shape-bucketed, backpressured.

The production inference path the ROADMAP north star asks for: a persisted
workflow model behind a long-lived server that coalesces concurrent
requests into padded power-of-2 micro-batches (warm compiled program per
bucket — zero steady-state recompiles), sheds load with structured 503s
when the bounded queue fills, and degrades to the numpy host scorer when
the device path errors.  See docs/serving.md for the architecture and the
degradation ladder.

    from transmogrifai_tpu.serving import ModelServer

    server = ModelServer.from_path("/models/titanic", name="titanic")
    with server:                      # warms every shape bucket
        out = server.score([{"age": 31.0, "sex": "male", ...}])
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

from .admission import AdmissionController, CircuitBreaker, ShedResult
from .aot import AOTStore, ScoringProgramSet, scoring_digest
from .batcher import MicroBatcher
from .drift import DriftConfig, DriftMonitor, export_drift_baselines
from .executor import BucketedExecutor, bucket_for, bucket_sizes
from .guarded import GuardedSwap, SwapDecision, SwapGateConfig
from .metrics import ServingMetrics
from .registry import ModelEntry, ModelRegistry

__all__ = ["ModelServer", "ModelRegistry", "ModelEntry", "MicroBatcher",
           "BucketedExecutor", "AdmissionController", "CircuitBreaker",
           "ShedResult", "ServingMetrics", "bucket_sizes", "bucket_for",
           "DriftMonitor", "DriftConfig", "export_drift_baselines",
           "GuardedSwap", "SwapGateConfig", "SwapDecision",
           "AOTStore", "ScoringProgramSet", "scoring_digest",
           "MultiTenantServer", "TenantConfig"]


class ModelServer:
    """Ties registry + batcher + bucketed executor + breaker together.

    One server serves one registry name; the entry (and its executor) is
    re-resolved per batch, so a registry hot-swap atomically redirects
    traffic to the new version after its buckets are warmed.
    """

    def __init__(self, registry: ModelRegistry, name: str,
                 max_batch: int = 64, max_latency_ms: float = 5.0,
                 max_queue_rows: int = 1024,
                 default_deadline_ms: Optional[float] = None,
                 failure_threshold: int = 3, breaker_reset_s: float = 30.0,
                 warmup_row: Optional[Dict[str, Any]] = None,
                 batch_mode: str = "continuous",
                 device_programs: bool = False,
                 aot_store: Any = None,
                 cost_lookup: Any = None):
        self.registry = registry
        self.name = name
        self.max_batch = int(max_batch)
        self.metrics = ServingMetrics()
        self.admission = AdmissionController(
            max_queue_rows=max_queue_rows,
            default_deadline_ms=default_deadline_ms)
        self.breaker = CircuitBreaker(
            failure_threshold=failure_threshold,
            reset_after_s=breaker_reset_s)
        #: opt-in AOT/device scoring (serving/aot.py): compile each shape
        #: bucket's scoring program once, persist the serialized executable
        #: in the content-addressed store, cold-start by LOADING it.
        #: ``aot_store`` accepts an AOTStore, a directory path, or True for
        #: the default store location; None with device_programs=True keeps
        #: JIT-only device scoring (no persistence).
        self.device_programs = bool(device_programs)
        if aot_store is True:
            aot_store = AOTStore()
        elif isinstance(aot_store, str):
            aot_store = AOTStore(aot_store)
        self.aot_store = aot_store
        self.batcher = MicroBatcher(
            self._execute, max_batch=max_batch,
            max_latency_ms=max_latency_ms,
            admission=self.admission, metrics=self.metrics,
            mode=batch_mode, cost_lookup=cost_lookup)
        self.warmup_row = warmup_row
        self._executors: Dict[int, BucketedExecutor] = {}  # entry version -> executor
        self._exec_lock = threading.Lock()
        #: optional drift monitor + guarded-swap controller (the online-
        #: refresh loop's serving half); None keeps the hot path untouched
        self.drift_monitor = None
        self.guard = None
        #: graceful-drain flag: once set, new submits shed with reason
        #: "draining" while queued/in-flight work completes — the SIGTERM
        #: half of the fabric's drain-vs-SIGKILL matrix
        self._draining = False
        registry.on_swap(self._on_swap)

    def with_drift_monitor(self, monitor) -> "ModelServer":
        """Feed sampled scoring traffic into a :class:`~transmogrifai_tpu.
        serving.drift.DriftMonitor`; its snapshot joins ``/metrics``."""
        self.drift_monitor = monitor
        return self

    def with_guard(self, guard) -> "ModelServer":
        """Attach a :class:`~transmogrifai_tpu.serving.guarded.GuardedSwap`:
        live traffic fills its replay window and drives bake probes, and
        its lifecycle snapshot joins ``/metrics``.  The guard shares this
        server's metrics object so gate/rollback counters land in the
        same ledger."""
        guard.metrics = self.metrics
        self.guard = guard
        return self

    # -- construction helpers ------------------------------------------------

    @classmethod
    def from_path(cls, path: str, name: str = "default",
                  registry: Optional[ModelRegistry] = None,
                  **kwargs) -> "ModelServer":
        """Load a persisted model directory and build a server around it."""
        registry = registry or ModelRegistry()
        server = cls(registry, name, **kwargs)
        registry.load(name, path)
        return server

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ModelServer":
        """Warm every shape bucket for the current model, then accept
        traffic.  Warmup happens BEFORE the dispatch thread starts so no
        request can race a cold program."""
        if self.warmup_row is not None:
            self._executor_for(self.registry.get(self.name)).warmup(
                self.warmup_row)
        self.batcher.start()
        return self

    def stop(self, drain: bool = True) -> None:
        self.batcher.close(drain=drain)

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting (new submits shed with reason ``"draining"``);
        queued and in-flight batches complete normally.  ``/healthz``
        reports status "draining" so the fabric router deregisters this
        host before ``stop(drain=True)`` tears the dispatch loop down."""
        self._draining = True

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- scoring -------------------------------------------------------------

    def submit(self, rows: Sequence[Dict[str, Any]],
               timeout_ms: Optional[float] = None) -> "Future[List[Any]]":
        if self._draining:
            self.metrics.record_shed(len(rows), reason="draining")
            fut: "Future[List[Any]]" = Future()
            fut.set_result([ShedResult(reason="draining")
                            for _ in rows])
            return fut
        return self.batcher.submit(rows, timeout_ms=timeout_ms)

    def score(self, rows: Sequence[Dict[str, Any]],
              timeout_ms: Optional[float] = None,
              wait_s: Optional[float] = 60.0) -> List[Any]:
        """Synchronous convenience: submit + wait.  Each element is either
        a score map or a ``ShedResult``."""
        return self.submit(rows, timeout_ms=timeout_ms).result(timeout=wait_s)

    # -- model lifecycle -----------------------------------------------------

    def swap(self, path: str) -> ModelEntry:
        """Hot-swap the served model from a persisted directory; buckets of
        the incoming version are warmed (via the registry swap listener)
        before the entry becomes current."""
        return self.registry.load(self.name, path)

    def _on_swap(self, entry: ModelEntry) -> None:
        if entry.name != self.name:
            return
        self.metrics.record_hot_swap()
        if self.warmup_row is not None:
            try:
                self._executor_for(entry).warmup(self.warmup_row)
            except Exception:
                pass  # cold buckets compile lazily on first hit instead

    def _executor_for(self, entry: ModelEntry) -> BucketedExecutor:
        with self._exec_lock:
            ex = self._executors.get(entry.version)
            if ex is None:
                ex = BucketedExecutor(
                    entry.scorer, max_batch=self.max_batch,
                    cache_key_prefix=f"serving.{entry.name}.v{entry.version}",
                    model=entry.model if self.device_programs else None,
                    aot_store=self.aot_store,
                    device_programs=self.device_programs)
                self._executors = {entry.version: ex}  # evict stale versions
            return ex

    # -- execution (called by the batcher's dispatch thread) -----------------

    def _execute(self, rows: List[Dict[str, Any]]) -> List[Any]:
        from ..obs.trace import begin_span, end_span

        if self.drift_monitor is not None:
            self.drift_monitor.observe_rows(rows)
        if self.guard is not None:
            self.guard.record_traffic(rows)
        entry = self.registry.get(self.name)
        executor = self._executor_for(entry)
        bucket = bucket_for(len(rows), executor.buckets) \
            if len(rows) <= executor.max_batch else executor.max_batch
        fallback_reason = "breaker_open"
        if self.breaker.allow_device():
            sp = begin_span("serve.execute", cat="serve", rows=len(rows),
                            bucket=bucket, path="device",
                            version=entry.version)
            t0 = time.perf_counter()
            try:
                out = executor.score(rows)
                self.breaker.record_success()
                self.metrics.record_batch(
                    len(rows), bucket, time.perf_counter() - t0)
                end_span(sp)
                return out
            except Exception as exc:
                fallback_reason = f"device_error:{type(exc).__name__}"
                end_span(sp, error=fallback_reason)
                self.metrics.record_device_error()
                if self.breaker.record_failure():
                    self.metrics.record_breaker_open()
        # degradation ladder rung 4: numpy host path, exact batch size —
        # slower, but it answers (the device worker-crash mode must degrade
        # a replica, not take it down)
        self.metrics.record_host_fallback(len(rows), reason=fallback_reason)
        sp = begin_span("serve.execute", cat="serve", rows=len(rows),
                        bucket=bucket, path="host",
                        reason=fallback_reason, version=entry.version)
        t0 = time.perf_counter()
        try:
            out = entry.scorer(rows)
        finally:
            end_span(sp)
        self.metrics.record_batch(len(rows), bucket,
                                  time.perf_counter() - t0)
        return out

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        snap["model"] = self.registry.get(self.name).describe() \
            if self.registry.maybe_get(self.name) else None
        snap["breakerState"] = self.breaker.state
        snap["batchMode"] = self.batcher.mode
        if self.batcher.cost_lookup is not None:
            snap["batchCost"] = self.batcher.cost_lookup.snapshot()
        if self.device_programs:
            ex = None
            entry = self.registry.maybe_get(self.name)
            if entry is not None:
                with self._exec_lock:
                    ex = self._executors.get(entry.version)
            if ex is not None and ex.programs is not None:
                snap["aotPrograms"] = ex.programs.modes
        if self.drift_monitor is not None:
            snap["drift"] = self.drift_monitor.snapshot()
        if self.guard is not None:
            snap["guardedSwap"] = self.guard.snapshot()
            snap["generations"] = self.registry.generations(self.name)
        return snap


# imported last: tenancy composes ModelServer instances per tenant, the
# fabric composes whole servers into a multi-host plane
from .fabric import (ControlChannel, FleetSwapController,  # noqa: E402
                     HashRing, HttpHostHandle, LocalHostHandle,
                     ServingFabric)
from .tenancy import MultiTenantServer, TenantConfig  # noqa: E402

__all__ += ["ServingFabric", "HashRing", "LocalHostHandle",
            "HttpHostHandle", "ControlChannel", "FleetSwapController"]
