"""Admission control — backpressure, deadlines, and graceful degradation.

A server in front of a device must fail *sideways*, not *over*: when the
queue is full the right answer is an immediate structured "try later"
(the HTTP-503 shape), and when the device path starts erroring the right
answer is to keep answering from the numpy host path while the breaker is
open — the same worker-crash mode the 1M bisection harness chases must
degrade a replica, not take it down.

Degradation ladder (documented in docs/serving.md):
  1. coalesce   — micro-batcher amortizes dispatch overhead
  2. queue      — bounded; absorbs bursts up to ``max_queue_rows``
  3. shed       — over-capacity / past-deadline requests get ``ShedResult``
  4. fall back  — circuit breaker routes device failures to the host scorer

Lock-order convention (pinned by the TM053 lint, analysis/concur_lint.py):
the admission and breaker locks are LEAF locks — every ``with self._lock``
region is a few field reads/writes with no calls out, so neither can
invert against the registry or batcher locks.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["ShedResult", "AdmissionController", "CircuitBreaker"]


@dataclasses.dataclass
class ShedResult:
    """Structured load-shed response (the 503 analogue).

    Returned *as the result* for every row of a shed request — callers get
    data they can inspect/serialize, never an exception storm.
    """

    status: int = 503
    reason: str = "overloaded"
    queue_depth: Optional[int] = None
    retry_after_ms: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"status": self.status, "reason": self.reason}
        if self.queue_depth is not None:
            out["queueDepth"] = self.queue_depth
        if self.retry_after_ms is not None:
            out["retryAfterMs"] = round(self.retry_after_ms, 3)
        return out


class AdmissionController:
    """Bounded-queue admission: admit, or shed with a ``ShedResult``.

    Depth is accounted in ROWS (the unit of device work), not requests —
    one 64-row request costs what 64 single-row requests cost.
    """

    def __init__(self, max_queue_rows: int = 1024,
                 default_deadline_ms: Optional[float] = None):
        self.max_queue_rows = int(max_queue_rows)
        self.default_deadline_ms = default_deadline_ms
        self._lock = threading.Lock()
        self._queued_rows = 0

    @property
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    def try_admit(self, n_rows: int,
                  est_drain_ms: Optional[float] = None
                  ) -> Optional[ShedResult]:
        """Reserve queue room for ``n_rows``; a ``ShedResult`` means NO —
        the caller must not enqueue (and must not call ``release``)."""
        with self._lock:
            if self._queued_rows + n_rows > self.max_queue_rows:
                return ShedResult(
                    reason="queue_full",
                    queue_depth=self._queued_rows,
                    retry_after_ms=est_drain_ms,
                )
            self._queued_rows += n_rows
            return None

    def release(self, n_rows: int) -> None:
        """Return queue room once the rows left the queue (scored or shed)."""
        with self._lock:
            self._queued_rows = max(0, self._queued_rows - n_rows)

    def deadline_for(self, timeout_ms: Optional[float]) -> Optional[float]:
        """Absolute monotonic deadline for a request, or None (no deadline)."""
        t = timeout_ms if timeout_ms is not None else self.default_deadline_ms
        return None if t is None else time.monotonic() + t / 1000.0


class CircuitBreaker:
    """Consecutive-failure breaker over the device scoring path.

    CLOSED  — device path in use; a failure streak of ``failure_threshold``
              opens the breaker.
    OPEN    — all traffic served by the host fallback for ``reset_after_s``.
    HALF_OPEN — one trial batch is allowed through; success closes the
              breaker, failure re-opens it (fresh cooldown).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3,
                 reset_after_s: float = 30.0):
        self.failure_threshold = int(failure_threshold)
        self.reset_after_s = float(reset_after_s)
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._trial_in_flight = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if (self._state == self.OPEN and self._opened_at is not None
                and time.monotonic() - self._opened_at >= self.reset_after_s):
            self._state = self.HALF_OPEN
            self._trial_in_flight = False
            from ..obs.flight import record_event

            record_event("breaker.half_open")

    def allow_device(self) -> bool:
        """May the next batch use the device path?"""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._trial_in_flight:
                self._trial_in_flight = True  # exactly one trial batch
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            reclosed = self._state != self.CLOSED
            self._state = self.CLOSED
            self._consecutive_failures = 0
            self._opened_at = None
            self._trial_in_flight = False
        if reclosed:
            from ..obs.flight import record_event

            record_event("breaker.closed")

    def record_failure(self) -> bool:
        """Register a device-path failure; returns True if the breaker
        transitioned to OPEN on this call."""
        with self._lock:
            self._consecutive_failures += 1
            was_open = self._state == self.OPEN
            opened = False
            if (self._state == self.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self._trial_in_flight = False
                opened = not was_open
        if opened:
            from ..obs.flight import record_event

            record_event("breaker.open",
                         failures=self.failure_threshold)
        return opened
