"""AOT scoring programs — serialized executables for millisecond cold starts.

The serving plane's per-process warm-up is dominated by tracing + XLA
compilation: every shape bucket of every served model is a distinct
program (the Titanic-shaped DAG compiles ~28 programs, ~50 s on the
tunneled TPU), paid again by every fresh replica.  Following the TPU
serving-comparison playbook (PAPERS.md), this module lowers each
``(model digest, shape bucket)`` scoring program AHEAD OF TIME and
persists the compiled executable in a content-addressed on-disk store
(``utils/compile_cache.AOTStore``), so a cold replica *loads* its warm
programs instead of compiling them:

  * key = digest(model scoring params, bucket, backend, jax version,
    x64 flag, format version) — a changed model, different backend, or
    jax upgrade misses and falls back to JIT (which writes the fresh
    entry through);
  * payload = ``jax.experimental.serialize_executable`` bytes; the call
    pytrees are RECONSTRUCTED from the spec's arity at load time (never
    pickled jax internals), and the sidecar meta carries a sha256 so a
    truncated/corrupted entry reads as a miss, never as a program;
  * parity: a deserialized executable is the same compiled artifact the
    in-process JIT produces, so AOT-path scores are byte-identical to
    JIT-path scores (test-asserted; the tier1 SERVING_COLDSTART gate
    also compares output digests across fresh subprocesses).

The device path is OPT-IN per server (``device_programs=True``): the
default executor keeps the host ``predict_batch`` path byte-identical to
PR 1, and the circuit breaker's host fallback never enters the device
scoring context, so an open breaker cannot touch these programs at all.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import compile_cache
from ..utils.compile_cache import AOT_FORMAT_VERSION, AOTStore

__all__ = ["ScoringProgramSet", "scoring_digest", "device_scoring",
           "device_scoring_active", "AOTStore"]


# ---------------------------------------------------------------------------
# device-scoring context — who may use installed programs
# ---------------------------------------------------------------------------

_tls = threading.local()


class device_scoring:
    """Context manager marking the current thread as the device scoring
    path.  ``PredictorModel.transform_columns`` consults this so ONLY the
    bucketed executor routes through compiled programs — the breaker's
    host fallback and offline scoring stay on the host path."""

    def __enter__(self):
        self._prev = getattr(_tls, "active", False)
        _tls.active = True
        return self

    def __exit__(self, *exc):
        _tls.active = self._prev
        return False


def device_scoring_active() -> bool:
    return getattr(_tls, "active", False)


# ---------------------------------------------------------------------------
# content addressing
# ---------------------------------------------------------------------------

def _jax_version() -> str:
    import jax

    return jax.__version__


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def model_params_digest(spec) -> str:
    """Digest of the scoring program identity: family name + parameter
    bytes/shapes/dtypes.  Two models with identical fitted parameters
    share executables; any parameter change changes every key."""
    h = hashlib.sha256()
    h.update(spec.name.encode())
    for p in spec.params:
        arr = np.asarray(p)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:20]


def scoring_digest(spec, bucket: int, backend: str) -> str:
    """The store key for one ``(model, bucket)`` executable."""
    h = hashlib.sha256()
    h.update(model_params_digest(spec).encode())
    h.update(f"|bucket={bucket}|backend={backend}".encode())
    h.update(f"|jax={_jax_version()}|x64={_x64_enabled()}".encode())
    h.update(f"|fmt={AOT_FORMAT_VERSION}".encode())
    return f"{spec.name.replace('.', '_')}-b{bucket}-{h.hexdigest()[:24]}"


# ---------------------------------------------------------------------------
# program set
# ---------------------------------------------------------------------------

class ScoringProgramSet:
    """Per-model set of compiled per-bucket scoring programs.

    ``ensure_bucket`` populates one bucket either by LOADING a serialized
    executable from the AOT store (milliseconds; recorded as an
    ``aotLoad``) or by JIT-compiling it (recorded as a ``compile``) and
    writing the serialized executable through to the store so the next
    replica loads it.  ``predict`` runs the program for an exact-shape
    batch; unknown shapes return None (caller falls back to the host
    ``predict_batch``).
    """

    def __init__(self, model, store: Optional[AOTStore] = None,
                 cache_key_prefix: str = "serving"):
        spec = model.aot_scoring_spec() if hasattr(
            model, "aot_scoring_spec") else None
        if spec is None:
            raise ValueError(
                f"{type(model).__name__} has no AOT scoring spec")
        self.model = model
        self.spec = spec
        self.store = store
        self.cache_key_prefix = cache_key_prefix
        from ..utils.profiling import backend_name

        self.backend = backend_name()
        # the spec carries D explicitly; infer from params[0] only for
        # legacy specs where params[0] happens to be (…, D)-shaped
        self.n_features = (int(spec.n_features)
                           if getattr(spec, "n_features", None) is not None
                           else int(np.asarray(spec.params[0]).shape[-1]))
        self._programs: Dict[int, Any] = {}
        self._modes: Dict[int, str] = {}  # bucket -> "aot" | "jit"
        self._lock = threading.Lock()
        #: jnp-ready parameter arrays (uploaded once, reused every call)
        self._params = tuple(np.asarray(p) for p in spec.params)

    # -- introspection ------------------------------------------------------

    @property
    def buckets(self) -> List[int]:
        with self._lock:
            return sorted(self._programs)

    @property
    def modes(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._modes)

    def cached_in_store(self, bucket: int) -> bool:
        """True when the AOT store already holds a valid entry for this
        (model, bucket) — the warmup skip probe."""
        if self.store is None:
            return False
        return self.store.contains(
            scoring_digest(self.spec, bucket, self.backend),
            expect=self._expect())

    def _expect(self) -> Dict[str, Any]:
        return {"backend": self.backend, "jaxVersion": _jax_version(),
                "program": self.spec.name,
                "outputs": list(self.spec.outputs)}

    # -- build / load -------------------------------------------------------

    def _arg_specs(self, bucket: int):
        import jax
        import jax.numpy as jnp

        specs = [jax.ShapeDtypeStruct((bucket, self.n_features),
                                      jnp.float32)]
        for p in self._params:
            specs.append(jax.ShapeDtypeStruct(np.shape(p), np.asarray(
                p).dtype))
        return tuple(specs)

    def _call_trees(self):
        import jax

        n_args = 1 + len(self._params)
        in_tree = jax.tree_util.tree_structure(((0,) * n_args, {}))
        out_tree = jax.tree_util.tree_structure((0,) * len(
            self.spec.outputs))
        return in_tree, out_tree

    def ensure_bucket(self, bucket: int, allow_load: bool = True) -> str:
        """Make ``bucket``'s program runnable; returns "aot" (loaded) or
        "jit" (compiled).  Corrupted / version-mismatched store entries
        fall back to JIT and are replaced by the write-through."""
        with self._lock:
            mode = self._modes.get(bucket)
            if mode is not None:
                return mode
        from ..obs.flight import record_event

        key = scoring_digest(self.spec, bucket, self.backend)
        ledger_key = f"{self.cache_key_prefix}.aot.bucket{bucket}"
        program = None
        mode = "jit"
        if allow_load and self.store is not None:
            got = self.store.get(key, expect=self._expect())
            if got is not None:
                payload, _meta = got
                try:
                    program = self._load(payload)
                    mode = "aot"
                    compile_cache.record_aot_load(ledger_key)
                    record_event("serve.aot_load", key=key, bucket=bucket)
                except Exception:
                    # undeserializable payload (e.g. foreign runtime):
                    # treat exactly like corruption — drop + recompile
                    self.store.invalidate(key)
                    program = None
            if program is None:
                compile_cache.record_aot_miss(ledger_key)
                record_event("serve.aot_miss", key=key, bucket=bucket)
        if program is None:
            program = self._compile(bucket)
            compile_cache.record_compile(ledger_key)
            record_event("serve.aot_compile", key=key, bucket=bucket)
            if self.store is not None:
                try:
                    payload = self._serialize(program)
                    self.store.put(key, payload, self._expect())
                except Exception:  # store is an optimization, never fatal
                    pass
        with self._lock:
            self._programs[bucket] = program
            self._modes[bucket] = mode
        return mode

    def _compile(self, bucket: int):
        import jax

        return jax.jit(self.spec.fn).lower(
            *self._arg_specs(bucket)).compile()

    def _serialize(self, program) -> bytes:
        from jax.experimental import serialize_executable as se

        payload, _in_tree, _out_tree = se.serialize(program)
        return payload

    def _load(self, payload: bytes):
        from jax.experimental import serialize_executable as se

        in_tree, out_tree = self._call_trees()
        return se.deserialize_and_load(payload, in_tree, out_tree)

    # -- execution ----------------------------------------------------------

    def predict(self, X: np.ndarray):
        """Run the compiled program for this exact batch shape; None when
        no program covers ``X`` (caller uses the host path)."""
        from ..models.prediction import PredictionBatch

        if X.ndim != 2 or X.shape[1] != self.n_features:
            return None
        bucket = int(X.shape[0])
        with self._lock:
            program = self._programs.get(bucket)
        if program is None:
            return None
        outs = program(np.ascontiguousarray(X, np.float32), *self._params)
        named = dict(zip(self.spec.outputs, outs))
        pred = np.asarray(named["prediction"]).astype(np.float64)
        raw = named.get("rawPrediction")
        proba = named.get("probability")
        return PredictionBatch(
            prediction=pred,
            raw_prediction=None if raw is None else np.asarray(raw),
            probability=None if proba is None else np.asarray(proba))


def find_predictor(workflow_model):
    """The AOT-relevant stage of a persisted workflow model: the LAST
    predictor stage in its scoring DAG (the one whose device program the
    serving hot path actually runs per batch)."""
    from ..models.prediction import PredictorModel

    found = None
    for stage in getattr(workflow_model, "stages", []) or []:
        if isinstance(stage, PredictorModel):
            found = stage
    return found


def program_set_for(model, store: Optional[AOTStore] = None,
                    cache_key_prefix: str = "serving"
                    ) -> Optional[ScoringProgramSet]:
    """Build + INSTALL a program set for a workflow model (or a bare
    predictor), or None when no stage has an AOT-exportable scoring
    program (serving stays on the host path — correct, just without the
    cold-start win).  Installation sets ``_serving_programs`` on the
    predictor stage; the programs only ever run inside the
    :class:`device_scoring` context."""
    predictor = None
    spec_fn = getattr(model, "aot_scoring_spec", None)
    if callable(spec_fn) and spec_fn() is not None:
        predictor = model
    else:
        cand = find_predictor(model)
        if cand is not None and cand.aot_scoring_spec() is not None:
            predictor = cand
    if predictor is None:
        return None
    ps = ScoringProgramSet(predictor, store=store,
                           cache_key_prefix=cache_key_prefix)
    predictor._serving_programs = ps
    return ps
