"""Admission queue + micro-batcher — continuous batch formation by default.

Concurrent callers submit small row lists; a single dispatch thread
coalesces them into micro-batches.  Two formation modes:

``continuous`` (default since serving v2)
    The next batch forms the moment the executor frees: no fixed
    coalescing window, no idle gap between batches.  The dispatcher picks
    the target shape bucket GREEDILY, maximizing predicted service rate
    ``rows / (projected_fill_wait + predicted_batch_cost)`` over the
    current queue depth, the measured arrival rate, and the per-bucket
    predicted batch cost (``tuning.costmodel.ServingCostLookup`` — online
    EWMA of measured batch walls, cost-model fallback).  When holding the
    batch open to fill a bigger bucket scores better (saturation: the
    queue refills in a millisecond or two), it admits late-arriving rows
    into the forming batch up to the projected-fill deadline (hard-capped
    at ``max_latency_ms`` — the same bound the windowed mode pays); when
    arrivals project nothing (light load), the batch dispatches
    IMMEDIATELY — that asymmetry is the continuous-batching win over a
    fixed window.

``windowed`` (the PR 1 behavior, behind this flag)
    Coalesce up to ``max_batch`` rows or until the oldest waiting request
    has waited ``max_latency_ms``.  Kept byte-identical (test-asserted) as
    the conservative fallback.

The batcher is transport-agnostic: ``execute`` is any
``rows -> score maps`` callable (the server wires in the circuit-breaker +
bucketed executor).  Results come back on per-request futures; shed and
expired requests resolve to ``ShedResult``s, not exceptions.

Shutdown discipline: ``close(drain=True)`` flips the batcher to *closing*
(new submits shed as ``shutting_down``) and then drains UNDER THE LOCK
until the queue is observably empty — a pending that made it into the
queue is always either scored or shed, never silently dropped (the PR 1
drain polled without the lock and could strand a submit that raced the
final empty-check; regression-tested).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.flight import record_event
from ..obs.trace import begin_span, end_span
from .admission import AdmissionController, ShedResult
from .executor import bucket_for, bucket_sizes
from .metrics import ServingMetrics

__all__ = ["MicroBatcher", "run_pending_batch"]

class _Pending:
    __slots__ = ("rows", "future", "deadline", "enqueued_at")

    def __init__(self, rows: List[Dict[str, Any]],
                 deadline: Optional[float]):
        self.rows = rows
        self.future: "Future[List[Any]]" = Future()
        self.deadline = deadline
        self.enqueued_at = time.monotonic()


class MicroBatcher:
    def __init__(self, execute: Callable[[List[Dict[str, Any]]], List[Any]],
                 max_batch: int = 64, max_latency_ms: float = 5.0,
                 admission: Optional[AdmissionController] = None,
                 metrics: Optional[ServingMetrics] = None,
                 mode: str = "continuous",
                 cost_lookup: Any = None):
        if mode not in ("continuous", "windowed"):
            raise ValueError(
                f"mode must be 'continuous' or 'windowed', got {mode!r}")
        self.execute = execute
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_ms) / 1000.0
        self.admission = admission or AdmissionController()
        self.metrics = metrics or ServingMetrics()
        self.mode = mode
        #: per-bucket predicted batch cost (ServingCostLookup); built lazily
        #: so a windowed batcher never touches tuning/
        self.cost_lookup = cost_lookup
        self._buckets = bucket_sizes(self.max_batch)
        #: recent arrivals (monotonic t, rows) — the continuous bucket
        #: choice anticipates rows that will land DURING the fill window,
        #: so a closed-loop burst forms full batches instead of
        #: fragmenting into whatever happened to be queued at form time
        self._arrivals: List[Tuple[float, int]] = []
        #: sticky saturation (continuous mode): once a near-full batch
        #: forms, stay in throughput mode even when the instantaneous
        #: arrival probe reads momentarily quiet — a single leaked
        #: fragment breaks the convoy permanently.  Cleared when a fill
        #: hold genuinely expires under-filled (load actually dropped).
        self._saturated = False
        self._queue: List[_Pending] = []
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._closing = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None or not self._thread.is_alive():
            self._closing = False
            self._closed = False
            if self.mode == "continuous" and self.cost_lookup is None:
                from ..tuning.costmodel import ServingCostLookup

                self.cost_lookup = ServingCostLookup()
            target = (self._dispatch_continuous
                      if self.mode == "continuous"
                      else self._dispatch_windowed)
            self._thread = threading.Thread(
                target=target, name="op-serving-batcher", daemon=True)
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the dispatch thread; by default drain queued work first.

        Drains UNDER the lock: ``_closing`` makes every later submit shed
        immediately, then we condition-wait until the dispatch thread has
        observably emptied the queue (it keeps running until ``_closed``),
        so nothing enqueued before the flag can be dropped."""
        alive = self._thread is not None and self._thread.is_alive()
        with self._work:
            self._closing = True
            if drain and alive:
                deadline = time.monotonic() + timeout_s
                while self._queue:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._work.wait(timeout=min(remaining, 0.005))
            self._closed = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- submission ---------------------------------------------------------

    def submit(self, rows: Sequence[Dict[str, Any]],
               timeout_ms: Optional[float] = None) -> "Future[List[Any]]":
        """Enqueue ``rows`` for coalesced scoring.

        Always returns a future.  Overload resolves it IMMEDIATELY with one
        ``ShedResult`` per row; otherwise it resolves with the score maps
        (or ``ShedResult``s if the deadline expires while queued).
        """
        rows = list(rows)
        fut: "Future[List[Any]]" = Future()
        if not rows:
            fut.set_result([])
            return fut
        admit_span = begin_span("serve.admit", cat="serve", rows=len(rows))
        if self._closing or self._closed:
            fut.set_result([ShedResult(reason="shutting_down")
                            for _ in rows])
            self.metrics.record_shed(len(rows), reason="shutting_down")
            end_span(admit_span, outcome="shed:shutting_down")
            return fut
        shed = self.admission.try_admit(
            len(rows), est_drain_ms=self._est_drain_ms())
        if shed is not None:
            self.metrics.record_shed(len(rows), reason=shed.reason)
            fut.set_result([shed for _ in rows])
            end_span(admit_span, outcome=f"shed:{shed.reason}")
            record_event("serve.shed", rows=len(rows), reason=shed.reason)
            return fut
        pending = _Pending(rows, self.admission.deadline_for(timeout_ms))
        with self._work:
            if self._closing or self._closed:
                # closing raced the unlocked check above: give back the
                # admission reservation and shed — NEVER enqueue into a
                # queue the dispatcher may already consider drained
                self.admission.release(len(rows))
                self.metrics.record_shed(len(rows),
                                         reason="shutting_down")
                end_span(admit_span, outcome="shed:shutting_down")
                fut.set_result([ShedResult(reason="shutting_down")
                                for _ in rows])
                return fut
            self.metrics.record_admitted(len(rows))
            self._queue.append(pending)
            self._arrivals.append((pending.enqueued_at, len(rows)))
            if len(self._arrivals) > 256:
                del self._arrivals[:128]
            self.metrics.set_queue_depth(
                sum(len(p.rows) for p in self._queue))
            self._work.notify()
        end_span(admit_span, outcome="admitted")
        return pending.future

    def _est_drain_ms(self) -> Optional[float]:
        """Rough retry-after hint: predicted batch wall (continuous) or one
        coalescing window (windowed) per queued batch."""
        with self._lock:
            queued = sum(len(p.rows) for p in self._queue)
        if queued == 0:
            return None
        batches = (queued + self.max_batch - 1) // self.max_batch
        per_batch_s = self.max_latency_s
        if self.cost_lookup is not None:
            per_batch_s = self.cost_lookup.predict_s(self.max_batch)
        return batches * per_batch_s * 1000.0

    # -- batch formation ----------------------------------------------------

    def _take_batch_locked(self, target: Optional[int] = None,
                           strict: bool = False) -> List[_Pending]:
        """Pop requests FIFO until the row budget is hit.  A request is
        never split across batches (its rows stay one contiguous slice);
        an oversized FIRST request is taken anyway (the executor chunks)
        unless ``strict`` — the late-admission path, where exceeding the
        already-chosen bucket would defeat the choice."""
        budget = self.max_batch if target is None else target
        batch: List[_Pending] = []
        rows = 0
        while self._queue:
            nxt = self._queue[0]
            if (batch or strict) and rows + len(nxt.rows) > budget:
                break
            batch.append(self._queue.pop(0))
            rows += len(nxt.rows)
            if rows >= budget:
                break
        return batch

    def _arrival_rate_locked(self) -> float:
        """Instantaneous arrival rate in rows/second (lock held), from
        the span of the most recent K submits.  Closed-loop traffic is
        BURSTY — all waiting callers resubmit within a couple of
        milliseconds of a batch resolving, then go quiet while the next
        batch runs — so a fixed-horizon average smears the burst down to
        the mean throughput and never projects a fillable big bucket.
        The recent-K span reads the burst as it happens and reads a lone
        caller (whose K recent submits span seconds) as ~nothing."""
        if len(self._arrivals) < 4:
            return 0.0   # too few samples to call anything a burst
        now = time.monotonic()
        recent = self._arrivals[-16:]
        # stale arrivals mean no burst is in progress
        if now - recent[-1][0] > 0.02:
            return 0.0
        span = max(now - recent[0][0], 5e-4)
        return sum(n for _t, n in recent) / span

    def _formation_locked(self, queued_rows: int
                          ) -> Tuple[int, float]:
        """Two-regime formation: ``(target_bucket, fill_wait_s)``.

        **Throughput mode** — when the instantaneous arrival rate
        projects that ``max_batch`` can fill within a generous horizon
        (2× ``max_latency_ms``), target the full bucket and hold the
        forming batch open up to the projected fill time (hard-capped at
        ``max_latency_ms``, the same bound the windowed mode pays).
        Closed-loop saturation is bursty — every resolved batch wakes its
        callers, who resubmit within a couple of milliseconds — and
        per-dispatch cost is floor-heavy, so full batches are what
        sustains peak rows/s; dispatching the fragment that happens to be
        queued mid-burst fragments the convoy permanently.

        **Latency mode** — otherwise (no burst in progress) pick the
        dispatch-NOW bucket greedily by predicted service rate
        ``servable / cost(b)`` and don't wait at all: a lone request
        under light load leaves immediately, which is the
        continuous-batching win over a fixed window.

        Mode choice is HYSTERETIC: a near-full formed batch latches
        saturation (momentarily-quiet arrival probes mid-burst must not
        leak convoy-breaking fragments); a fill hold expiring under-
        filled unlatches it."""
        deficit = self.max_batch - queued_rows
        if deficit <= 0:
            return (self.max_batch, 0.0)
        rate = self._arrival_rate_locked()
        if rate > 0:
            wait = deficit / rate
            if wait <= 2.0 * self.max_latency_s:
                return (self.max_batch,
                        min(wait * 1.25, self.max_latency_s))
        if self._saturated:
            return (self.max_batch, self.max_latency_s)
        return (self._choose_bucket(queued_rows), 0.0)

    def _choose_bucket(self, queued_rows: int) -> int:
        """Target bucket for what is queued right now (no hold-open
        component) — the formation policy's dispatch-now half, used
        directly by the multi-tenant dispatcher."""
        lookup = self.cost_lookup
        best_b, best_rate = self._buckets[0], -1.0
        for b in self._buckets:
            servable = min(queued_rows, b)
            if servable <= 0:
                break
            cost = (lookup.predict_s(b) if lookup is not None
                    else 1e-4 + b * 2e-5)
            score = servable / max(cost, 1e-9)
            if score >= best_rate:
                best_rate, best_b = score, b
        return best_b

    # -- dispatch: continuous ------------------------------------------------

    def _dispatch_continuous(self) -> None:
        while True:
            with self._work:
                while not self._queue and not self._closed:
                    self._work.wait(timeout=0.1)
                if self._closed and not self._queue:
                    return
                queued = sum(len(p.rows) for p in self._queue)
                target, fill_wait = self._formation_locked(queued)
                batch = self._take_batch_locked(target)
                rows = sum(len(p.rows) for p in batch)
                # late admission up to dispatch: when the formation policy
                # chose to hold the batch open (fill_wait > 0), admit
                # arrivals into the forming batch until the bucket fills
                # or the projected-fill deadline passes.  Skipped when
                # closing (drain wants the queue empty, not fuller
                # batches).
                if rows < target and fill_wait > 0 and not self._closing:
                    deadline = time.monotonic() + fill_wait
                    while rows < target and not self._closing:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._work.wait(timeout=remaining)
                        late = self._take_batch_locked(target - rows,
                                                       strict=True)
                        batch.extend(late)
                        rows += sum(len(p.rows) for p in late)
                # saturation hysteresis: a near-full batch latches
                # throughput mode; a hold that expired nearly EMPTY
                # (quarter bucket) means load really dropped — unlatch.
                # The asymmetric thresholds stop a single scheduler
                # stall from unlatching mid-convoy (the fragment cascade
                # that follows costs far more than one held batch).
                if rows >= self.max_batch // 2:
                    self._saturated = True
                elif fill_wait > 0 and rows < max(1, self.max_batch // 4):
                    self._saturated = False
                self.metrics.set_queue_depth(
                    sum(len(p.rows) for p in self._queue))
            if batch:
                self._run_batch(batch, target=target)

    # -- dispatch: windowed (PR 1 semantics, byte-identical) -----------------

    def _dispatch_windowed(self) -> None:
        while True:
            with self._work:
                while not self._queue and not self._closed:
                    self._work.wait(timeout=0.1)
                if self._closed and not self._queue:
                    return
                # coalescing window: wait for more arrivals until the
                # OLDEST request has waited max_latency or the batch fills
                oldest = self._queue[0].enqueued_at
                while (sum(len(p.rows) for p in self._queue) < self.max_batch
                       and not self._closed):
                    remaining = self.max_latency_s - (time.monotonic() - oldest)
                    if remaining <= 0:
                        break
                    self._work.wait(timeout=remaining)
                    if not self._queue:
                        break
                batch = self._take_batch_locked()
                self.metrics.set_queue_depth(
                    sum(len(p.rows) for p in self._queue))
            if batch:
                self._run_batch(batch)

    # -- execution -----------------------------------------------------------

    def _run_batch(self, batch: List[_Pending],
                   target: Optional[int] = None) -> None:
        n_rows = sum(len(p.rows) for p in batch)
        batch_span = begin_span(
            "serve.batch", cat="serve", requests=len(batch),
            rows=n_rows, mode=self.mode,
            **({"bucket": target} if target is not None else {}))
        t0 = time.perf_counter()
        try:
            self._run_batch_inner(batch)
        finally:
            wall = time.perf_counter() - t0
            if self.cost_lookup is not None and n_rows > 0:
                # feed the dispatch occupancy back into the formation
                # policy: the EWMA converges on measured batch walls
                self.cost_lookup.observe(
                    bucket_for(min(n_rows, self.max_batch), self._buckets),
                    wall)
            end_span(batch_span)
            # wake a close(drain=True) waiting on queue-empty
            with self._work:
                self._work.notify_all()

    def _run_batch_inner(self, batch: List[_Pending]) -> None:
        run_pending_batch(batch, self.execute, self.admission, self.metrics)


def run_pending_batch(batch: List[_Pending], execute, admission,
                      metrics) -> None:
    """Resolve one formed batch: expire past-deadline pendings, release
    their admission reservations, execute the live rows, and scatter
    results back onto the per-request futures.  Shared by the single-
    tenant dispatch thread and the multi-tenant WFQ dispatcher
    (serving/tenancy.py) so the two paths cannot diverge."""
    now = time.monotonic()
    live: List[_Pending] = []
    n_released = 0
    for p in batch:
        n_released += len(p.rows)
        if p.deadline is not None and now > p.deadline:
            metrics.record_deadline_expired(len(p.rows))
            p.future.set_result(
                [ShedResult(reason="deadline_expired")
                 for _ in p.rows])
        else:
            live.append(p)
    admission.release(n_released)
    if not live:
        return
    rows: List[Dict[str, Any]] = []
    for p in live:
        rows.extend(p.rows)
    try:
        results = execute(rows)
    except Exception as exc:  # last-resort: executor+fallback both died
        for p in live:
            if not p.future.done():
                p.future.set_exception(exc)
        return
    off = 0
    for p in live:
        p.future.set_result(results[off:off + len(p.rows)])
        off += len(p.rows)
        metrics.record_request_latency(
            time.monotonic() - p.enqueued_at)
