"""Admission queue + adaptive micro-batcher.

Concurrent callers submit small row lists; a single dispatch thread
coalesces them into one micro-batch up to ``max_batch`` rows or until the
oldest waiting request has waited ``max_latency_ms`` — the classic
serving trade: a request never waits more than the coalescing deadline,
and under load batches fill to the cap so per-dispatch overhead (host↔
device round trip, program launch) amortizes across requests.

The batcher is transport-agnostic: ``execute`` is any
``rows -> score maps`` callable (the server wires in the circuit-breaker +
bucketed executor).  Results come back on per-request futures; shed and
expired requests resolve to ``ShedResult``s, not exceptions.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..obs.flight import record_event
from ..obs.trace import begin_span, end_span
from .admission import AdmissionController, ShedResult
from .metrics import ServingMetrics

__all__ = ["MicroBatcher"]


class _Pending:
    __slots__ = ("rows", "future", "deadline", "enqueued_at")

    def __init__(self, rows: List[Dict[str, Any]],
                 deadline: Optional[float]):
        self.rows = rows
        self.future: "Future[List[Any]]" = Future()
        self.deadline = deadline
        self.enqueued_at = time.monotonic()


class MicroBatcher:
    def __init__(self, execute: Callable[[List[Dict[str, Any]]], List[Any]],
                 max_batch: int = 64, max_latency_ms: float = 5.0,
                 admission: Optional[AdmissionController] = None,
                 metrics: Optional[ServingMetrics] = None):
        self.execute = execute
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_ms) / 1000.0
        self.admission = admission or AdmissionController()
        self.metrics = metrics or ServingMetrics()
        self._queue: List[_Pending] = []
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None or not self._thread.is_alive():
            self._closed = False
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="op-serving-batcher",
                daemon=True)
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the dispatch thread; by default drain queued work first."""
        if drain and self._thread is not None and self._thread.is_alive():
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._queue:
                        break
                time.sleep(0.001)
        with self._work:
            self._closed = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- submission ---------------------------------------------------------

    def submit(self, rows: Sequence[Dict[str, Any]],
               timeout_ms: Optional[float] = None) -> "Future[List[Any]]":
        """Enqueue ``rows`` for coalesced scoring.

        Always returns a future.  Overload resolves it IMMEDIATELY with one
        ``ShedResult`` per row; otherwise it resolves with the score maps
        (or ``ShedResult``s if the deadline expires while queued).
        """
        rows = list(rows)
        fut: "Future[List[Any]]" = Future()
        if not rows:
            fut.set_result([])
            return fut
        admit_span = begin_span("serve.admit", cat="serve", rows=len(rows))
        if self._closed:
            fut.set_result([ShedResult(reason="shutting_down")
                            for _ in rows])
            self.metrics.record_shed(len(rows))
            end_span(admit_span, outcome="shed:shutting_down")
            return fut
        shed = self.admission.try_admit(
            len(rows), est_drain_ms=self._est_drain_ms())
        if shed is not None:
            self.metrics.record_shed(len(rows))
            fut.set_result([shed for _ in rows])
            end_span(admit_span, outcome=f"shed:{shed.reason}")
            record_event("serve.shed", rows=len(rows), reason=shed.reason)
            return fut
        self.metrics.record_admitted(len(rows))
        end_span(admit_span, outcome="admitted")
        pending = _Pending(rows, self.admission.deadline_for(timeout_ms))
        with self._work:
            self._queue.append(pending)
            self.metrics.set_queue_depth(
                sum(len(p.rows) for p in self._queue))
            self._work.notify()
        return pending.future

    def _est_drain_ms(self) -> Optional[float]:
        """Rough retry-after hint: one coalescing window per queued batch."""
        with self._lock:
            queued = sum(len(p.rows) for p in self._queue)
        if queued == 0:
            return None
        batches = (queued + self.max_batch - 1) // self.max_batch
        return batches * self.max_latency_s * 1000.0

    # -- dispatch -----------------------------------------------------------

    def _take_batch_locked(self) -> List[_Pending]:
        """Pop requests FIFO until the row budget is hit.  A request is
        never split across batches (its rows stay one contiguous slice)."""
        batch: List[_Pending] = []
        rows = 0
        while self._queue:
            nxt = self._queue[0]
            if batch and rows + len(nxt.rows) > self.max_batch:
                break
            batch.append(self._queue.pop(0))
            rows += len(nxt.rows)
            if rows >= self.max_batch:
                break
        return batch

    def _dispatch_loop(self) -> None:
        while True:
            with self._work:
                while not self._queue and not self._closed:
                    self._work.wait(timeout=0.1)
                if self._closed and not self._queue:
                    return
                # coalescing window: wait for more arrivals until the
                # OLDEST request has waited max_latency or the batch fills
                oldest = self._queue[0].enqueued_at
                while (sum(len(p.rows) for p in self._queue) < self.max_batch
                       and not self._closed):
                    remaining = self.max_latency_s - (time.monotonic() - oldest)
                    if remaining <= 0:
                        break
                    self._work.wait(timeout=remaining)
                    if not self._queue:
                        break
                batch = self._take_batch_locked()
                self.metrics.set_queue_depth(
                    sum(len(p.rows) for p in self._queue))
            if batch:
                self._run_batch(batch)

    def _run_batch(self, batch: List[_Pending]) -> None:
        batch_span = begin_span(
            "serve.batch", cat="serve", requests=len(batch),
            rows=sum(len(p.rows) for p in batch))
        try:
            self._run_batch_inner(batch)
        finally:
            end_span(batch_span)

    def _run_batch_inner(self, batch: List[_Pending]) -> None:
        now = time.monotonic()
        live: List[_Pending] = []
        n_released = 0
        for p in batch:
            n_released += len(p.rows)
            if p.deadline is not None and now > p.deadline:
                self.metrics.record_deadline_expired(len(p.rows))
                p.future.set_result(
                    [ShedResult(reason="deadline_expired")
                     for _ in p.rows])
            else:
                live.append(p)
        self.admission.release(n_released)
        if not live:
            return
        rows: List[Dict[str, Any]] = []
        for p in live:
            rows.extend(p.rows)
        try:
            results = self.execute(rows)
        except Exception as exc:  # last-resort: executor+fallback both died
            for p in live:
                if not p.future.done():
                    p.future.set_exception(exc)
            return
        off = 0
        for p in live:
            p.future.set_result(results[off:off + len(p.rows)])
            off += len(p.rows)
            self.metrics.record_request_latency(
                time.monotonic() - p.enqueued_at)
