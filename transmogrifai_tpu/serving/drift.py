"""Serving-side drift detection — train/serve distribution comparison.

The closing move of ROADMAP item 4: the vectorizers already export a
train-side distribution snapshot onto the fitted model
(``metadata["drift_baseline"]`` — Welford moments + StreamingHistogram
bins for numerics, top-category counts for categoricals; see
ops/vectorizers.py), so a server only needs to maintain the SAME sketch
monoids over sampled scoring traffic and compare.  Comparison is
per-feature:

* **PSI** (population stability index) between the baseline histogram /
  category frequencies and the serving-window ones — the standard
  deployment-drift metric; >0.25 is the conventional "significant shift"
  line and the default threshold here.
* **moment z-score** — a two-sample z on the means (pooled baseline +
  window variance), catching location shifts PSI's binning can smear.

A window is evaluated once ``min_rows`` sampled rows accumulate (and
every ``check_every`` rows after); any feature crossing a threshold sets
``refresh_triggered`` and fires the ``on_drift`` callback — the hook a
refresh driver (``OpWorkflow.refresh`` + serving/guarded.py) closes the
loop on.  The ``drift.window`` fault point (utils/faults.py) fires at
every evaluation so the whole drift→refresh→swap matrix is
seed-deterministic to test.

The monitor is deliberately host-cheap: sampling is a seeded Bernoulli
per request batch, updates are the same vectorized sketch updates the
streaming fitters use, and evaluation is a few dozen-element numpy ops.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils import faults
from ..utils.sketches import WelfordMoments
from ..utils.streaming_histogram import StreamingHistogram

__all__ = ["DriftMonitor", "DriftConfig", "export_drift_baselines",
           "psi_from_counts"]

#: PSI smoothing epsilon: a category/bin absent on one side contributes a
#: large-but-finite term instead of infinity
_PSI_EPS = 1e-4


def export_drift_baselines(model) -> Dict[str, Dict[str, Any]]:
    """Collect every fitted stage's exported drift baseline from a
    workflow model: {raw feature name -> baseline dict}.  Later stages
    win on (unexpected) name collisions."""
    out: Dict[str, Dict[str, Any]] = {}
    for stage in getattr(model, "stages", []):
        base = (stage.metadata or {}).get("drift_baseline")
        if isinstance(base, dict):
            for name, rec in base.items():
                if isinstance(rec, dict) and "kind" in rec:
                    out[name] = rec
    return out


def _anchored_cdf(centroids, counts, lo, hi):
    """(xs, ys) support points of the Ben-Haim/Tom-Tov interpolated CDF:
    mass linear between adjacent centroids (half a centroid's count on
    each side), ANCHORED at the observed min/max so the curve resolves
    below the first and above the last centroid — a heavy-tailed column
    merges ~30% of its mass into one low centroid, and without the
    anchor the CDF there is a step that reads as drift."""
    c = np.asarray(centroids, np.float64)
    n = np.asarray(counts, np.float64)
    total = n.sum()
    cum_mid = np.cumsum(n) - n / 2.0
    xs, ys = list(c), list(cum_mid)
    if lo is not None and (not xs or lo < xs[0]):
        xs, ys = [float(lo)] + xs, [0.0] + ys
    if hi is not None and (not xs or hi > xs[-1]):
        xs, ys = xs + [float(hi)], ys + [total]
    return np.asarray(xs), np.asarray(ys), total


def _interp_cell_masses(centroids, counts, edges, lo=None,
                        hi=None) -> np.ndarray:
    """Per-cell mass of a merged-centroid histogram on ``edges`` via the
    anchored interpolated CDF.  Whole-centroid cell assignment
    (``StreamingHistogram.density``) books a fat merged centroid
    entirely into one cell, which reads as drift when it is only bin
    quantization; the interpolation spreads it smoothly and the
    artifact cancels between the two sides."""
    edges = np.asarray(edges, np.float64)
    if np.asarray(counts).size == 0 or np.asarray(counts).sum() <= 0:
        return np.zeros(edges.size + 1)
    xs, ys, total = _anchored_cdf(centroids, counts, lo, hi)
    cdf = np.interp(edges, xs, ys, left=0.0, right=total)
    return np.diff(np.concatenate([[0.0], cdf, [total]]))


def psi_from_counts(expected, observed) -> float:
    """PSI between two aligned count vectors (eps-smoothed proportions)."""
    e = np.asarray(expected, np.float64)
    o = np.asarray(observed, np.float64)
    if e.sum() <= 0 or o.sum() <= 0:
        return 0.0
    p = np.maximum(e / e.sum(), _PSI_EPS)
    q = np.maximum(o / o.sum(), _PSI_EPS)
    p, q = p / p.sum(), q / q.sum()
    return float(((q - p) * np.log(q / p)).sum())


class DriftConfig:
    """Thresholds + sampling knobs for a DriftMonitor."""

    def __init__(self, sample_rate: float = 1.0, min_rows: int = 200,
                 check_every: Optional[int] = None,
                 psi_threshold: float = 0.25, z_threshold: float = 8.0,
                 max_bins: int = 32, seed: int = 7):
        self.sample_rate = float(sample_rate)
        self.min_rows = int(min_rows)
        self.check_every = int(check_every or min_rows)
        self.psi_threshold = float(psi_threshold)
        self.z_threshold = float(z_threshold)
        self.max_bins = int(max_bins)
        self.seed = int(seed)

    def to_json(self) -> Dict[str, Any]:
        return {"sampleRate": self.sample_rate, "minRows": self.min_rows,
                "checkEvery": self.check_every,
                "psiThreshold": self.psi_threshold,
                "zThreshold": self.z_threshold}


class _NumericTracker:
    __slots__ = ("mom", "hist")

    def __init__(self, max_bins: int):
        self.mom = WelfordMoments()
        self.hist = StreamingHistogram(max_bins)

    def update(self, values: List[float]) -> None:
        v = np.asarray(values, np.float64)
        v = v[np.isfinite(v)]
        if v.size:
            self.mom.update(v)
            self.hist.update(v)


class _CategoricalTracker:
    __slots__ = ("counts", "n")

    def __init__(self):
        self.counts: Dict[str, float] = {}
        self.n = 0.0

    def update(self, values: List[str]) -> None:
        for v in values:
            self.counts[v] = self.counts.get(v, 0.0) + 1.0
            self.n += 1.0


class DriftMonitor:
    """Compares sampled scoring traffic against train-side baselines.

    Thread-safe: ``observe_rows`` runs on the serving dispatch thread,
    ``snapshot`` on HTTP handler threads.  Evaluation happens inline on
    the observing thread at the ``check_every`` cadence (a few numpy ops
    over <=64-element vectors — cheaper than one scoring batch).
    """

    def __init__(self, baselines: Dict[str, Dict[str, Any]],
                 config: Optional[DriftConfig] = None,
                 on_drift: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.baselines = dict(baselines)
        self.config = config or DriftConfig()
        self.on_drift = on_drift
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(self.config.seed)
        self._trackers: Dict[str, Any] = {}
        self._window_rows = 0
        self._rows_since_eval = 0
        self.rows_observed = 0
        self.windows_evaluated = 0
        self.drift_fires = 0
        self.refresh_triggered = False
        self.last_evaluation: Optional[Dict[str, Any]] = None
        self._reset_trackers()

    @classmethod
    def from_model(cls, model, config: Optional[DriftConfig] = None,
                   on_drift=None) -> "DriftMonitor":
        """Build a monitor from a fitted/loaded workflow model's exported
        baselines (ops/vectorizers.py ``metadata["drift_baseline"]``)."""
        return cls(export_drift_baselines(model), config=config,
                   on_drift=on_drift)

    def _reset_trackers(self) -> None:
        self._trackers = {}
        for name, base in self.baselines.items():
            if base.get("kind") == "numeric":
                self._trackers[name] = _NumericTracker(self.config.max_bins)
            elif base.get("kind") == "categorical":
                self._trackers[name] = _CategoricalTracker()

    # -- observation ---------------------------------------------------------

    def observe_rows(self, rows: Sequence[Dict[str, Any]]) -> None:
        """Fold a scoring batch's raw rows into the current window
        (sampled at ``sample_rate`` per batch, seeded — deterministic for
        a fixed request sequence)."""
        if not rows or not self._trackers:
            return
        with self._lock:
            if (self.config.sample_rate < 1.0
                    and self._rng.random() >= self.config.sample_rate):
                return
            for name, tracker in self._trackers.items():
                if isinstance(tracker, _NumericTracker):
                    vals = [r.get(name) for r in rows]
                    tracker.update([float(v) for v in vals
                                    if isinstance(v, (int, float))])
                else:
                    vals = [r.get(name) for r in rows]
                    flat: List[str] = []
                    for v in vals:
                        if v is None:
                            continue
                        if isinstance(v, (list, tuple, set, frozenset)):
                            flat.extend(str(x) for x in v)
                        else:
                            flat.append(str(v))
                    tracker.update(flat)
            self._window_rows += len(rows)
            self._rows_since_eval += len(rows)
            self.rows_observed += len(rows)
            due = (self._window_rows >= self.config.min_rows
                   and self._rows_since_eval >= self.config.check_every)
        if due:
            self.evaluate()

    # -- evaluation ----------------------------------------------------------

    def _feature_drift(self, name: str, base: Dict[str, Any],
                       tracker) -> Optional[Dict[str, Any]]:
        if isinstance(tracker, _NumericTracker):
            if tracker.mom.mean is None or base.get("n", 0) <= 1:
                return None
            n_b, n_s = float(base["n"]), float(tracker.mom.n)
            var_b = float(base["m2"]) / max(n_b - 1.0, 1.0)
            var_s = float(tracker.mom.variance(ddof=1))
            delta = abs(float(tracker.mom.mean) - float(base["mean"]))
            denom = math.sqrt(max(var_b / n_b + var_s / max(n_s, 1.0),
                                  1e-300))
            z = delta / denom if delta > 0 else 0.0
            # PSI on the baseline's DECILE grid (the conventional ~10
            # PSI buckets): both histograms are merged-centroid sketches,
            # and comparing them cell-per-centroid would book pure bin-
            # boundary quantization as drift — deciles give each cell
            # ~10% expected mass, far above the quantization noise
            psi = 0.0
            centroids = np.asarray(base["histCentroids"], np.float64)
            counts = np.asarray(base["histCounts"], np.float64)
            if centroids.size >= 2 and counts.sum() > 0:
                # decile grid from the baseline's anchored CDF (the
                # conventional ~10 PSI buckets, ~10% expected mass each)
                xs, ys, total = _anchored_cdf(
                    centroids, counts, base.get("min"), base.get("max"))
                grid = np.unique(np.interp(
                    np.linspace(0.1, 0.9, 9) * total, ys, xs))
                if grid.size >= 1:
                    psi = psi_from_counts(
                        _interp_cell_masses(centroids, counts, grid,
                                            base.get("min"),
                                            base.get("max")),
                        _interp_cell_masses(
                            tracker.hist.centroids, tracker.hist.counts,
                            grid, tracker.mom.min, tracker.mom.max))
            drifted = (psi > self.config.psi_threshold
                       or z > self.config.z_threshold)
            return {"kind": "numeric", "psi": round(psi, 4),
                    "z": round(min(z, 1e9), 3),
                    "baselineMean": float(base["mean"]),
                    "windowMean": float(tracker.mom.mean),
                    "rows": int(n_s), "drifted": drifted}
        # categorical: align the window counts onto the baseline's
        # category list; everything unseen at train time pools into OTHER
        if tracker.n <= 0 or base.get("n", 0) <= 0:
            return None
        values = list(base.get("values", []))
        base_counts = np.asarray(base.get("counts", []), np.float64)
        known = set(values)
        obs = np.array([tracker.counts.get(v, 0.0) for v in values]
                       + [sum(c for k, c in tracker.counts.items()
                              if k not in known)], np.float64)
        exp_other = max(float(base["n"]) - float(base_counts.sum()), 0.0)
        exp = np.concatenate([base_counts, [exp_other]])
        psi = psi_from_counts(exp, obs)
        drifted = psi > self.config.psi_threshold
        return {"kind": "categorical", "psi": round(psi, 4),
                "rows": int(tracker.n), "drifted": drifted}

    def evaluate(self) -> Dict[str, Any]:
        """Score the current window against the baselines; rolls the
        window forward (trackers reset) and records the result."""
        with self._lock:
            faults.fire("drift.window", index=self.windows_evaluated)
            features: Dict[str, Any] = {}
            for name, base in self.baselines.items():
                tracker = self._trackers.get(name)
                if tracker is None:
                    continue
                rec = self._feature_drift(name, base, tracker)
                if rec is not None:
                    features[name] = rec
            drifted = sorted(n for n, r in features.items() if r["drifted"])
            result = {
                "at": time.time(),
                "windowRows": self._window_rows,
                "features": features,
                "driftedFeatures": drifted,
                "drifted": bool(drifted),
            }
            self.windows_evaluated += 1
            self._window_rows = 0
            self._rows_since_eval = 0
            self._reset_trackers()
            self.last_evaluation = result
            fired = bool(drifted) and not self.refresh_triggered
            if drifted:
                self.drift_fires += 1
                self.refresh_triggered = True
            cb = self.on_drift if fired else None
        from ..obs.flight import record_event

        record_event("drift.window", drifted=bool(drifted),
                     features=list(drifted),
                     windowRows=result["windowRows"])
        if fired:
            record_event("drift.trigger", features=list(drifted))
        if cb is not None:
            try:
                cb(result)
            except Exception:  # callbacks must not break the serving path
                pass
        return result

    def clear_refresh_trigger(self) -> None:
        """Acknowledge a handled refresh trigger (the refresh driver calls
        this after a successful guarded swap)."""
        with self._lock:
            self.refresh_triggered = False

    # -- reading -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view for ``/metrics``."""
        with self._lock:
            return {
                "config": self.config.to_json(),
                "trackedFeatures": len(self._trackers),
                "rowsObserved": self.rows_observed,
                "windowRows": self._window_rows,
                "windowsEvaluated": self.windows_evaluated,
                "driftFires": self.drift_fires,
                "refreshTriggered": self.refresh_triggered,
                "lastEvaluation": self.last_evaluation,
            }
