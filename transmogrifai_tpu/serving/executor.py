"""Shape-bucketed batch executor — warm compiled programs, zero steady-state
recompiles.

Why buckets: every distinct batch size N is a distinct XLA program shape —
the model kernels under the scoring DAG are jitted on ``(N, D)`` arrays, so
serving raw request sizes would compile a fresh multi-second program for
every new N (docs/performance.md).  Padding each micro-batch up to a
power-of-2 bucket caps the program count at ``log2(max_batch)+1``, all of
which are compiled ONCE at warmup; after that the device only ever sees
shapes it has already compiled.

Padding discipline: pad rows are copies of a real row (never synthetic
zeros — a synthetic row could take host-side code paths a real row never
takes), and results are sliced back to the true row count before anyone
sees them, so padding cannot leak into responses.  Scoring is row-wise
independent (columnar transforms + per-row model predictions), which the
serving parity test pins: bucketed scores must be byte-identical to the
unpadded host scorer's.

Accounting: each bucket's first execution records a ``compile`` in
``utils/compile_cache``; every reuse records a ``hit`` — the counters the
zero-recompile acceptance test asserts on.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils import compile_cache

__all__ = ["BucketedExecutor", "bucket_sizes", "bucket_for"]


def bucket_sizes(max_batch: int, min_bucket: int = 1) -> List[int]:
    """Power-of-2 ladder ``[min_bucket, ..., max_batch]`` (max included
    even when not a power of 2 — the coalescer's cap must be servable)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out: List[int] = []
    b = max(1, int(min_bucket))
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (buckets ascending; n <= buckets[-1])."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} rows exceeds max bucket {buckets[-1]}")


class BucketedExecutor:
    """Pads micro-batches to warm shape buckets and runs the score program.

    ``score_fn`` is a ``rows -> score maps`` callable (normally
    ``local.scorer.score_function_batch(model)``); the executor owns the
    shape discipline around it.
    """

    def __init__(self, score_fn: Callable[[List[Dict[str, Any]]],
                                          List[Dict[str, Any]]],
                 max_batch: int = 64, min_bucket: int = 1,
                 cache_key_prefix: str = "serving",
                 model: Any = None, aot_store: Any = None,
                 device_programs: bool = False):
        self.score_fn = score_fn
        self.buckets = bucket_sizes(max_batch, min_bucket)
        self.max_batch = self.buckets[-1]
        self.cache_key_prefix = cache_key_prefix
        self._warm: Dict[int, bool] = {}
        #: opt-in AOT/device scoring: per-bucket compiled programs for the
        #: model's predictor stage, loadable from the persistent AOT store
        #: (serving/aot.py).  None keeps the PR 1 host path byte-identical.
        self.programs = None
        if device_programs and model is not None:
            from .aot import program_set_for

            self.programs = program_set_for(
                model, store=aot_store, cache_key_prefix=cache_key_prefix)
        # best effort: cross-process persistent cache on top of the
        # in-process warm set (first warmup of a fresh replica reuses the
        # previous replica's XLA programs where the platform allows it)
        compile_cache.enable_persistent_cache()

    # -- warmup -------------------------------------------------------------

    def warmup(self, sample_row: Dict[str, Any],
               buckets: Optional[Sequence[int]] = None) -> Dict[int, float]:
        """Make every bucket's program warm up front; returns
        {bucket: seconds}.

        Order is LARGEST-FIRST: under load the first live batches coalesce
        toward ``max_batch``, so the big buckets are the ones real traffic
        hits first — smallest-first used to leave exactly those cold
        through the initial burst.

        With a program set attached, a bucket already satisfied by the AOT
        store is a *load* (milliseconds, no trace/compile) and skips the
        full scoring warm-run entirely — the host half of the scoring DAG
        is numpy (nothing to warm), and the executable needs no first
        execution to be warm.  Cold buckets JIT-compile and write the
        serialized executable through for the next replica.
        """
        order = sorted(buckets if buckets is not None else self.buckets,
                       reverse=True)
        timings: Dict[int, float] = {}
        for b in order:
            t0 = time.perf_counter()
            if self.programs is not None:
                if self.programs.ensure_bucket(b) == "aot":
                    # AOT-satisfied: no warm-run needed, the executable is
                    # already the steady-state artifact — warm immediately
                    self._warm[b] = True
                    timings[b] = time.perf_counter() - t0
                    continue
            # JIT case: _run_bucket marks the bucket warm only AFTER the
            # warm-run succeeds — a failed first execution stays cold
            self._run_bucket([dict(sample_row)] * b, b)
            timings[b] = time.perf_counter() - t0
        return timings

    @property
    def warm_buckets(self) -> List[int]:
        return sorted(self._warm)

    # -- execution ----------------------------------------------------------

    def _cache_key(self, bucket: int) -> str:
        return f"{self.cache_key_prefix}.bucket{bucket}"

    def _run_bucket(self, padded_rows: List[Dict[str, Any]],
                    bucket: int) -> List[Dict[str, Any]]:
        first = bucket not in self._warm
        if self.programs is not None:
            from .aot import device_scoring

            # a bucket the warmup never covered (direct caller, resized
            # ladder) compiles lazily here — counted, like any first
            # execution
            if first:
                self.programs.ensure_bucket(bucket)
            with device_scoring():
                out = self.score_fn(padded_rows)
        else:
            out = self.score_fn(padded_rows)
        # count only AFTER success: a failed first execution must stay a
        # cold bucket (and must not skew the zero-recompile assertion)
        if first:
            self._warm[bucket] = True
            compile_cache.record_compile(self._cache_key(bucket))
        else:
            compile_cache.record_hit(self._cache_key(bucket))
        return out

    def score(self, rows: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Score ``rows`` (1..max_batch of them) through the bucketed path."""
        rows = list(rows)
        n = len(rows)
        if n == 0:
            return []
        if n > self.max_batch:
            # callers (the micro-batcher) never exceed max_batch; a direct
            # caller gets chunking rather than an error
            out: List[Dict[str, Any]] = []
            for i in range(0, n, self.max_batch):
                out.extend(self.score(rows[i:i + self.max_batch]))
            return out
        bucket = bucket_for(n, self.buckets)
        padded = rows + [dict(rows[-1]) for _ in range(bucket - n)]
        return self._run_bucket(padded, bucket)[:n]
