"""Pod-scale serving fabric — fault-tolerant multi-host serving plane.

ROADMAP open item 2 (the "millions of users" half of the north star): the
serving plane of PRs 1/13 is one process; this module is the shared-
nothing ROUTER over N per-host ``ModelServer``/``MultiTenantServer``
replicas that makes it a fleet.  The TPU serving comparison (PAPERS.md)
makes the two points the design reproduces: cold-start/compile reuse
dominates fleet elasticity (the shared :class:`~transmogrifai_tpu.utils.
compile_cache.AOTStore` directory — one host's compile warms every later
cold start), and tail latency under replica CHURN — not steady-state
throughput — is what distinguishes a production tier (health-routed
failover with zero failed requests through a host SIGKILL).

Layers:

* **placement** — :class:`HashRing`: consistent-hash tenant→host mapping
  over virtual nodes (stable digests, never Python ``hash()``), so every
  router instance computes the SAME placement and adding a host remaps
  only the tenants it takes over;
* **health** — :meth:`ServingFabric.probe_once` polls every host's
  ``/healthz`` (heartbeat age + breaker state + shed rate); eviction and
  readmission are HYSTERETIC (consecutive-failure/age thresholds to
  evict, ``readmit_probes`` consecutive healthy probes to readmit) so a
  flapping host cannot oscillate in and out of rotation;
* **routing** — per-request deadline budgets; bounded spill to the next
  ring neighbors under quota pressure (``max_spill``); single-retry
  failover to a survivor on transport failure (idempotent scoring makes
  the retry safe, and the router-level tenant quota is acquired ONCE per
  request so a retried request never double-counts);
* **drain vs kill** — a draining host (SIGTERM → ``begin_drain`` → shed
  new admissions with reason ``"draining"`` → in-flight completes →
  deregister) leaves rotation gracefully; a SIGKILLed host is evicted by
  heartbeat timeout and its in-flight requests are retried to survivors
  — the zero-failed-requests path bench_serving's pod leg gates on;
* **control channel** — :class:`ControlChannel` rides the PR 15 host-
  collective substrate (``PodContext.broadcast_obj``/``allgather_obj``)
  so registry swaps/rollbacks and drift baselines are FLEET-consistent:
  :class:`FleetSwapController` makes a ``GuardedSwap``-style bake verdict
  collective — a bake failure on ANY replica vetoes the fleet swap, and
  a rollback rolls every replica back.

Determinism: failover choices are a pure function of ring order + health
state, and retry jitter comes from a stateless seeded draw keyed on
``(seed, request, attempt)`` (like ``readers/resilience.RetryPolicy`` —
never ``random`` module state), so two routers at one seed make identical
choices and the SIGKILL bench leg replays byte-identically.  The
``host.heartbeat`` / ``router.forward`` / ``swap.propagate`` fault points
(utils/faults.py) make the whole failover/veto matrix seed-testable.
"""
from __future__ import annotations

import bisect
import hashlib
import json
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..obs.flight import record_event
from ..utils import faults
from .admission import ShedResult
from .guarded import probe_digest
from .metrics import LatencyReservoir

__all__ = ["HashRing", "HostUnavailable", "LocalHostHandle",
           "HttpHostHandle", "TenantQuota", "FabricMetrics",
           "ServingFabric", "ControlChannel", "FleetSwapController",
           "stable_digest", "probe_digest"]


def stable_digest(*parts: Any) -> int:
    """Stable 64-bit digest of the joined parts — placement and jitter
    must never depend on process-seeded ``hash()``."""
    raw = "\x1f".join(str(p) for p in parts).encode()
    return int.from_bytes(hashlib.blake2s(raw, digest_size=8).digest(),
                          "big")


class HostUnavailable(RuntimeError):
    """Transport-level failure talking to one host (connection refused /
    reset, timeout, malformed response) — the class of error the single-
    retry failover absorbs."""


# ---------------------------------------------------------------------------
# placement — consistent-hash ring over virtual nodes
# ---------------------------------------------------------------------------

class HashRing:
    """Consistent-hash ring: ``vnodes`` virtual points per host, placed
    by stable digest.  ``candidates(key)`` returns the distinct hosts in
    ring order from the key's point — element 0 is the primary placement,
    the rest the bounded-spill / failover order.  Adding a host remaps
    only the keys whose arcs it takes over (test-pinned)."""

    def __init__(self, hosts: Sequence[str] = (), vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._hosts: List[str] = []
        self._points: List[Any] = []  # sorted (point, host)
        for h in hosts:
            self.add(h)

    def add(self, host: str) -> None:
        if host in self._hosts:
            return
        self._hosts.append(host)
        for v in range(self.vnodes):
            bisect.insort(self._points,
                          (stable_digest("vnode", host, v), host))

    def remove(self, host: str) -> None:
        if host not in self._hosts:
            return
        self._hosts.remove(host)
        self._points = [p for p in self._points if p[1] != host]

    def hosts(self) -> List[str]:
        return sorted(self._hosts)

    def candidates(self, key: str, k: Optional[int] = None) -> List[str]:
        if not self._points:
            return []
        point = stable_digest("tenant", key)
        i = bisect.bisect_left(self._points, (point, "")) \
            % len(self._points)
        out: List[str] = []
        seen = set()
        for j in range(len(self._points)):
            host = self._points[(i + j) % len(self._points)][1]
            if host not in seen:
                seen.add(host)
                out.append(host)
                if k is not None and len(out) >= k:
                    break
        return out

    def primary(self, key: str) -> Optional[str]:
        c = self.candidates(key, 1)
        return c[0] if c else None


# ---------------------------------------------------------------------------
# host handles — the router's transport seam
# ---------------------------------------------------------------------------

class LocalHostHandle:
    """In-process replica handle (deterministic unit tests + single-
    process fleets): wraps a ``ModelServer``/``MultiTenantServer``
    directly.  ``kill()`` simulates a SIGKILLed host (every call raises
    :class:`HostUnavailable` until ``restart()``)."""

    def __init__(self, host_id: str, server: Any):
        self.host_id = str(host_id)
        self.server = server
        self.killed = False

    def _check(self) -> None:
        if self.killed:
            raise HostUnavailable(f"host {self.host_id} is down")

    def forward(self, rows: Sequence[Dict[str, Any]],
                tenant: Optional[str] = None,
                timeout_s: Optional[float] = None) -> List[Any]:
        self._check()
        timeout_ms = None if timeout_s is None else timeout_s * 1000.0
        wait_s = None if timeout_s is None else timeout_s + 5.0
        try:
            if getattr(self.server, "is_multi_tenant", False):
                return self.server.score(rows, tenant=tenant,
                                         timeout_ms=timeout_ms,
                                         wait_s=wait_s)
            return self.server.score(rows, timeout_ms=timeout_ms,
                                     wait_s=wait_s)
        except FutureTimeout as exc:
            raise HostUnavailable(
                f"host {self.host_id} deadline overrun") from exc

    def healthz(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        self._check()
        from .http import healthz_doc

        return healthz_doc(self.server)[1]

    def swap(self, path: str, tenant: Optional[str] = None) -> Any:
        self._check()
        if getattr(self.server, "is_multi_tenant", False):
            return self.server.swap(tenant, path)
        return self.server.swap(path)

    def drain(self) -> None:
        self._check()
        self.server.begin_drain()

    def kill(self) -> None:
        self.killed = True

    def restart(self) -> None:
        self.killed = False


class HttpHostHandle:
    """HTTP replica handle against ``serving/http.py`` endpoints.  Every
    transport-level problem (refused/reset connection, timeout, non-JSON
    body) raises :class:`HostUnavailable`; structured 503 sheds come back
    as ``ShedResult`` rows, exactly like the in-process path."""

    def __init__(self, host_id: str, address: str,
                 connect_timeout_s: float = 2.0):
        self.host_id = str(host_id)
        self.address = str(address)  # "127.0.0.1:8080"
        self.connect_timeout_s = float(connect_timeout_s)

    def _request(self, method: str, path: str, body: Any = None,
                 timeout_s: Optional[float] = None):
        import http.client

        timeout = timeout_s if timeout_s and timeout_s > 0 \
            else self.connect_timeout_s
        conn = http.client.HTTPConnection(self.address, timeout=timeout)
        try:
            payload = None if body is None else json.dumps(
                body, default=str).encode()
            headers = {"Content-Type": "application/json"} \
                if payload is not None else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        except (OSError, http.client.HTTPException,
                json.JSONDecodeError) as exc:
            raise HostUnavailable(
                f"host {self.host_id} transport failure: "
                f"{type(exc).__name__}") from exc
        finally:
            conn.close()

    @staticmethod
    def _parse_row(r: Any) -> Any:
        if isinstance(r, dict) and r.get("status") == 503 and "reason" in r:
            return ShedResult(reason=r["reason"],
                              queue_depth=r.get("queueDepth"),
                              retry_after_ms=r.get("retryAfterMs"))
        return r

    def forward(self, rows: Sequence[Dict[str, Any]],
                tenant: Optional[str] = None,
                timeout_s: Optional[float] = None) -> List[Any]:
        body: Dict[str, Any] = {"rows": list(rows)}
        if tenant is not None:
            body["tenant"] = tenant
        if timeout_s is not None:
            body["timeoutMs"] = timeout_s * 1000.0
        status, doc = self._request("POST", "/score", body, timeout_s)
        if status in (200, 503) and isinstance(doc.get("scores"), list):
            return [self._parse_row(r) for r in doc["scores"]]
        raise HostUnavailable(
            f"host {self.host_id} bad /score response ({status}): "
            f"{doc.get('error')}")

    def healthz(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        _status, doc = self._request("GET", "/healthz",
                                     timeout_s=timeout_s)
        return doc

    def swap(self, path: str, tenant: Optional[str] = None) -> Any:
        body: Dict[str, Any] = {"path": path}
        if tenant is not None:
            body["tenant"] = tenant
        status, doc = self._request("POST", "/swap", body)
        if status != 200:
            raise RuntimeError(f"swap on {self.host_id} failed "
                               f"({status}): {doc.get('error')}")
        return doc

    def drain(self) -> None:
        self._request("POST", "/drain", {})


# ---------------------------------------------------------------------------
# router-level tenant quotas
# ---------------------------------------------------------------------------

class TenantQuota:
    """Router-side in-flight row quota for one tenant.  Acquired ONCE per
    request — retries and spills reuse the same admission, so a failed-
    over request never double-counts (ISSUE-pinned)."""

    def __init__(self, max_inflight_rows: int):
        self.max_inflight_rows = int(max_inflight_rows)
        self._lock = threading.Lock()
        self._used = 0

    @property
    def used(self) -> int:
        with self._lock:
            return self._used

    def try_acquire(self, n_rows: int) -> bool:
        with self._lock:
            if self._used + n_rows > self.max_inflight_rows:
                return False
            self._used += n_rows
            return True

    def release(self, n_rows: int) -> None:
        with self._lock:
            self._used = max(0, self._used - n_rows)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_HOST_COUNTER_KEYS = ("forwards", "rows", "failovers", "spills",
                      "probeFailures", "evictions", "readmissions")


class FabricMetrics:
    """Thread-safe router-side ledger: per-host counters (the Prometheus
    ``host="..."`` labels) plus fleet-level request/shed/latency totals."""

    def __init__(self, reservoir_capacity: int = 4096):
        self._lock = threading.Lock()
        self._latency = LatencyReservoir(reservoir_capacity)
        self.requests = 0
        self.rows = 0
        self.retried_requests = 0
        self.shed_by_reason: Dict[str, int] = {}
        self._hosts: Dict[str, Dict[str, int]] = {}

    def _host(self, host: str) -> Dict[str, int]:
        h = self._hosts.get(host)
        if h is None:
            h = self._hosts[host] = {k: 0 for k in _HOST_COUNTER_KEYS}
        return h

    def record_request(self, host: str, n_rows: int, seconds: float,
                       retried: bool = False) -> None:
        with self._lock:
            self.requests += 1
            self.rows += n_rows
            if retried:
                self.retried_requests += 1
            self._latency.observe(seconds)
            h = self._host(host)
            h["forwards"] += 1
            h["rows"] += n_rows

    def record_failover(self, host: str) -> None:
        with self._lock:
            self._host(host)["failovers"] += 1

    def record_spill(self, host: str) -> None:
        with self._lock:
            self._host(host)["spills"] += 1

    def record_probe_failure(self, host: str) -> None:
        with self._lock:
            self._host(host)["probeFailures"] += 1

    def record_evict(self, host: str) -> None:
        with self._lock:
            self._host(host)["evictions"] += 1

    def record_readmit(self, host: str) -> None:
        with self._lock:
            self._host(host)["readmissions"] += 1

    def record_shed(self, reason: str, n_rows: int) -> None:
        with self._lock:
            self.shed_by_reason[reason] = \
                self.shed_by_reason.get(reason, 0) + n_rows

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = {f"p{int(q * 100)}": (None if v is None
                                        else round(v * 1000.0, 3))
                   for q, v in ((q, self._latency.quantile(q))
                                for q in (0.50, 0.95, 0.99))}
            return {
                "requests": self.requests,
                "rows": self.rows,
                "retriedRequests": self.retried_requests,
                "shedByReason": dict(sorted(self.shed_by_reason.items())),
                "latencyMs": lat,
                "hosts": {h: dict(c)
                          for h, c in sorted(self._hosts.items())},
            }


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class _HostState:
    """Router-side health record for one host."""

    def __init__(self, host_id: str, now: float):
        self.host_id = host_id
        self.last_seen = now          # monotonic time of last healthy obs
        self.evicted = False
        self.draining = False
        self.consecutive_fail = 0
        self.consecutive_ok = 0
        self.breaker_state: Optional[str] = None
        self.shed_rate = 0.0
        self.probes = 0

    def admitting(self) -> bool:
        return not self.evicted and not self.draining

    def describe(self, now: float) -> Dict[str, Any]:
        return {"evicted": self.evicted, "draining": self.draining,
                "heartbeatAgeSecs": round(now - self.last_seen, 3),
                "consecutiveFail": self.consecutive_fail,
                "consecutiveOk": self.consecutive_ok,
                "breakerState": self.breaker_state,
                "shedRate": self.shed_rate}


class ServingFabric:
    """Shared-nothing router over N host replicas.

    ``hosts`` is an iterable of handles (``LocalHostHandle`` /
    ``HttpHostHandle`` / anything with ``host_id``/``forward``/
    ``healthz``).  ``tenant_quota_rows`` (int, or ``{tenant: int}``) arms
    the router-level in-flight quota; ``record_decisions=True`` keeps the
    per-request decision log the determinism gate compares."""

    def __init__(self, hosts: Sequence[Any] = (), seed: int = 0,
                 vnodes: int = 64, max_spill: int = 1,
                 retry_limit: int = 1,
                 default_timeout_ms: Optional[float] = 2000.0,
                 evict_after_s: float = 3.0,
                 probe_fail_threshold: int = 2,
                 readmit_probes: int = 2,
                 shed_rate_spill: float = 0.5,
                 retry_base_s: float = 0.002,
                 retry_cap_s: float = 0.05,
                 probe_timeout_s: float = 2.0,
                 tenant_quota_rows: Any = None,
                 record_decisions: bool = False):
        self.seed = int(seed)
        self.max_spill = int(max_spill)
        self.retry_limit = int(retry_limit)
        self.default_timeout_ms = default_timeout_ms
        self.evict_after_s = float(evict_after_s)
        self.probe_fail_threshold = int(probe_fail_threshold)
        self.readmit_probes = int(readmit_probes)
        self.shed_rate_spill = float(shed_rate_spill)
        self.retry_base_s = float(retry_base_s)
        self.retry_cap_s = float(retry_cap_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.metrics = FabricMetrics()
        self.ring = HashRing(vnodes=vnodes)
        self._hosts: Dict[str, Any] = {}
        self._states: Dict[str, _HostState] = {}
        self._quotas: Dict[str, TenantQuota] = {}
        self._quota_rows = tenant_quota_rows
        self._lock = threading.Lock()   # LEAF: seq/log/quota-map only
        self._req_seq = 0
        self.decisions: Optional[List[Dict[str, Any]]] = \
            [] if record_decisions else None
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_stop = threading.Event()
        for h in hosts:
            self.add_host(h)

    # -- topology ------------------------------------------------------------

    def add_host(self, handle: Any) -> None:
        host_id = handle.host_id
        self._hosts[host_id] = handle
        self._states[host_id] = _HostState(host_id, time.monotonic())
        self.ring.add(host_id)
        record_event("fabric.add_host", host=host_id)

    def remove_host(self, host_id: str) -> None:
        """Deregister (the drain protocol's last step)."""
        self._hosts.pop(host_id, None)
        self._states.pop(host_id, None)
        self.ring.remove(host_id)
        record_event("fabric.remove_host", host=host_id)

    def hosts(self) -> List[str]:
        return sorted(self._hosts)

    def host_state(self, host_id: str) -> _HostState:
        return self._states[host_id]

    # -- health --------------------------------------------------------------

    def probe_once(self, now: Optional[float] = None) -> Dict[str, bool]:
        """One health sweep over every host (deterministic tests/benches
        drive this directly; ``start_probing`` runs it on a thread).
        Returns ``{host_id: admitting}`` after the sweep."""
        now = time.monotonic() if now is None else now
        for host_id in sorted(self._hosts):
            st = self._states[host_id]
            st.probes += 1
            observed, ok, doc = True, False, None
            try:
                faults.fire("host.heartbeat", tag=host_id)
                doc = self._hosts[host_id].healthz(
                    timeout_s=self.probe_timeout_s)
                ok = doc.get("status") in ("ok", "degraded", "draining")
            except faults.FaultSkip:
                observed = False   # suppressed heartbeat: age keeps growing
            except Exception:
                ok = False
            if observed:
                if ok:
                    st.last_seen = now
                    st.consecutive_ok += 1
                    st.consecutive_fail = 0
                    st.breaker_state = doc.get("breakerState")
                    st.shed_rate = float(doc.get("shedRate") or 0.0)
                    st.draining = (doc.get("status") == "draining"
                                   or bool(doc.get("draining")))
                else:
                    st.consecutive_fail += 1
                    st.consecutive_ok = 0
                    self.metrics.record_probe_failure(host_id)
            age = now - st.last_seen
            if not st.evicted and (
                    st.consecutive_fail >= self.probe_fail_threshold
                    or age > self.evict_after_s):
                reason = ("probe_failures"
                          if st.consecutive_fail
                          >= self.probe_fail_threshold
                          else "heartbeat_timeout")
                self._evict(host_id, reason)
            elif st.evicted and st.consecutive_ok >= self.readmit_probes:
                self._readmit(host_id)
        return {h: self._states[h].admitting() for h in sorted(self._hosts)}

    def _evict(self, host_id: str, reason: str) -> None:
        st = self._states[host_id]
        st.evicted = True
        st.consecutive_ok = 0   # hysteresis: readmission starts from zero
        self.metrics.record_evict(host_id)
        record_event("fabric.evict", host=host_id, reason=reason)

    def _readmit(self, host_id: str) -> None:
        st = self._states[host_id]
        st.evicted = False
        st.consecutive_fail = 0
        self.metrics.record_readmit(host_id)
        record_event("fabric.readmit", host=host_id)

    def start_probing(self, interval_s: float = 0.5) -> None:
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return
        self._probe_stop.clear()

        def loop():
            while not self._probe_stop.wait(interval_s):
                self.probe_once()

        self._probe_thread = threading.Thread(
            target=loop, name="op-fabric-probe", daemon=True)
        self._probe_thread.start()

    def stop_probing(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    def drain_host(self, host_id: str) -> None:
        """Graceful-drain entry: tell the host to stop admissions and
        mark it non-admitting immediately (in-flight completes on the
        host; ``remove_host`` deregisters once it exits)."""
        self._states[host_id].draining = True
        try:
            self._hosts[host_id].drain()
        finally:
            record_event("fabric.drain", host=host_id)

    # -- deterministic jitter ------------------------------------------------

    def failover_jitter_s(self, request_id: int, attempt: int) -> float:
        """Stateless seeded backoff draw — keyed on (seed, request,
        attempt), independent of call interleaving across threads, so two
        routers at one seed produce identical delays."""
        h = stable_digest("jitter", self.seed, request_id, attempt)
        rng = np.random.default_rng(h & 0xFFFFFFFF)
        base = self.retry_base_s * (2.0 ** (attempt - 1))
        return float(min(self.retry_cap_s, base * (1.0 + rng.random())))

    # -- routing -------------------------------------------------------------

    def _quota_for(self, tenant: str) -> Optional[TenantQuota]:
        cfg = self._quota_rows
        if cfg is None:
            return None
        with self._lock:
            q = self._quotas.get(tenant)
            if q is None:
                rows = cfg.get(tenant) if isinstance(cfg, dict) else cfg
                if rows is None:
                    return None
                q = self._quotas[tenant] = TenantQuota(rows)
            return q

    def _pressured(self, host_id: str) -> bool:
        st = self._states[host_id]
        return (st.breaker_state == "open"
                or st.shed_rate > self.shed_rate_spill)

    def _log(self, req: int, tenant: str, attempted: List[str],
             served: str) -> None:
        if self.decisions is not None:
            with self._lock:
                self.decisions.append({
                    "request": req, "tenant": tenant,
                    "attempted": list(attempted), "served": served})

    def _note_forward_failure(self, host_id: str) -> None:
        st = self._states.get(host_id)
        if st is None:
            return
        st.consecutive_fail += 1
        st.consecutive_ok = 0
        if (not st.evicted
                and st.consecutive_fail >= self.probe_fail_threshold):
            self._evict(host_id, "forward_failures")

    def _note_forward_success(self, host_id: str) -> None:
        st = self._states.get(host_id)
        if st is None:
            return
        st.last_seen = time.monotonic()
        st.consecutive_fail = 0

    def score(self, rows: Sequence[Dict[str, Any]],
              tenant: str = "default",
              timeout_ms: Optional[float] = None) -> List[Any]:
        """Route one scoring request: placement → bounded spill under
        quota pressure → single-retry failover on transport failure, all
        within the request's deadline budget.  Every row comes back as a
        score map or a ``ShedResult`` — never an exception storm."""
        rows = list(rows)
        if not rows:
            return []
        with self._lock:
            self._req_seq += 1
            req = self._req_seq
        t0 = time.monotonic()
        budget_ms = timeout_ms if timeout_ms is not None \
            else self.default_timeout_ms
        deadline = None if budget_ms is None else t0 + budget_ms / 1000.0
        quota = self._quota_for(tenant)
        if quota is not None and not quota.try_acquire(len(rows)):
            self.metrics.record_shed("tenant_quota", len(rows))
            self._log(req, tenant, [], "shed:tenant_quota")
            return [ShedResult(reason="tenant_quota") for _ in rows]
        try:
            # the quota token is held across EVERY attempt below: a
            # retried/spilled request is admitted once, not re-admitted
            return self._route(req, rows, tenant, deadline, t0)
        finally:
            if quota is not None:
                quota.release(len(rows))

    def _shed(self, req: int, tenant: str, attempted: List[str],
              reason: str, n: int) -> List[Any]:
        self.metrics.record_shed(reason, n)
        self._log(req, tenant, attempted, f"shed:{reason}")
        return [ShedResult(reason=reason) for _ in range(n)]

    def _route(self, req: int, rows: List[Dict[str, Any]], tenant: str,
               deadline: Optional[float], t0: float) -> List[Any]:
        order = [h for h in self.ring.candidates(tenant)
                 if self._states[h].admitting()]
        attempted: List[str] = []
        spills = 0
        retries = 0
        i = 0
        last_shed: Optional[ShedResult] = None
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                return self._shed(req, tenant, attempted, "deadline",
                                  len(rows))
            while i < len(order) and not self._states[
                    order[i]].admitting():
                i += 1   # evicted mid-request (e.g. by our own failure)
            if i >= len(order):
                reason = last_shed.reason if last_shed is not None \
                    else "no_hosts"
                return self._shed(req, tenant, attempted, reason,
                                  len(rows))
            host = order[i]
            # proactive spill: the placement target is shedding or its
            # breaker is open — prefer the next neighbor (bounded)
            if (spills < self.max_spill and i + 1 < len(order)
                    and self._pressured(host)
                    and not self._pressured(order[i + 1])):
                spills += 1
                self.metrics.record_spill(host)
                record_event("fabric.spill", host=host, request=req,
                             reason="pressure")
                i += 1
                continue
            attempted.append(host)
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            try:
                faults.fire("router.forward", tag=host)
                out = self._hosts[host].forward(
                    rows, tenant=tenant, timeout_s=remaining)
            except (HostUnavailable, OSError, FutureTimeout,
                    TimeoutError) as exc:
                self._note_forward_failure(host)
                self.metrics.record_failover(host)
                record_event("fabric.failover", host=host, request=req,
                             error=type(exc).__name__)
                if retries >= self.retry_limit:
                    return self._shed(req, tenant, attempted,
                                      "upstream_error", len(rows))
                retries += 1
                delay = self.failover_jitter_s(req, retries)
                if remaining is not None:
                    delay = max(0.0, min(delay, remaining))
                if delay > 0:
                    time.sleep(delay)
                i += 1
                continue
            self._note_forward_success(host)
            sheds = [r for r in out if isinstance(r, ShedResult)]
            if (sheds and len(sheds) == len(out)
                    and sheds[0].reason in ("queue_full", "draining",
                                            "shutting_down")):
                # quota pressure on the placement target: bounded spill
                # to the next ring neighbor
                last_shed = sheds[0]
                self.metrics.record_spill(host)
                record_event("fabric.spill", host=host, request=req,
                             reason=sheds[0].reason)
                if sheds[0].reason == "draining":
                    self._states[host].draining = True
                if spills >= self.max_spill:
                    self._log(req, tenant, attempted,
                              f"shed:{sheds[0].reason}")
                    self.metrics.record_shed(sheds[0].reason, len(rows))
                    return out
                spills += 1
                i += 1
                continue
            self.metrics.record_request(host, len(rows),
                                        time.monotonic() - t0,
                                        retried=retries > 0)
            self._log(req, tenant, attempted, host)
            return out

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        snap = self.metrics.snapshot()
        hosts = snap.get("hosts", {})
        for host_id in sorted(self._hosts):
            doc = hosts.setdefault(
                host_id, {k: 0 for k in _HOST_COUNTER_KEYS})
            doc.update(self._states[host_id].describe(now))
        snap["hosts"] = hosts
        snap["ring"] = {"vnodes": self.ring.vnodes,
                        "hosts": self.ring.hosts()}
        return snap


# ---------------------------------------------------------------------------
# control channel + fleet-consistent swaps (PR 15 substrate)
# ---------------------------------------------------------------------------

class ControlChannel:
    """Small fleet-control bus on the pod host-collective substrate.

    Every operation is a COLLECTIVE: all pod processes call it at the
    same point (the collective ledger, TM07x, attributes any divergence).
    ``publish`` broadcasts the coordinator's message; the
    ``swap.propagate`` fault point fires AFTER the exchange, so an armed
    ``skip`` drops the message on one process only — the transport stays
    lockstep while the delivery is lost, exactly a dropped control
    datagram, and the verdict gather detects it."""

    def __init__(self, transport: Any = None):
        self._transport = transport
        self.seq = 0

    def _pod(self) -> Any:
        if self._transport is not None:
            return self._transport
        from ..distributed.runtime import current_pod

        return current_pod()

    @property
    def process_index(self) -> int:
        return int(getattr(self._pod(), "process_index", 0))

    @property
    def process_count(self) -> int:
        return int(getattr(self._pod(), "process_count", 1))

    def is_coordinator(self) -> bool:
        pod = self._pod()
        if hasattr(pod, "is_coordinator"):
            return bool(pod.is_coordinator())
        return True

    def publish(self, msg: Optional[Dict[str, Any]]
                ) -> Optional[Dict[str, Any]]:
        """Coordinator's ``msg`` lands on every process; replicas may
        pass anything (conventionally their own draft — ignored).
        Returns the delivered message, or None when an armed
        ``swap.propagate`` fault dropped it locally."""
        pod = self._pod()
        self.seq += 1
        out = pod.broadcast_obj(msg if self.is_coordinator() else None,
                                kind="fabric.control")
        op = (out or {}).get("op") if isinstance(out, dict) else None
        try:
            faults.fire("swap.propagate", tag=op, index=self.seq - 1)
        except faults.FaultSkip:
            record_event("fabric.control_drop", seq=self.seq - 1, op=op)
            return None
        return out

    def gather(self, obj: Any) -> List[Any]:
        """Allgather one object per process (verdict collection)."""
        pod = self._pod()
        return pod.allgather_obj(obj, _kind="fabric.verdicts")


class FleetSwapController:
    """Fleet-consistent guarded swap/rollback over the control channel.

    The single-host ``GuardedSwap`` gates a swap on one replica's shadow
    + bake verdict; at pod scale the verdict must be FLEET-consistent.
    Protocol (every process calls :meth:`fleet_swap` at a synchronized
    point — all branches below derive from allgathered data, so every
    process takes the same one):

    1. the coordinator publishes ``{"op": "swap", path, probe}`` (probe
       rows ride the message so every replica bakes the SAME queries);
    2. every replica that received it applies — pin the outgoing
       generation first (the rollback target), load the artifact, bake-
       score the probe rows (``swap.bake`` fault point) — and digests
       its answers;
    3. verdicts allgather; every process computes the same decision:
       a bake failure on ANY replica **vetoes** the fleet swap (all
       applied replicas roll back to the pinned generation); a dropped
       control message (non-receipt) triggers ONE repair re-publish
       before the rollback; divergent probe digests (replicas loaded
       different artifacts) also veto.
    """

    def __init__(self, registry: Any, name: str,
                 channel: Optional[ControlChannel] = None,
                 metrics: Any = None, max_repairs: int = 1):
        self.registry = registry
        self.name = name
        self.channel = channel or ControlChannel()
        self.metrics = metrics
        self.max_repairs = int(max_repairs)
        self._probes = 0
        self._round_applied = False
        self._pending: Optional[Dict[str, Any]] = None
        self.last_result: Optional[Dict[str, Any]] = None

    # -- one replica's apply+bake -------------------------------------------

    def _apply(self, msg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        idx = self.channel.process_index
        if msg is None:
            cur = self.registry.maybe_get(self.name)
            return {"process": idx, "received": False, "ok": False,
                    "reason": "not_received",
                    "version": cur.version if cur else None,
                    "digest": None}
        if self._pending is not None:
            # repair round: already applied this candidate — re-verdict
            # from the recorded bake, don't re-load
            return {"process": idx, "received": True, "ok": True,
                    "reason": None,
                    "version": self._pending["version"],
                    "digest": self._pending["digest"]}
        version = None
        try:
            if self.registry.maybe_get(self.name) is not None:
                # outgoing generation = the fleet rollback target
                self.registry.pin(self.name)
            entry = self.registry.load(self.name, msg["path"])
            self._round_applied = True
            version = entry.version
            self._probes += 1
            faults.fire("swap.bake", tag="fleet", index=self._probes - 1)
            digest = probe_digest(entry.scorer, msg.get("probe") or [])
            self._pending = {"version": entry.version, "digest": digest}
            return {"process": idx, "received": True, "ok": True,
                    "reason": None, "version": entry.version,
                    "digest": digest}
        except Exception as exc:
            return {"process": idx, "received": True, "ok": False,
                    "reason": f"bake:{type(exc).__name__}",
                    "version": version, "digest": None}

    def _rollback_local(self, reason: str) -> None:
        if not self._round_applied:
            return   # this replica never switched; nothing to undo
        if self.registry.pinned(self.name) is not None:
            self.registry.rollback(self.name)
        else:
            self.registry.evict(self.name)   # first deploy: no fallback
        if self.metrics is not None:
            self.metrics.record_rollback(reason)

    # -- the collective ------------------------------------------------------

    def fleet_swap(self, path: Optional[str] = None,
                   probe_rows: Optional[Sequence[Dict[str, Any]]] = None
                   ) -> Dict[str, Any]:
        """COLLECTIVE: run on every pod process.  The coordinator's
        ``path``/``probe_rows`` are authoritative (replicas may pass
        None).  Returns the fleet decision (identical on every
        process)."""
        self._round_applied = False
        self._pending = None
        draft = {"op": "swap", "path": path,
                 "probe": list(probe_rows or [])}
        msg = self.channel.publish(draft)
        repairs = 0
        while True:
            verdict = self._apply(msg)
            verdicts = self.channel.gather(verdict)
            vetoes = [v for v in verdicts
                      if v["received"] and not v["ok"]]
            missing = [v for v in verdicts if not v["received"]]
            digests = {v["digest"] for v in verdicts
                       if v["received"] and v["ok"]}
            reasons = sorted(
                f"p{v['process']}:{v['reason']}" for v in vetoes)
            if len(digests) > 1:
                reasons.append("digest_divergence")
            if not reasons and not missing:
                return self._conclude(True, verdicts, [])
            if reasons or repairs >= self.max_repairs:
                if missing and not reasons:
                    reasons.append("control_message_lost")
                return self._conclude(False, verdicts, reasons)
            # non-receipt only, repair budget left: re-publish — applied
            # replicas re-verdict from their recorded bake, the dropped
            # one applies now
            repairs += 1
            record_event("fleet.repair",
                         missing=[v["process"] for v in missing])
            msg = self.channel.publish(draft)

    def _conclude(self, accepted: bool, verdicts: List[Dict[str, Any]],
                  reasons: List[str]) -> Dict[str, Any]:
        versions = sorted({v["version"] for v in verdicts
                           if v["version"] is not None})
        result = {"accepted": accepted, "reasons": reasons,
                  "verdicts": verdicts, "versions": versions,
                  "processes": len(verdicts)}
        if accepted:
            record_event("fleet.swap", version=versions[-1]
                         if versions else None,
                         processes=len(verdicts))
        else:
            record_event("fleet.veto", reasons=reasons,
                         processes=len(verdicts))
            self._rollback_local(";".join(reasons) or "fleet_veto")
            record_event("fleet.rollback", reasons=reasons)
        if self.metrics is not None:
            self.metrics.record_swap_decision(
                {"accepted": accepted, "reasons": reasons,
                 "checks": {"fleet": len(verdicts),
                            "versions": versions},
                 "version": versions[-1] if versions else None})
        self._pending = None
        self._round_applied = False
        self.last_result = result
        return result

    def sync_drift_baselines(self, baselines: Optional[Dict[str, Any]]
                             = None) -> Optional[Dict[str, Any]]:
        """COLLECTIVE: the coordinator's exported drift baselines land on
        every replica (so fleet drift decisions compare against ONE
        reference, not N per-host ones).  Returns the fleet baselines, or
        None when the control message was dropped locally (caller keeps
        its local baselines — the next sync repairs)."""
        msg = self.channel.publish({"op": "drift",
                                    "baselines": baselines})
        if msg is None:
            return None
        out = msg.get("baselines")
        record_event("fleet.drift_baselines",
                     features=sorted(out) if isinstance(out, dict)
                     else None)
        return out
