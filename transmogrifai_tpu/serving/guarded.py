"""Guarded hot-swap — shadow validation, pinned rollback target, bake window.

``ModelRegistry.swap`` installs ANY model unconditionally; in a
continuously-refreshing deployment that is exactly the dangerous step —
a drift-corrupted or regressed refresh would swap straight into the path
serving live traffic.  ``GuardedSwap`` makes rollout a guarded,
reversible operation (the discipline the TPU serving comparison in
PAPERS.md applies to model rollout):

1. **Shadow validation** (``propose``): the candidate is scored AGAINST
   the live model on a held replay window (sampled live traffic rows the
   guard retains, plus any caller-provided replay set) and must pass
   three acceptance gates:

   * *prediction parity* — mean absolute score distance and score-
     distribution PSI within bounds (a collapsed/flipped model fails
     here even without labels);
   * *metric parity* — when replay rows carry the label, the candidate's
     log-loss must not regress beyond ``metric_tol``;
   * *latency* — the candidate's p99 per-batch latency must stay within
     ``p99_factor`` of the live model's (and under ``p99_bound_ms`` when
     set).

2. **Pinned swap**: only on pass does the registry swap run — with the
   outgoing generation PINNED as last-known-good first, so the rollback
   target can never be evicted (serving/registry.py generation history).

3. **Bake window + automatic rollback**: at swap time the guard captures
   golden queries (replay rows + the candidate's own answers).  During
   the bake window, probes re-score the golden rows against the CURRENT
   registry entry; a divergence beyond ``golden_tol``, a probe error, or
   an error-rate regression triggers ``rollback`` — the pinned
   generation is atomically reinstated and the structured reason lands
   in the serving metrics (``lastRollbackReason``), leaving the
   circuit-breaker path untouched.

The ``swap.shadow`` / ``swap.bake`` fault points (utils/faults.py) fire
at shadow evaluation and at every bake probe, so gate-fail and rollback
paths are seed-deterministically testable.
"""
from __future__ import annotations

import hashlib
import json
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs.flight import record_event
from ..utils import faults
from .drift import psi_from_counts
from .metrics import ServingMetrics
from .registry import ModelEntry, ModelRegistry

__all__ = ["SwapGateConfig", "SwapDecision", "GuardedSwap",
           "probe_digest"]


class SwapGateConfig:
    """Acceptance gates + bake-window knobs for a GuardedSwap."""

    def __init__(self,
                 pred_distance_max: float = 0.15,
                 pred_psi_max: float = 0.5,
                 metric_tol: float = 0.05,
                 p99_factor: float = 3.0,
                 p99_bound_ms: Optional[float] = None,
                 min_replay_rows: int = 16,
                 replay_capacity: int = 512,
                 shadow_batch: int = 16,
                 label_name: Optional[str] = None,
                 golden_rows: int = 16,
                 golden_tol: float = 1e-3,
                 bake_rows: int = 256,
                 probe_every: int = 64,
                 error_rate_max: float = 0.05):
        self.pred_distance_max = float(pred_distance_max)
        self.pred_psi_max = float(pred_psi_max)
        self.metric_tol = float(metric_tol)
        self.p99_factor = float(p99_factor)
        self.p99_bound_ms = p99_bound_ms
        self.min_replay_rows = int(min_replay_rows)
        self.replay_capacity = int(replay_capacity)
        self.shadow_batch = int(shadow_batch)
        self.label_name = label_name
        self.golden_rows = int(golden_rows)
        self.golden_tol = float(golden_tol)
        self.bake_rows = int(bake_rows)
        self.probe_every = int(probe_every)
        self.error_rate_max = float(error_rate_max)

    def to_json(self) -> Dict[str, Any]:
        return {"predDistanceMax": self.pred_distance_max,
                "predPsiMax": self.pred_psi_max,
                "metricTol": self.metric_tol,
                "p99Factor": self.p99_factor,
                "p99BoundMs": self.p99_bound_ms,
                "minReplayRows": self.min_replay_rows,
                "goldenTol": self.golden_tol,
                "bakeRows": self.bake_rows}


class SwapDecision:
    """Structured outcome of one guarded-swap proposal."""

    def __init__(self, accepted: bool, reasons: List[str],
                 checks: Dict[str, Any], version: Optional[int] = None):
        self.accepted = accepted
        self.reasons = reasons
        self.checks = checks
        self.version = version
        self.at = time.time()

    def to_json(self) -> Dict[str, Any]:
        return {"accepted": self.accepted, "reasons": list(self.reasons),
                "checks": dict(self.checks), "version": self.version,
                "at": self.at}


def _score_of(out: Any) -> float:
    """One comparable scalar per scored row: positive-class probability
    when the result carries one, else the raw prediction value."""
    if isinstance(out, dict):
        for key in ("probability_1", "prediction"):
            v = out.get(key)
            if isinstance(v, (int, float)):
                return float(v)
        for v in out.values():
            if isinstance(v, (int, float)):
                return float(v)
    if isinstance(out, (int, float)):
        return float(out)
    return 0.0


def _first_result(row_out: Dict[str, Any]) -> Any:
    """The first result feature's value of one scored row map."""
    for v in row_out.values():
        return v
    return None


def probe_digest(scorer, rows: Sequence[Dict[str, Any]],
                 decimals: int = 9) -> Optional[str]:
    """Content digest of a scorer's answers on probe ``rows`` (rounded to
    ``decimals`` so float formatting noise cannot diverge it).  The
    fleet-swap consistency check (serving/fabric.FleetSwapController):
    replicas that loaded the same artifact answer the same bake probe
    byte-identically, so divergent digests across the pod mean divergent
    artifacts — an automatic fleet veto."""
    if not rows:
        return None
    out = scorer(list(rows))
    scores = [round(_score_of(_first_result(o)), decimals) for o in out]
    return hashlib.sha256(
        json.dumps(scores, sort_keys=True).encode()).hexdigest()


def _shadow_score(scorer, rows: Sequence[Dict[str, Any]],
                  batch: int) -> Dict[str, Any]:
    """Score ``rows`` in fixed micro-batches, collecting the comparable
    scalar per row plus per-batch wall times (the p99 source)."""
    scores: List[float] = []
    walls: List[float] = []
    for i in range(0, len(rows), batch):
        chunk = list(rows[i:i + batch])
        t0 = time.perf_counter()
        out = scorer(chunk)
        walls.append(time.perf_counter() - t0)
        scores.extend(_score_of(_first_result(r)) for r in out)
    walls.sort()
    p99 = walls[min(len(walls) - 1,
                    max(0, int(math.ceil(0.99 * len(walls))) - 1))]
    return {"scores": np.asarray(scores, np.float64),
            "p99_s": p99, "batches": len(walls)}


def _log_loss(labels: np.ndarray, probs: np.ndarray) -> float:
    p = np.clip(probs, 1e-7, 1 - 1e-7)
    return float(-(labels * np.log(p) + (1 - labels) * np.log1p(-p)).mean())


class GuardedSwap:
    """Guarded rollout controller for ONE registry name.

    Wire it behind a server with ``ModelServer.with_guard`` (live traffic
    then feeds the replay window and drives bake probes automatically),
    or drive it directly: ``record_traffic`` → ``propose`` →
    ``bake_probe``.
    """

    def __init__(self, registry: ModelRegistry, name: str,
                 gate: Optional[SwapGateConfig] = None,
                 metrics: Optional[ServingMetrics] = None,
                 sample_rate: float = 1.0, seed: int = 11):
        self.registry = registry
        self.name = name
        self.gate = gate or SwapGateConfig()
        self.metrics = metrics or ServingMetrics()
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(seed)
        self._sample_rate = float(sample_rate)
        self._replay: List[Dict[str, Any]] = []
        self._replay_pos = 0
        self._proposals = 0
        self._probes = 0
        #: bake state after an accepted swap: golden rows + expected
        #: scores, error counters at swap time, rows left to bake
        self._bake: Optional[Dict[str, Any]] = None
        self.last_decision: Optional[SwapDecision] = None

    # -- replay window -------------------------------------------------------

    def record_traffic(self, rows: Sequence[Dict[str, Any]]) -> None:
        """Sample live rows into the bounded replay ring; during a bake
        window, also advance the bake budget and run due probes."""
        probe_due = False
        with self._lock:
            if rows and (self._sample_rate >= 1.0
                         or self._rng.random() < self._sample_rate):
                for r in rows:
                    if not isinstance(r, dict):
                        continue
                    if len(self._replay) < self.gate.replay_capacity:
                        self._replay.append(dict(r))
                    else:
                        self._replay[self._replay_pos] = dict(r)
                        self._replay_pos = ((self._replay_pos + 1)
                                            % self.gate.replay_capacity)
            if self._bake is not None and rows:
                self._bake["rows_seen"] += len(rows)
                if (self._bake["rows_seen"] - self._bake["last_probe_rows"]
                        >= self.gate.probe_every):
                    self._bake["last_probe_rows"] = self._bake["rows_seen"]
                    probe_due = True
        if probe_due:
            self.bake_probe()

    def replay_rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._replay)

    # -- shadow gate ---------------------------------------------------------

    def propose(self, model, path: Optional[str] = None,
                replay: Optional[Sequence[Dict[str, Any]]] = None,
                scorer: Optional[Callable] = None) -> SwapDecision:
        """Shadow-validate ``model`` against the live entry; swap only on
        pass.  ``replay`` overrides/extends the sampled window;
        ``scorer`` overrides the candidate's score function (tests)."""
        from ..local.scorer import score_function_batch

        live = self.registry.get(self.name)
        rows = list(replay) if replay is not None else self.replay_rows()
        reasons: List[str] = []
        checks: Dict[str, Any] = {"rows": len(rows)}
        self._proposals += 1
        try:
            faults.fire("swap.shadow", index=self._proposals - 1)
            if len(rows) < self.gate.min_replay_rows:
                reasons.append(
                    f"insufficient_replay:{len(rows)}"
                    f"<{self.gate.min_replay_rows}")
            else:
                cand_scorer = scorer or score_function_batch(model)
                cand = _shadow_score(cand_scorer, rows,
                                     self.gate.shadow_batch)
                ref = _shadow_score(live.scorer, rows,
                                    self.gate.shadow_batch)
                self._gate_predictions(cand, ref, rows, reasons, checks)
                self._gate_latency(cand, ref, reasons, checks)
        except Exception as exc:
            reasons.append(f"shadow_error:{type(exc).__name__}")
        decision = self._conclude(model, path, rows, reasons, checks)
        return decision

    def _gate_predictions(self, cand, ref, rows, reasons, checks) -> None:
        a, b = cand["scores"], ref["scores"]
        dist = float(np.abs(a - b).mean()) if len(a) else 0.0
        checks["predDistance"] = round(dist, 5)
        if dist > self.gate.pred_distance_max:
            reasons.append(
                f"pred_distance:{dist:.4f}>{self.gate.pred_distance_max}")
        # distribution shift of the scores themselves (catches a
        # collapsed-to-constant candidate that small mean distance hides)
        grid = np.linspace(0.0, 1.0, 11)
        psi = psi_from_counts(np.histogram(b, bins=grid)[0],
                              np.histogram(a, bins=grid)[0])
        checks["predPsi"] = round(psi, 4)
        if psi > self.gate.pred_psi_max:
            reasons.append(f"pred_psi:{psi:.3f}>{self.gate.pred_psi_max}")
        label = self.gate.label_name
        if label is not None:
            labeled = [(i, r[label]) for i, r in enumerate(rows)
                       if isinstance(r.get(label), (int, float))]
            if labeled:
                idx = np.asarray([i for i, _ in labeled])
                y = np.asarray([v for _, v in labeled], np.float64)
                cand_ll = _log_loss(y, a[idx])
                live_ll = _log_loss(y, b[idx])
                checks["candLogLoss"] = round(cand_ll, 5)
                checks["liveLogLoss"] = round(live_ll, 5)
                if cand_ll > live_ll + self.gate.metric_tol:
                    reasons.append(
                        f"metric_parity:logloss {cand_ll:.4f} > "
                        f"{live_ll:.4f}+{self.gate.metric_tol}")

    def _gate_latency(self, cand, ref, reasons, checks) -> None:
        cand_ms = cand["p99_s"] * 1000.0
        ref_ms = ref["p99_s"] * 1000.0
        checks["candP99Ms"] = round(cand_ms, 3)
        checks["liveP99Ms"] = round(ref_ms, 3)
        if cand_ms > max(ref_ms * self.gate.p99_factor, 1.0):
            reasons.append(
                f"latency:p99 {cand_ms:.1f}ms > "
                f"{self.gate.p99_factor}x live ({ref_ms:.1f}ms)")
        if (self.gate.p99_bound_ms is not None
                and cand_ms > self.gate.p99_bound_ms):
            reasons.append(
                f"latency:p99 {cand_ms:.1f}ms > bound "
                f"{self.gate.p99_bound_ms}ms")

    def _conclude(self, model, path, rows, reasons, checks) -> SwapDecision:
        if reasons:
            decision = SwapDecision(False, reasons, checks)
            self.last_decision = decision
            self.metrics.record_swap_decision(decision.to_json())
            record_event("swap.reject", reasons=list(reasons),
                         replay_rows=len(rows))
            return decision
        # PASS: pin the outgoing generation first — the rollback target
        # must exist before the new generation can take traffic
        self.registry.pin(self.name)
        entry = self.registry.register(self.name, model, path=path)
        golden = self._capture_golden(entry, rows)
        snap = self.metrics.snapshot()
        with self._lock:
            self._bake = {
                "version": entry.version,
                "golden": golden,
                "rows_seen": 0,
                "last_probe_rows": 0,
                "errors_at_swap": (snap["deviceErrors"]
                                   + snap["hostFallbacks"]),
                "requests_at_swap": snap["requests"],
            }
        decision = SwapDecision(True, [], checks, version=entry.version)
        self.last_decision = decision
        self.metrics.record_swap_decision(decision.to_json())
        record_event("swap.accept", version=entry.version,
                     replay_rows=len(rows))
        return decision

    def _capture_golden(self, entry: ModelEntry, rows) -> List[Dict[str, Any]]:
        """Golden queries = replay rows + the accepted candidate's own
        answers at decision time; bake probes assert the SERVED model
        still answers them (catches post-swap corruption/regression)."""
        take = list(rows[: self.gate.golden_rows])
        if not take:
            return []
        out = entry.scorer(take)
        return [{"row": r, "score": _score_of(_first_result(o))}
                for r, o in zip(take, out)]

    # -- bake window + rollback ----------------------------------------------

    @property
    def baking(self) -> bool:
        with self._lock:
            return self._bake is not None

    def bake_probe(self) -> Optional[str]:
        """Probe the CURRENT entry against the golden queries; returns the
        rollback reason when one fired (None otherwise).  Ends the bake
        window once ``bake_rows`` of traffic passed without incident."""
        with self._lock:
            bake = self._bake
        if bake is None:
            return None
        self._probes += 1
        reason: Optional[str] = None
        try:
            faults.fire("swap.bake", index=self._probes - 1)
            entry = self.registry.get(self.name)
            if entry.version != bake["version"]:
                # someone else swapped/rolled back under us: stop baking
                with self._lock:
                    self._bake = None
                return None
            golden = bake["golden"]
            if golden:
                out = entry.scorer([g["row"] for g in golden])
                got = np.asarray(
                    [_score_of(_first_result(o)) for o in out], np.float64)
                want = np.asarray([g["score"] for g in golden], np.float64)
                bad = int((np.abs(got - want) > self.gate.golden_tol).sum())
                if bad:
                    reason = f"probe_mismatch:{bad}/{len(golden)}"
            if reason is None:
                snap = self.metrics.snapshot()
                d_req = max(snap["requests"] - bake["requests_at_swap"], 1)
                d_err = ((snap["deviceErrors"] + snap["hostFallbacks"])
                         - bake["errors_at_swap"])
                rate = d_err / d_req
                if rate > self.gate.error_rate_max:
                    reason = f"error_rate:{rate:.3f}>{self.gate.error_rate_max}"
        except Exception as exc:
            reason = f"probe_error:{type(exc).__name__}"
        record_event("swap.bake_probe", probe=self._probes - 1,
                     ok=reason is None, reason=reason)
        if reason is not None:
            self.rollback(reason)
            return reason
        with self._lock:
            if (self._bake is bake
                    and bake["rows_seen"] >= self.gate.bake_rows):
                self._bake = None  # baked clean: the swap is final
        return None

    def rollback(self, reason: str) -> ModelEntry:
        """Reinstate the pinned last-known-good generation and record the
        structured reason (visible as ``lastRollbackReason`` in
        /metrics).  The circuit-breaker path is untouched — rollback is a
        model-quality action, not a device-health one."""
        entry = self.registry.rollback(self.name)
        self.metrics.record_rollback(reason)
        record_event("swap.rollback", reason=reason,
                     version=entry.version)
        with self._lock:
            self._bake = None
        return entry

    # -- reading -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            bake = None
            if self._bake is not None:
                bake = {"version": self._bake["version"],
                        "rowsSeen": self._bake["rows_seen"],
                        "goldenRows": len(self._bake["golden"])}
            return {
                "gate": self.gate.to_json(),
                "replayRows": len(self._replay),
                "proposals": self._proposals,
                "probes": self._probes,
                "baking": bake,
                "lastDecision": (self.last_decision.to_json()
                                 if self.last_decision else None),
            }
