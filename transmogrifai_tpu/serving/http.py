"""Minimal stdlib HTTP front end for a ``ModelServer``.

No framework dependency (the container bakes none in): a threaded
``http.server`` is plenty, because every request handler thread just parks
on a batcher future while the single dispatch thread does the real work —
the concurrency model IS the micro-batcher, not the HTTP layer.

Endpoints:
  POST /score    {"rows": [{...}, ...], "timeoutMs": 50}  -> {"scores": [...]}
                 (rows shed by backpressure come back as their ShedResult
                 JSON and flip the response to 503; multi-tenant servers
                 additionally take {"tenant": "<name>"})
  GET  /metrics  serving metrics snapshot (queue depth, batch histogram,
                 latency quantiles, shed/fallback counts, compile counters);
                 ``?format=prometheus`` renders the same ledgers (plus the
                 global RunCounters) in Prometheus text exposition for a
                 stock scraper (obs/prometheus.py) — multi-tenant servers
                 label every serving sample ``tenant="<name>"``
  GET  /healthz  {"status": "ok", "model": {...}} (multi-tenant: per-tenant
                 statuses; overall degraded if ANY tenant is)
  GET  /tenants  multi-tenant only: configured tenants + weights
  POST /swap     {"path": "/models/titanic_v2"}           -> new entry info
                 (multi-tenant: {"tenant": ..., "path": ...})
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from .admission import ShedResult

__all__ = ["make_http_server", "serve_forever"]


def _jsonable_scores(results) -> Tuple[list, bool]:
    out, any_shed = [], False
    for r in results:
        if isinstance(r, ShedResult):
            out.append(r.to_json())
            any_shed = True
        else:
            out.append(r)
    return out, any_shed


def make_http_server(server, host: str = "127.0.0.1",
                     port: int = 8080) -> ThreadingHTTPServer:
    """Build (not start) an HTTP server wrapping ``ModelServer`` ``server``."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _reply(self, code: int, payload: Any) -> None:
            body = json.dumps(payload, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> Optional[Any]:
            try:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError):
                return None

        def _reply_text(self, code: int, text: str,
                        content_type: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _healthz_single(self, srv):
            entry = srv.registry.maybe_get(srv.name)
            breaker_state = srv.breaker.state
            status = "ok" if entry else "no_model"
            if entry and breaker_state != srv.breaker.CLOSED:
                status = "degraded"  # serving, but from the host path
            return entry is not None, {
                "status": status,
                "model": entry.describe() if entry else None,
                "breakerState": breaker_state,
                "lastFallbackReason":
                    srv.metrics.last_fallback_reason,
            }

        def do_GET(self):
            url = urlsplit(self.path)
            self.path = url.path
            query = parse_qs(url.query)
            multi = getattr(server, "is_multi_tenant", False)
            if self.path == "/healthz":
                if multi:
                    tenants = {}
                    any_model, degraded = False, False
                    for name in server.tenants():
                        ok, doc = self._healthz_single(server.tenant(name))
                        tenants[name] = doc
                        any_model = any_model or ok
                        degraded = degraded or doc["status"] != "ok"
                    self._reply(200 if any_model else 503, {
                        "status": ("degraded" if degraded else "ok")
                        if any_model else "no_model",
                        "tenants": tenants,
                    })
                else:
                    ok, doc = self._healthz_single(server)
                    self._reply(200 if ok else 503, doc)
            elif self.path == "/metrics":
                fmt = (query.get("format") or ["json"])[0]
                if fmt == "prometheus":
                    from ..obs.prometheus import prometheus_text

                    if multi:
                        text = prometheus_text(
                            tenants=server.tenant_snapshots())
                    else:
                        text = prometheus_text(server.snapshot())
                    self._reply_text(
                        200, text,
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._reply(200, server.snapshot())
            elif self.path == "/tenants" and multi:
                snap = server.snapshot()
                self._reply(200, {
                    "tenants": [t["tenantConfig"]
                                for _, t in sorted(snap["tenants"].items())]})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            doc = self._read_json()
            if doc is None or not isinstance(doc, dict):
                return self._reply(400, {"error": "invalid JSON body"})
            multi = getattr(server, "is_multi_tenant", False)
            if self.path == "/score":
                rows = doc.get("rows")
                if not isinstance(rows, list):
                    return self._reply(
                        400, {"error": "body must be {'rows': [...]}"})
                try:
                    if multi:
                        results = server.score(
                            rows, tenant=doc.get("tenant"),
                            timeout_ms=doc.get("timeoutMs"))
                    else:
                        results = server.score(
                            rows, timeout_ms=doc.get("timeoutMs"))
                except KeyError as exc:  # unknown/ambiguous tenant
                    return self._reply(404, {"error": str(exc)})
                except TypeError as exc:  # non-dict rows etc.
                    return self._reply(400, {"error": str(exc)})
                scores, any_shed = _jsonable_scores(results)
                self._reply(503 if any_shed else 200, {"scores": scores})
            elif self.path == "/swap":
                path = doc.get("path")
                if not path:
                    return self._reply(
                        400, {"error": "body must be {'path': ...}"})
                try:
                    if multi:
                        entry = server.swap(doc.get("tenant"), path)
                    else:
                        entry = server.swap(path)
                except KeyError as exc:
                    return self._reply(404, {"error": str(exc)})
                except Exception as exc:
                    return self._reply(500, {"error": str(exc)})
                self._reply(200, {"swapped": entry.describe()})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return ThreadingHTTPServer((host, port), Handler)


def serve_forever(server, host: str = "127.0.0.1", port: int = 8080,
                  background: bool = False):
    """Start serving HTTP; returns the httpd (after start when background)."""
    httpd = make_http_server(server, host, port)
    if background:
        t = threading.Thread(target=httpd.serve_forever,
                             name="op-serving-http", daemon=True)
        t.start()
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        httpd.server_close()
    return httpd
