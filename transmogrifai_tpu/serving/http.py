"""Minimal stdlib HTTP front end for a ``ModelServer``.

No framework dependency (the container bakes none in): a threaded
``http.server`` is plenty, because every request handler thread just parks
on a batcher future while the single dispatch thread does the real work —
the concurrency model IS the micro-batcher, not the HTTP layer.

Endpoints:
  POST /score    {"rows": [{...}, ...], "timeoutMs": 50}  -> {"scores": [...]}
                 (rows shed by backpressure come back as their ShedResult
                 JSON and flip the response to 503; multi-tenant servers
                 additionally take {"tenant": "<name>"})
  GET  /metrics  serving metrics snapshot (queue depth, batch histogram,
                 latency quantiles, shed/fallback counts, compile counters);
                 ``?format=prometheus`` renders the same ledgers (plus the
                 global RunCounters) in Prometheus text exposition for a
                 stock scraper (obs/prometheus.py) — multi-tenant servers
                 label every serving sample ``tenant="<name>"``
  GET  /healthz  {"status": "ok", "model": {...}, "shedRate": ...,
                 "draining": ...} (multi-tenant: per-tenant statuses;
                 overall degraded if ANY tenant is) — the router's
                 health probe (serving/fabric.py) feeds on this doc
  GET  /tenants  multi-tenant only: configured tenants + weights
  POST /swap     {"path": "/models/titanic_v2"}           -> new entry info
                 (multi-tenant: {"tenant": ..., "path": ...})
  POST /drain    begin graceful drain: stop admitting (new submits shed
                 with reason "draining"), let in-flight complete; /healthz
                 flips to "draining" so the router stops routing here

Handler connections carry a SERVER-SIDE socket timeout
(``request_timeout_s``): a stalled or half-open client used to hold its
worker thread indefinitely, which under the fabric router's retry policy
turns one slow client into a thread leak across the fleet — now the read
times out and the connection closes.
"""
from __future__ import annotations

import json
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from .admission import ShedResult

__all__ = ["make_http_server", "serve_forever", "healthz_doc",
           "install_sigterm_drain"]


def _jsonable_scores(results) -> Tuple[list, bool]:
    out, any_shed = [], False
    for r in results:
        if isinstance(r, ShedResult):
            out.append(r.to_json())
            any_shed = True
        else:
            out.append(r)
    return out, any_shed


def _healthz_single_doc(srv) -> Tuple[bool, dict]:
    """One server's health doc: model presence, breaker state, shed rate
    (the fraction of offered rows shed — the router's spill signal), and
    drain state."""
    entry = srv.registry.maybe_get(srv.name)
    breaker_state = srv.breaker.state
    draining = bool(getattr(srv, "draining", False))
    status = "ok" if entry else "no_model"
    if entry and breaker_state != srv.breaker.CLOSED:
        status = "degraded"  # serving, but from the host path
    if entry and draining:
        status = "draining"
    snap = srv.metrics.snapshot()
    offered = (snap.get("requests") or 0) + (snap.get("shed") or 0)
    shed_rate = (snap.get("shed") or 0) / offered if offered else 0.0
    return entry is not None, {
        "status": status,
        "model": entry.describe() if entry else None,
        "breakerState": breaker_state,
        "lastFallbackReason": srv.metrics.last_fallback_reason,
        "shedRate": round(shed_rate, 4),
        "draining": draining,
    }


def healthz_doc(server) -> Tuple[bool, dict]:
    """The ``/healthz`` document for a single- or multi-tenant server —
    module-level so in-process host handles (fabric.LocalHostHandle) see
    the exact same doc a remote router reads over HTTP."""
    if getattr(server, "is_multi_tenant", False):
        tenants = {}
        any_model, degraded = False, False
        draining = bool(getattr(server, "draining", False))
        shed_rates = []
        for name in server.tenants():
            ok, doc = _healthz_single_doc(server.tenant(name))
            tenants[name] = doc
            any_model = any_model or ok
            degraded = degraded or doc["status"] not in ("ok", "draining")
            shed_rates.append(doc["shedRate"])
        status = "ok" if any_model else "no_model"
        if any_model and degraded:
            status = "degraded"
        if any_model and draining:
            status = "draining"
        return any_model, {
            "status": status,
            "tenants": tenants,
            "draining": draining,
            "shedRate": round(max(shed_rates), 4) if shed_rates else 0.0,
        }
    return _healthz_single_doc(server)


def make_http_server(server, host: str = "127.0.0.1", port: int = 8080,
                     request_timeout_s: float = 30.0
                     ) -> ThreadingHTTPServer:
    """Build (not start) an HTTP server wrapping ``ModelServer`` ``server``."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # server-side socket timeout: BaseHTTPRequestHandler applies this
        # to the connection before reading the request line, so a half-
        # open client releases its worker thread instead of pinning it
        timeout = request_timeout_s

        def handle_one_request(self):
            try:
                super().handle_one_request()
            except TimeoutError:  # socket.timeout — stalled client
                self.close_connection = True

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _reply(self, code: int, payload: Any) -> None:
            body = json.dumps(payload, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> Optional[Any]:
            try:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError):
                return None

        def _reply_text(self, code: int, text: str,
                        content_type: str) -> None:
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            url = urlsplit(self.path)
            self.path = url.path
            query = parse_qs(url.query)
            multi = getattr(server, "is_multi_tenant", False)
            if self.path == "/healthz":
                ok, doc = healthz_doc(server)
                self._reply(200 if ok else 503, doc)
            elif self.path == "/metrics":
                fmt = (query.get("format") or ["json"])[0]
                if fmt == "prometheus":
                    from ..obs.prometheus import prometheus_text

                    if multi:
                        text = prometheus_text(
                            tenants=server.tenant_snapshots())
                    else:
                        text = prometheus_text(server.snapshot())
                    self._reply_text(
                        200, text,
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._reply(200, server.snapshot())
            elif self.path == "/tenants" and multi:
                snap = server.snapshot()
                self._reply(200, {
                    "tenants": [t["tenantConfig"]
                                for _, t in sorted(snap["tenants"].items())]})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            doc = self._read_json()
            if doc is None or not isinstance(doc, dict):
                return self._reply(400, {"error": "invalid JSON body"})
            multi = getattr(server, "is_multi_tenant", False)
            if self.path == "/score":
                rows = doc.get("rows")
                if not isinstance(rows, list):
                    return self._reply(
                        400, {"error": "body must be {'rows': [...]}"})
                try:
                    if multi:
                        results = server.score(
                            rows, tenant=doc.get("tenant"),
                            timeout_ms=doc.get("timeoutMs"))
                    else:
                        results = server.score(
                            rows, timeout_ms=doc.get("timeoutMs"))
                except KeyError as exc:  # unknown/ambiguous tenant
                    return self._reply(404, {"error": str(exc)})
                except TypeError as exc:  # non-dict rows etc.
                    return self._reply(400, {"error": str(exc)})
                scores, any_shed = _jsonable_scores(results)
                self._reply(503 if any_shed else 200, {"scores": scores})
            elif self.path == "/swap":
                path = doc.get("path")
                if not path:
                    return self._reply(
                        400, {"error": "body must be {'path': ...}"})
                try:
                    if multi:
                        entry = server.swap(doc.get("tenant"), path)
                    else:
                        entry = server.swap(path)
                except KeyError as exc:
                    return self._reply(404, {"error": str(exc)})
                except Exception as exc:
                    return self._reply(500, {"error": str(exc)})
                self._reply(200, {"swapped": entry.describe()})
            elif self.path == "/drain":
                server.begin_drain()
                self._reply(200, {"draining": True})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    class _Server(ThreadingHTTPServer):
        # stdlib default is 5: under fabric-router load (many clients,
        # one connection per request) a connect burst deeper than that
        # gets REFUSED at the socket, which the router reads as a dead
        # host — a healthy replica must absorb the burst in the backlog
        request_queue_size = 128

    return _Server((host, port), Handler)


def install_sigterm_drain(server, httpd) -> None:
    """SIGTERM → graceful drain: stop admissions immediately (new submits
    shed with reason ``"draining"``, /healthz flips to "draining" so the
    router deregisters this host), let in-flight batches complete, then
    stop the server and shut the HTTP listener down.  Pair with the
    router's hard-failure path: SIGKILL skips all of this and relies on
    heartbeat-timeout eviction + retry-to-survivor instead."""

    def _drain(_signum, _frame):
        def worker():
            server.begin_drain()
            server.stop(drain=True)
            httpd.shutdown()

        threading.Thread(target=worker, name="op-serving-drain",
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _drain)


def serve_forever(server, host: str = "127.0.0.1", port: int = 8080,
                  background: bool = False,
                  request_timeout_s: float = 30.0):
    """Start serving HTTP; returns the httpd (after start when background)."""
    httpd = make_http_server(server, host, port,
                             request_timeout_s=request_timeout_s)
    if background:
        t = threading.Thread(target=httpd.serve_forever,
                             name="op-serving-http", daemon=True)
        t.start()
        return httpd
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        httpd.server_close()
    return httpd
