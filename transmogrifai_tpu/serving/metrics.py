"""Serving metrics — queue depth, batch-size histogram, latency quantiles.

The serving-side analogue of ``utils/profiling.py``'s per-run
``MetricsCollector``: a long-lived server has no "run end", so metrics are
a live snapshot API instead of an application-end handler.  Wall-clock per
executed batch is still attributed through the existing profiling hooks
(``OpStep.Serving`` into the thread-current collector, ``count_launch``
into the global ``RunCounters``) so serving time shows up in the same
ledgers as training/scoring time.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..utils.profiling import (MetricsCollector, OpStep, count_launch,
                               current_collector)

__all__ = ["LatencyReservoir", "ServingMetrics"]


class LatencyReservoir:
    """Fixed-capacity ring of recent latency observations (seconds).

    Quantiles are computed over the retained window — recent behavior, not
    process-lifetime behavior, which is what an operator watching p95 wants
    from a long-lived server.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._ring: List[float] = []
        self._pos = 0
        self.count = 0

    def observe(self, seconds: float) -> None:
        if len(self._ring) < self.capacity:
            self._ring.append(seconds)
        else:
            self._ring[self._pos] = seconds
            self._pos = (self._pos + 1) % self.capacity
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        if not self._ring:
            return None
        vals = sorted(self._ring)
        idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
        return vals[idx]


class ServingMetrics:
    """Thread-safe counters + histograms for one model server.

    Everything an operator needs to see the degradation ladder working:
    how deep the queue is, what batch sizes the coalescer actually forms,
    how much padding the bucketer adds, end-to-end latency quantiles, and
    how many requests were shed / deadline-expired / degraded to the host
    path.
    """

    def __init__(self, reservoir_capacity: int = 4096,
                 collector: Optional[MetricsCollector] = None):
        self._lock = threading.Lock()
        self._latency = LatencyReservoir(reservoir_capacity)
        self._batch_hist: Dict[int, int] = {}
        self.collector = collector
        self.started_at = time.time()
        self.queue_depth = 0
        self.queue_depth_peak = 0
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.padded_rows = 0
        self.shed = 0
        #: shed rows broken down by ShedResult reason ("queue_full",
        #: "draining", "shutting_down", ...) — the router's spill logic
        #: treats these differently, so the operator view must too
        self.shed_by_reason: Dict[str, int] = {}
        self.deadline_expired = 0
        self.device_errors = 0
        self.host_fallbacks = 0
        self.breaker_opens = 0
        self.hot_swaps = 0
        #: why the LAST host fallback engaged ("breaker_open", or
        #: "device_error:<ExceptionType>") + when — the operator-facing
        #: answer to "why is this replica slow": visible in /metrics and
        #: /healthz, not just a counter that something happened
        self.last_fallback_reason: Optional[str] = None
        self.last_fallback_at: Optional[float] = None
        #: guarded-swap lifecycle (serving/guarded.py): gate outcomes,
        #: rollbacks, and the STRUCTURED reason for each — the operator
        #: answer to "why is v7 not serving" lives here, not in logs
        self.swaps_accepted = 0
        self.swaps_rejected = 0
        self.rollbacks = 0
        self.last_swap_decision: Optional[Dict[str, Any]] = None
        self.last_rollback_reason: Optional[str] = None
        self.last_rollback_at: Optional[float] = None

    # -- recording ----------------------------------------------------------

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth
            self.queue_depth_peak = max(self.queue_depth_peak, depth)

    def record_admitted(self, n_rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += n_rows

    def record_batch(self, n_rows: int, bucket: int, seconds: float) -> None:
        """One executed micro-batch: ``n_rows`` real rows padded to
        ``bucket``."""
        with self._lock:
            self.batches += 1
            self.padded_rows += max(0, bucket - n_rows)
            self._batch_hist[bucket] = self._batch_hist.get(bucket, 0) + 1
        coll = self.collector or current_collector()
        if coll is not None:
            coll.record(OpStep.Serving, seconds)
        count_launch("serving.batch")

    def record_request_latency(self, seconds: float) -> None:
        with self._lock:
            self._latency.observe(seconds)

    def record_shed(self, n: int = 1, reason: Optional[str] = None) -> None:
        with self._lock:
            self.shed += n
            if reason is not None:
                self.shed_by_reason[reason] = \
                    self.shed_by_reason.get(reason, 0) + n

    def record_deadline_expired(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_expired += n

    def record_device_error(self) -> None:
        with self._lock:
            self.device_errors += 1

    def record_host_fallback(self, n_rows: int = 0,
                             reason: Optional[str] = None) -> None:
        with self._lock:
            self.host_fallbacks += 1
            if reason is not None:
                self.last_fallback_reason = reason
                self.last_fallback_at = time.time()

    def record_breaker_open(self) -> None:
        with self._lock:
            self.breaker_opens += 1

    def record_hot_swap(self) -> None:
        with self._lock:
            self.hot_swaps += 1

    def record_swap_decision(self, decision: Dict[str, Any]) -> None:
        """One guarded-swap gate outcome (serving/guarded.py SwapDecision
        JSON): accepted candidates count as swaps, rejected ones keep the
        structured reasons visible in /metrics."""
        with self._lock:
            if decision.get("accepted"):
                self.swaps_accepted += 1
            else:
                self.swaps_rejected += 1
            self.last_swap_decision = decision

    def record_rollback(self, reason: str) -> None:
        with self._lock:
            self.rollbacks += 1
            self.last_rollback_reason = reason
            self.last_rollback_at = time.time()

    # -- reading ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time JSON-able view (the /metrics payload)."""
        from ..utils.compile_cache import cache_stats

        with self._lock:
            lat_ms = {
                f"p{int(q * 100)}": (None if v is None
                                     else round(v * 1000.0, 3))
                for q, v in ((q, self._latency.quantile(q))
                             for q in (0.50, 0.95, 0.99))
            }
            snap = {
                "uptimeSecs": round(time.time() - self.started_at, 3),
                "queueDepth": self.queue_depth,
                "queueDepthPeak": self.queue_depth_peak,
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "paddedRows": self.padded_rows,
                "batchSizeHistogram": dict(sorted(self._batch_hist.items())),
                "latencyMs": lat_ms,
                "latencyObservations": self._latency.count,
                "shed": self.shed,
                "shedByReason": dict(sorted(self.shed_by_reason.items())),
                "deadlineExpired": self.deadline_expired,
                "deviceErrors": self.device_errors,
                "hostFallbacks": self.host_fallbacks,
                "breakerOpens": self.breaker_opens,
                "hotSwaps": self.hot_swaps,
                "lastFallbackReason": self.last_fallback_reason,
                "lastFallbackAgeSecs": (
                    None if self.last_fallback_at is None
                    else round(time.time() - self.last_fallback_at, 3)),
                "swapsAccepted": self.swaps_accepted,
                "swapsRejected": self.swaps_rejected,
                "rollbacks": self.rollbacks,
                "lastSwapDecision": self.last_swap_decision,
                "lastRollbackReason": self.last_rollback_reason,
                "lastRollbackAgeSecs": (
                    None if self.last_rollback_at is None
                    else round(time.time() - self.last_rollback_at, 3)),
            }
        snap["compileCache"] = cache_stats()
        return snap
