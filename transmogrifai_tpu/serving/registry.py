"""Model registry — versioned, hot-swappable persisted workflow models.

Loads models through ``workflow/persistence.py`` (the same artifact a
training run saves), builds the host score function ONCE per load (the
scoring DAG is memoized on the model, so registry reloads never redo DAG
construction), and exposes an atomic get/swap surface: scoring threads
resolve a ``ModelEntry`` by name and keep using that immutable entry for
the whole batch even while a newer version is being swapped in — no lock
is held across scoring.

Lock-order convention (pinned by the TM053 lint, analysis/concur_lint.py):
the registry lock is a LEAF — nothing is called out to while holding it
(listeners fire after release, entry construction happens before
acquisition), so it can never participate in an acquisition-order cycle
with the admission/batcher/metrics locks.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ModelEntry", "ModelRegistry"]


class ModelEntry:
    """One immutable (name, version) of a servable model."""

    def __init__(self, name: str, version: int, model: Any,
                 path: Optional[str] = None):
        from ..local.scorer import score_function_batch

        self.name = name
        self.version = version
        self.model = model
        self.path = path
        self.loaded_at = time.time()
        #: host score function (rows -> score maps); built once per entry
        self.scorer = score_function_batch(model)
        self.result_features = [f.name for f in model.result_features]

    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "version": self.version,
            "path": self.path,
            "loadedAt": self.loaded_at,
            "resultFeatures": list(self.result_features),
        }

    def __repr__(self):
        return f"ModelEntry({self.name!r} v{self.version})"


class ModelRegistry:
    """Thread-safe name -> ModelEntry map with versioned atomic swaps.

    Beyond the current entry, the registry retains a bounded GENERATION
    history per name (``max_generations`` slots, oldest-first eviction)
    so a guarded swap (serving/guarded.py) can pin the last-known-good
    generation and roll back to it.  Eviction NEVER drops the pinned
    generation or the current one — the rollback target must survive any
    amount of swap churn (the whole point of pinning).
    """

    def __init__(self, max_generations: int = 4):
        if max_generations < 1:
            raise ValueError("max_generations must be >= 1")
        self._lock = threading.Lock()
        self._entries: Dict[str, ModelEntry] = {}
        self._versions: Dict[str, int] = {}
        self._swap_listeners: List[Callable[[ModelEntry], None]] = []
        self.max_generations = int(max_generations)
        #: name -> {version: entry}, oldest-insertion-first
        self._history: Dict[str, Dict[int, ModelEntry]] = {}
        #: name -> pinned (last-known-good) version
        self._pinned: Dict[str, int] = {}

    # -- lifecycle ----------------------------------------------------------

    def load(self, name: str, path: str) -> ModelEntry:
        """Load (or hot-swap) ``name`` from a persisted model directory.

        The expensive work — artifact parse, stage reconstruction, scoring
        DAG + score-function build — happens OUTSIDE the lock; only the
        final dict swap is locked, so in-flight scoring against the old
        entry is never blocked and either sees the old version or the new
        one, never a half-built state.
        """
        from ..workflow.persistence import load_workflow_model

        model = load_workflow_model(path)
        return self.register(name, model, path=path)

    def register(self, name: str, model: Any,
                 path: Optional[str] = None) -> ModelEntry:
        """Register an in-memory model (tests / freshly-trained hot swaps)."""
        with self._lock:
            version = self._versions.get(name, 0) + 1
            self._versions[name] = version
        # expensive: scoring DAG + score-function build (no lock held)
        entry = ModelEntry(name, version, model, path=path)
        with self._lock:
            current = self._entries.get(name)
            if current is not None and current.version > entry.version:
                # a concurrent newer load finished first; keep it
                return current
            swapped = current is not None
            self._entries[name] = entry
            hist = self._history.setdefault(name, {})
            hist[entry.version] = entry
            self._evict_generations(name)
            listeners = list(self._swap_listeners)
        if swapped:
            for fn in listeners:
                try:
                    fn(entry)
                except Exception:  # listeners must not break the swap
                    pass
        return entry

    def _evict_generations(self, name: str) -> None:
        """Slot-based eviction of stale generations (lock held).  The
        CURRENT entry and the PINNED last-known-good generation are never
        eviction candidates — dropping the rollback target under swap
        churn was the bug this guard pins (regression-tested)."""
        hist = self._history.get(name)
        if hist is None:
            return
        current = self._entries.get(name)
        protected = {self._pinned.get(name)}
        if current is not None:
            protected.add(current.version)
        for version in sorted(hist):
            if len(hist) <= self.max_generations:
                break
            if version in protected:
                continue
            del hist[version]

    def evict(self, name: str) -> bool:
        """Drop ``name`` (ALL generations, pin included) from the
        registry; in-flight batches holding an entry finish unaffected.
        Returns True if something was evicted."""
        with self._lock:
            self._history.pop(name, None)
            self._pinned.pop(name, None)
            return self._entries.pop(name, None) is not None

    # -- generations / pinning ----------------------------------------------

    def pin(self, name: str, version: Optional[int] = None) -> ModelEntry:
        """Pin a generation (default: the current one) as last-known-good:
        it survives generation eviction and is the ``rollback`` target."""
        with self._lock:
            if version is None:
                current = self._entries.get(name)
                if current is None:
                    raise KeyError(f"no model {name!r} to pin")
                version = current.version
            entry = self._history.get(name, {}).get(version)
            if entry is None:
                raise KeyError(f"no generation v{version} of {name!r} "
                               f"in the registry history")
            self._pinned[name] = version
            return entry

    def unpin(self, name: str) -> None:
        with self._lock:
            self._pinned.pop(name, None)
            self._evict_generations(name)

    def pinned(self, name: str) -> Optional[ModelEntry]:
        with self._lock:
            version = self._pinned.get(name)
            if version is None:
                return None
            return self._history.get(name, {}).get(version)

    def generations(self, name: str) -> List[Dict[str, Any]]:
        with self._lock:
            hist = list(self._history.get(name, {}).values())
            pinned = self._pinned.get(name)
            current = self._entries.get(name)
        out = []
        for e in hist:
            rec = e.describe()
            rec["pinned"] = e.version == pinned
            rec["current"] = current is not None and \
                e.version == current.version
            out.append(rec)
        return out

    def rollback(self, name: str) -> ModelEntry:
        """Atomically reinstate the pinned last-known-good generation as
        the current entry (its original version id is kept — rollbacks
        are visible in the version sequence).  Swap listeners fire, so a
        server re-warms the restored generation's buckets exactly like a
        forward swap."""
        with self._lock:
            version = self._pinned.get(name)
            if version is None:
                raise KeyError(f"no pinned generation for {name!r}")
            entry = self._history.get(name, {}).get(version)
            if entry is None:  # pragma: no cover - pin protects eviction
                raise KeyError(f"pinned generation v{version} of {name!r} "
                               f"is gone")
            self._entries[name] = entry
            listeners = list(self._swap_listeners)
        for fn in listeners:
            try:
                fn(entry)
            except Exception:  # listeners must not break the rollback
                pass
        return entry

    # -- resolution ---------------------------------------------------------

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
            have = sorted(self._entries)
        if entry is None:
            raise KeyError(
                f"no model {name!r} in registry "
                f"(have: {have or 'none'})")
        return entry

    def maybe_get(self, name: str) -> Optional[ModelEntry]:
        with self._lock:
            return self._entries.get(name)

    def models(self) -> List[Dict[str, Any]]:
        with self._lock:
            entries = list(self._entries.values())
        return [e.describe() for e in entries]

    def on_swap(self, fn: Callable[[ModelEntry], None]) -> None:
        """Register a hot-swap listener (the server re-warms shape buckets
        for the incoming version before routing traffic to it)."""
        with self._lock:
            self._swap_listeners.append(fn)
