"""Multi-tenant serving — per-tenant quotas, weighted-fair dequeue, isolation.

One host, one registry, MANY served models: every tenant gets its own
registry name, admission quota, circuit breaker, metrics ledger, shape-
bucketed executor set, and (optionally) its own DriftMonitor + GuardedSwap
— the per-tenant machinery has existed since PR 10; this module is the
plumbing that shares one dispatch loop across tenants WITHOUT letting one
tenant's behavior leak into another's:

* **quotas** — admission is per tenant (``max_queue_rows`` each), so a
  flooding tenant sheds its own traffic and ONLY its own traffic;
* **weighted-fair dequeue** — the dispatcher picks the next batch by
  virtual-time WFQ (``vtime += rows / weight``): under saturation each
  tenant's dispatched-row share converges to its weight, while an idle
  tenant re-entering is clamped to the current virtual clock so it cannot
  hoard credit and starve the others;
* **isolation** — batches never mix tenants (they are different models);
  a breaker opening, a shed storm, or a guarded-swap rollback on tenant A
  touches only A's breaker/metrics/generations (test-asserted);
* **observability** — ``snapshot()`` nests per-tenant serving snapshots,
  and the Prometheus exposition labels every serving sample with
  ``tenant="<name>"`` (obs/prometheus.py).

Batch formation per tenant reuses the continuous-batching policy
(greedy bucket choice from queue depth + predicted per-bucket cost, see
serving/batcher.py); execution reuses the tenant's full degradation
ladder (``ModelServer._execute``: breaker -> device/AOT path -> host
fallback), so everything PR 1-12 built per server now exists per tenant.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..obs.flight import record_event
from ..obs.trace import begin_span, end_span
from .admission import ShedResult
from .batcher import _Pending
from .executor import bucket_for, bucket_sizes
from .registry import ModelEntry, ModelRegistry

__all__ = ["TenantConfig", "MultiTenantServer"]


class TenantConfig:
    """Static per-tenant serving configuration.

    ``weight`` is the WFQ share (2.0 gets twice the dispatched rows of
    1.0 under saturation); ``max_queue_rows`` is the tenant's admission
    quota — both enforced per tenant, never pooled.
    """

    def __init__(self, name: str, weight: float = 1.0,
                 max_batch: int = 64, max_queue_rows: int = 1024,
                 default_deadline_ms: Optional[float] = None,
                 failure_threshold: int = 3,
                 breaker_reset_s: float = 30.0,
                 warmup_row: Optional[Dict[str, Any]] = None):
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        self.name = str(name)
        self.weight = float(weight)
        self.max_batch = int(max_batch)
        self.max_queue_rows = int(max_queue_rows)
        self.default_deadline_ms = default_deadline_ms
        self.failure_threshold = int(failure_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self.warmup_row = warmup_row

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "weight": self.weight,
                "maxBatch": self.max_batch,
                "maxQueueRows": self.max_queue_rows}


class _TenantLane:
    """One tenant's runtime state inside the shared dispatcher."""

    def __init__(self, config: TenantConfig, server):
        self.config = config
        self.server = server          # per-tenant ModelServer (its
        #                               batcher is NEVER started — the
        #                               shared dispatcher owns dequeue)
        self.queue: List[_Pending] = []
        self.vtime = 0.0
        self.dispatched_rows = 0
        self.buckets = bucket_sizes(config.max_batch)

    def queued_rows(self) -> int:
        return sum(len(p.rows) for p in self.queue)


class MultiTenantServer:
    """Weighted-fair multi-tenant serving over one shared registry.

    Usage::

        mts = MultiTenantServer(device_programs=True, aot_store=True)
        mts.add_tenant(TenantConfig("ads", weight=3.0), path="/models/ads")
        mts.add_tenant(TenantConfig("risk"), path="/models/risk")
        with mts:
            out = mts.score([{...}], tenant="ads")
    """

    is_multi_tenant = True

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 device_programs: bool = False, aot_store: Any = None,
                 max_generations: int = 4):
        self.registry = registry or ModelRegistry(
            max_generations=max_generations)
        self.device_programs = device_programs
        self.aot_store = aot_store
        self._lanes: Dict[str, _TenantLane] = {}
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._closing = False
        self._closed = False
        #: graceful-drain flag (fabric drain protocol): new submits shed
        #: with reason "draining" while queued work dispatches normally
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        #: WFQ virtual clock: the vtime of the most recently dispatched
        #: lane; re-activating lanes are clamped up to it (no hoarding)
        self._vclock = 0.0

    # -- tenant lifecycle ----------------------------------------------------

    def add_tenant(self, config, path: Optional[str] = None,
                   model: Any = None):
        """Register a tenant (``TenantConfig`` or just a name) and load or
        register its model.  Returns the tenant's ``ModelServer`` (the
        per-tenant engine: breaker, metrics, executors, drift, guard)."""
        from . import ModelServer

        if isinstance(config, str):
            config = TenantConfig(config)
        server = ModelServer(
            self.registry, config.name, max_batch=config.max_batch,
            max_queue_rows=config.max_queue_rows,
            default_deadline_ms=config.default_deadline_ms,
            failure_threshold=config.failure_threshold,
            breaker_reset_s=config.breaker_reset_s,
            warmup_row=config.warmup_row,
            device_programs=self.device_programs,
            aot_store=self.aot_store)
        lane = _TenantLane(config, server)
        with self._lock:
            if config.name in self._lanes:
                raise ValueError(f"tenant {config.name!r} already exists")
            lane.vtime = self._vclock
            self._lanes[config.name] = lane
        if path is not None:
            self.registry.load(config.name, path)
        elif model is not None:
            self.registry.register(config.name, model)
        return server

    def remove_tenant(self, name: str, drain_shed_reason: str =
                      "tenant_removed") -> bool:
        """Drop a tenant: queued pendings shed, model evicted.  Other
        tenants' queues and state are untouched."""
        with self._work:
            lane = self._lanes.pop(name, None)
            pendings = list(lane.queue) if lane else []
            if lane:
                lane.queue.clear()
        for p in pendings:
            lane.server.admission.release(len(p.rows))
            lane.server.metrics.record_shed(len(p.rows),
                                            reason=drain_shed_reason)
            p.future.set_result(
                [ShedResult(reason=drain_shed_reason) for _ in p.rows])
        self.registry.evict(name)
        return lane is not None

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._lanes)

    def tenant(self, name: str):
        """The tenant's per-tenant engine (``ModelServer``) — the handle
        for ``with_drift_monitor`` / ``with_guard`` / ``swap``."""
        with self._lock:
            lane = self._lanes.get(name)
        if lane is None:
            raise KeyError(f"no tenant {name!r} "
                           f"(have: {self.tenants() or 'none'})")
        return lane.server

    def _lane(self, name: Optional[str]) -> _TenantLane:
        # NOTE: the error paths must not call self.tenants() while the
        # (non-reentrant) lock is held — collect the names in the same
        # critical section instead
        with self._lock:
            have = sorted(self._lanes)
            if name is None:
                if len(self._lanes) == 1:
                    return next(iter(self._lanes.values()))
                raise KeyError(
                    f"tenant is required with {len(self._lanes)} tenants "
                    f"registered (have: {have})")
            lane = self._lanes.get(name)
        if lane is None:
            raise KeyError(f"no tenant {name!r} (have: {have or 'none'})")
        return lane

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MultiTenantServer":
        """Warm every tenant's buckets (largest-first; AOT-satisfied
        buckets load instead of compiling), then start the shared
        weighted-fair dispatcher."""
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            row = lane.config.warmup_row
            if row is not None:
                entry = self.registry.get(lane.config.name)
                lane.server._executor_for(entry).warmup(row)
        if self._thread is None or not self._thread.is_alive():
            self._closing = False
            self._closed = False
            self._thread = threading.Thread(
                target=self._dispatch_loop, name="op-serving-wfq",
                daemon=True)
            self._thread.start()
        return self

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting on every lane (new submits shed with reason
        ``"draining"``); queued pendings still dispatch.  The fabric
        router reads the flag via ``/healthz`` and deregisters."""
        self._draining = True

    def stop(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        alive = self._thread is not None and self._thread.is_alive()
        with self._work:
            self._closing = True
            if drain and alive:
                deadline = time.monotonic() + timeout_s
                while any(lane.queue for lane in self._lanes.values()):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._work.wait(timeout=min(remaining, 0.005))
            self._closed = True
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self) -> "MultiTenantServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- scoring -------------------------------------------------------------

    def submit(self, rows: Sequence[Dict[str, Any]],
               tenant: Optional[str] = None,
               timeout_ms: Optional[float] = None):
        """Enqueue ``rows`` on ``tenant``'s lane; same future contract as
        ``MicroBatcher.submit`` (sheds resolve, never raise)."""
        from concurrent.futures import Future

        lane = self._lane(tenant)
        rows = list(rows)
        fut: "Future[List[Any]]" = Future()
        if not rows:
            fut.set_result([])
            return fut
        server = lane.server
        span = begin_span("serve.admit", cat="serve", rows=len(rows),
                          tenant=lane.config.name)
        if self._draining:
            server.metrics.record_shed(len(rows), reason="draining")
            fut.set_result([ShedResult(reason="draining")
                            for _ in rows])
            end_span(span, outcome="shed:draining")
            return fut
        if self._closing or self._closed:
            server.metrics.record_shed(len(rows), reason="shutting_down")
            fut.set_result([ShedResult(reason="shutting_down")
                            for _ in rows])
            end_span(span, outcome="shed:shutting_down")
            return fut
        shed = server.admission.try_admit(len(rows))
        if shed is not None:
            server.metrics.record_shed(len(rows), reason=shed.reason)
            fut.set_result([shed for _ in rows])
            end_span(span, outcome=f"shed:{shed.reason}")
            record_event("serve.shed", rows=len(rows), reason=shed.reason,
                         tenant=lane.config.name)
            return fut
        pending = _Pending(rows,
                           server.admission.deadline_for(timeout_ms))
        with self._work:
            if self._closing or self._closed:
                server.admission.release(len(rows))
                server.metrics.record_shed(len(rows),
                                           reason="shutting_down")
                end_span(span, outcome="shed:shutting_down")
                fut.set_result([ShedResult(reason="shutting_down")
                                for _ in rows])
                return fut
            if not lane.queue:
                # idle lane re-entering: clamp to the virtual clock so a
                # long-idle tenant cannot starve the others with hoarded
                # credit
                lane.vtime = max(lane.vtime, self._vclock)
            server.metrics.record_admitted(len(rows))
            lane.queue.append(pending)
            server.metrics.set_queue_depth(lane.queued_rows())
            self._work.notify()
        end_span(span, outcome="admitted")
        return pending.future

    def score(self, rows: Sequence[Dict[str, Any]],
              tenant: Optional[str] = None,
              timeout_ms: Optional[float] = None,
              wait_s: Optional[float] = 60.0) -> List[Any]:
        return self.submit(rows, tenant=tenant,
                           timeout_ms=timeout_ms).result(timeout=wait_s)

    # -- model lifecycle (per tenant) -----------------------------------------

    def swap(self, tenant: Optional[str], path: str) -> ModelEntry:
        """Hot-swap one tenant's model (tenant optional only when a single
        tenant is registered) — other tenants' entries/generations are
        untouched by construction (distinct registry names)."""
        return self._lane(tenant).server.swap(path)

    # -- dispatch ------------------------------------------------------------

    def _pick_lane_locked(self) -> Optional[_TenantLane]:
        """Min-vtime lane among the non-empty ones — classic WFQ."""
        best: Optional[_TenantLane] = None
        for lane in self._lanes.values():
            if not lane.queue:
                continue
            if best is None or lane.vtime < best.vtime:
                best = lane
        return best

    def _form_batch_locked(self, lane: _TenantLane) -> List[_Pending]:
        """Continuous formation on one lane: greedy bucket from queue
        depth + the lane's predicted per-bucket cost (the tenant server's
        batcher cost lookup), FIFO no-split up to the bucket."""
        batcher = lane.server.batcher
        if batcher.cost_lookup is None:
            from ..tuning.costmodel import ServingCostLookup

            batcher.cost_lookup = ServingCostLookup()
        queued = lane.queued_rows()
        target = batcher._choose_bucket(queued)
        batch: List[_Pending] = []
        rows = 0
        while lane.queue:
            nxt = lane.queue[0]
            if batch and rows + len(nxt.rows) > target:
                break
            batch.append(lane.queue.pop(0))
            rows += len(nxt.rows)
            if rows >= target:
                break
        return batch

    def _dispatch_loop(self) -> None:
        from .batcher import run_pending_batch

        while True:
            with self._work:
                lane = self._pick_lane_locked()
                while lane is None and not self._closed:
                    self._work.wait(timeout=0.1)
                    lane = self._pick_lane_locked()
                if lane is None and self._closed:
                    return
                batch = self._form_batch_locked(lane)
                n_rows = sum(len(p.rows) for p in batch)
                lane.vtime += n_rows / lane.config.weight
                lane.dispatched_rows += n_rows
                self._vclock = max(self._vclock, lane.vtime)
                lane.server.metrics.set_queue_depth(lane.queued_rows())
                self._work.notify_all()  # wake a draining stop()
            if not batch:
                continue
            server = lane.server
            span = begin_span("serve.batch", cat="serve",
                              tenant=lane.config.name,
                              requests=len(batch), rows=n_rows,
                              mode="continuous")
            t0 = time.perf_counter()
            try:
                run_pending_batch(batch, server._execute,
                                  server.admission, server.metrics)
            finally:
                wall = time.perf_counter() - t0
                lookup = server.batcher.cost_lookup
                if lookup is not None and n_rows > 0:
                    lookup.observe(
                        bucket_for(min(n_rows, lane.config.max_batch),
                                   lane.buckets), wall)
                end_span(span)
                with self._work:
                    self._work.notify_all()

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Aggregate + per-tenant serving snapshots (the /metrics JSON)."""
        with self._lock:
            lanes = dict(self._lanes)
            vclock = self._vclock
        tenants: Dict[str, Any] = {}
        totals = {"requests": 0, "rows": 0, "batches": 0, "shed": 0,
                  "hostFallbacks": 0, "rollbacks": 0}
        for name, lane in sorted(lanes.items()):
            snap = lane.server.snapshot()
            snap["tenantConfig"] = lane.config.to_json()
            snap["wfq"] = {"vtime": round(lane.vtime, 3),
                           "dispatchedRows": lane.dispatched_rows}
            tenants[name] = snap
            for k in totals:
                totals[k] += snap.get(k) or 0
        return {"tenants": tenants, "aggregate": totals,
                "vclock": round(vclock, 3)}

    def tenant_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant ``ServingMetrics`` snapshots for the Prometheus
        exposition (labels come from the key)."""
        return {name: snap
                for name, snap in self.snapshot()["tenants"].items()}
