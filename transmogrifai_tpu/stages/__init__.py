from .base import *  # noqa: F401,F403
from .generator import FeatureGeneratorStage  # noqa: F401
