"""Pipeline stage abstraction.

Reference: ``OpPipelineStageBase``/``OpPipelineStage`` and the arity-typed
estimator/transformer bases
(features/src/main/scala/com/salesforce/op/stages/OpPipelineStages.scala:55,169,218-503;
stages/base/unary/UnaryTransformer.scala:104, UnaryEstimator.scala:56,118;
binary/ternary/quaternary/sequence equivalents).

TPU-native redesign notes:
 * Stages transform *columns* (vectorized numpy/JAX), not rows.  The
   row-level ``OpTransformer.transformKeyValue`` used by the reference for
   Spark-free local scoring (OpPipelineStages.scala:526-550) is replaced by
   running the same columnar code on a batch of one — no second code path.
 * Estimator ``fit`` receives the extracted input columns only, mirroring the
   typed ``Dataset`` handed to ``fitFn`` in the reference.
 * Param persistence: constructor kwargs are discovered via ``inspect`` (the
   Python analogue of the reference's reflection-based
   ``DefaultOpPipelineStageReaderWriter``) — see ``get_params``/``to_json``.
"""
from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..features.feature import Feature
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import FeatureType
from ..utils import faults
from ..utils.uid import uid_for

__all__ = [
    "SchemaError",
    "PipelineStage", "Transformer", "Estimator", "Model",
    "UnaryTransformer", "UnaryEstimator", "UnaryModel",
    "BinaryTransformer", "BinaryEstimator", "BinaryModel",
    "TernaryTransformer", "TernaryEstimator", "TernaryModel",
    "QuaternaryTransformer", "QuaternaryEstimator", "QuaternaryModel",
    "SequenceTransformer", "SequenceEstimator", "SequenceModel",
    "BinarySequenceTransformer", "BinarySequenceEstimator", "BinarySequenceModel",
    "LambdaTransformer",
]


class SchemaError(TypeError):
    """A stage was wired with an input of the wrong feature type.

    Raised at ``set_input`` time — the Python analogue of the reference's
    compile-time rejection of mis-typed wires — instead of the downstream
    ``KeyError``/dtype crash the bad column would cause layers later.  The
    message carries the stage uid plus expected/actual types; the static
    DAG lint re-checks the same declarations post-hoc as rule TM004.
    """


class PipelineStage:
    """Base of all stages.

    Subclass constructors must call ``super().__init__(operation_name=...,
    output_type=...)`` and store every hyperparameter as an attribute named
    exactly like the constructor keyword (sklearn convention) so persistence
    can round-trip it.
    """

    def __init__(
        self,
        operation_name: str,
        output_type: Type[FeatureType],
        uid: Optional[str] = None,
    ):
        self.operation_name = operation_name
        self.output_type = output_type
        self.uid = uid or uid_for(type(self))
        self.input_features: List[Feature] = []
        self._output_feature: Optional[Feature] = None
        #: structured metadata attached during fit (summaries, vector metadata)
        self.metadata: Dict[str, Any] = {}

    # -- input wiring (OpPipelineStageBase.setInput / checkInputLength) -----

    #: (min, max) allowed number of inputs; None = unbounded
    input_arity: Tuple[int, Optional[int]] = (1, None)

    #: declared per-position input feature types (the stage's input schema).
    #: ``None`` = untyped (accept anything, the historical behavior).  For
    #: variadic stages the LAST entry repeats for every further input.
    #: Checked at wiring time (``set_input`` raises ``SchemaError``) and
    #: statically by the DAG lint (analysis/linter.py rule TM004).
    input_types: Optional[Tuple[Type[FeatureType], ...]] = None

    #: input positions that legitimately receive the response/label (e.g.
    #: position 0 of SanityChecker and every model estimator).  The label-
    #: leakage lint (TM006) lets response-derived features flow into these
    #: and flags them anywhere else.
    label_input_positions: Tuple[int, ...] = ()

    #: Stages whose fit/transform dispatches XLA programs (models, the
    #: selector sweep, SanityChecker's stats pass).  The execution plan
    #: (workflow/plan.py) serializes these in stable layer order — one
    #: jit dispatch stream, deterministic compile-cache accounting — while
    #: host-side stages in the same layer run on the thread pool.
    device_heavy: bool = False

    def check_input_length(self, features: Sequence[Feature]) -> None:
        lo, hi = self.input_arity
        if len(features) < lo or (hi is not None and len(features) > hi):
            raise ValueError(
                f"{type(self).__name__} expects between {lo} and {hi} inputs, "
                f"got {len(features)}"
            )

    def expected_input_type(self, i: int) -> Optional[Type[FeatureType]]:
        """Declared feature type for input position ``i`` (None = untyped);
        for variadic stages the last declared entry repeats."""
        if not self.input_types:
            return None
        return self.input_types[min(i, len(self.input_types) - 1)]

    def check_input_schema(self, features: Sequence[Feature]) -> None:
        for i, f in enumerate(features):
            exp = self.expected_input_type(i)
            if exp is not None and not (isinstance(f.ftype, type)
                                        and issubclass(f.ftype, exp)):
                raise SchemaError(
                    f"{type(self).__name__}({self.uid}): input {i} "
                    f"({f.name!r}) must be {exp.__name__}, got "
                    f"{getattr(f.ftype, '__name__', f.ftype)}")

    def on_set_input(self) -> None:
        """Hook called after inputs are set (OpPipelineStageBase.onSetInput)."""

    def set_input(self, *features: Feature) -> "PipelineStage":
        self.check_input_length(features)
        self.check_input_schema(features)
        self.input_features = list(features)
        self.on_set_input()
        self._output_feature = Feature(
            name=self.make_output_name(),
            ftype=self.output_type,
            is_response=self.output_is_response(),
            origin_stage=self,
            parents=list(features),
        )
        return self

    def output_is_response(self) -> bool:
        return any(f.is_response for f in self.input_features)

    def make_output_name(self) -> str:
        base = "-".join(f.name for f in self.input_features[:4]) or "out"
        return f"{base}_{self.operation_name}_{self.uid}"

    def get_output(self) -> Feature:
        if self._output_feature is None:
            raise RuntimeError(f"{self.uid}: set_input() not called")
        return self._output_feature

    @property
    def input_names(self) -> List[str]:
        return [f.name for f in self.input_features]

    # -- params / persistence ----------------------------------------------

    # param names that are not hyperparameters
    _NON_PARAMS = frozenset({"uid", "operation_name", "output_type"})

    def get_params(self) -> Dict[str, Any]:
        """Hyperparameters = constructor kwargs, read back from attributes.

        Only the RESOLVED constructor signature counts: a subclass that
        re-parameterises its base (OpXGBoostClassifier's num_round/eta over
        _GBTBase's max_iter/step_size) must not report the base's kwargs —
        ``copy()`` feeds these back into ``__init__``, and base-only names
        made every XGBoost ``copy()`` (hence every XGB selector candidate)
        raise TypeError."""
        out = {}
        try:
            sig = inspect.signature(type(self).__init__)
        except (TypeError, ValueError):  # pragma: no cover - builtin init
            return out
        for name, p in sig.parameters.items():
            if name in ("self",) or p.kind in (p.VAR_POSITIONAL,
                                               p.VAR_KEYWORD):
                continue
            if name in self._NON_PARAMS:
                continue
            if hasattr(self, name):
                out[name] = getattr(self, name)
        return out

    def set_params(self, **params) -> "PipelineStage":
        for k, v in params.items():
            if not hasattr(self, k):
                raise ValueError(f"{type(self).__name__} has no param {k!r}")
            setattr(self, k, v)
        return self

    def copy(self, **overrides) -> "PipelineStage":
        """Fresh instance with same params (reference ReflectionUtils.copy).

        Required constructor args that get_params() excludes as
        non-hyperparameters (e.g. LambdaTransformer's output_type) are pulled
        from the instance's attributes; uid is never copied (new identity).
        """
        params = {**self.get_params(), **overrides}
        sig = inspect.signature(type(self).__init__)
        for name, p in sig.parameters.items():
            if (name not in ("self", "uid") and p.default is p.empty
                    and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
                    and name not in params and hasattr(self, name)):
                params[name] = getattr(self, name)
        return type(self)(**params)

    def extra_state(self) -> Dict[str, Any]:
        """Fitted state not captured by constructor params — persistence hook
        (analogue of a custom ``@ReaderWriter`` serializer, SURVEY §2.3).
        Values must be JSON-able or numpy arrays."""
        return {}

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        pass

    def __repr__(self):
        return f"{type(self).__name__}(uid={self.uid!r})"


class Transformer(PipelineStage):
    """A fitted/stateless stage: input columns -> one output column."""

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        raise NotImplementedError

    def transform_output(self, data: ColumnarDataset
                         ) -> Tuple[str, FeatureColumn]:
        """Compute this stage's output column WITHOUT touching the dataset.

        The execution-plan seam: the layer-parallel executor
        (workflow/plan.py) calls this concurrently for independent stages
        and merges the columns itself in stable stage order.
        """
        cols = [data[n] for n in self.input_names]
        out = self.transform_columns(*cols)
        if out.ftype is not self.output_type and not issubclass(
            out.ftype, self.output_type
        ):
            out = FeatureColumn(self.output_type, out.values, out.mask)
        return self.get_output().name, out

    def checked_transform_output(self, data: ColumnarDataset
                                 ) -> Tuple[str, FeatureColumn]:
        """``transform_output`` routed through the runtime contract guards
        when ``TMOG_CHECK=1`` (analysis/contracts.py: input buffers frozen
        ``writeable=False`` to catch COW violations, double-run determinism
        probe).  The executors call this instead of ``transform_output``
        directly; disabled mode costs one env lookup."""
        import os as _os

        if _os.environ.get("TMOG_CHECK") == "1":
            from ..analysis.contracts import guarded_transform_output

            return guarded_transform_output(self, data)
        return self.transform_output(data)

    def transform(self, data: ColumnarDataset) -> ColumnarDataset:
        """Copy-on-write transform: returns a NEW dataset view sharing every
        untouched column buffer with ``data`` (which is never mutated),
        with this stage's output appended/overridden."""
        faults.fire("stage.transform", tag=type(self).__name__)
        name, out = self.checked_transform_output(data)
        return data.with_columns({name: out})

    def transform_values(self, *rows: Any) -> Any:
        """Row-level transform via a batch of one (local-scoring parity)."""
        cols = [
            FeatureColumn.from_values(f.ftype, [v])
            for f, v in zip(self.input_features, rows)
        ]
        return self.transform_columns(*cols).to_list()[0]


class Model(Transformer):
    """A fitted estimator. Keeps the parent estimator's uid so workflow DAG
    substitution is by-uid (reference: models share the estimator uid)."""


class Estimator(PipelineStage):
    """A stage that must be fit before it can transform."""

    #: True when the subclass implements the mergeable streaming-fit
    #: protocol (begin_fit / update_chunk / merge_states / finish_fit) —
    #: the out-of-core two-pass driver (workflow/streaming.py) fits such
    #: stages one bounded chunk at a time instead of on a materialized
    #: dataset.  May be a property (e.g. SanityChecker streams for Pearson
    #: but not Spearman).
    supports_streaming_fit: bool = False

    #: documented |fit_streaming - fit| tolerance on transform outputs (the
    #: contract checker's TM022 bound): counting-based fits are exact, so
    #: the default only absorbs float noise; moment-based fitters override
    #: (e.g. RealVectorizer's chunked Welford summation order).
    streaming_fit_tol: float = 1e-6

    #: True when merge_states is commutative as well as associative —
    #: tie-break ordering (e.g. TopK first-seen ranks) makes most counting
    #: fits order-sensitive, so this is opt-in; the contract checker
    #: (TM021) only property-checks chunk-order permutations when set.
    streaming_order_insensitive: bool = False

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn) -> Model:
        raise NotImplementedError

    # -- streaming-fit protocol (XGBoost-style two-pass external memory) ----
    #
    # State objects are opaque to callers; the contract is:
    #   state = est.begin_fit()
    #   for chunk in chunks:  state = est.update_chunk(state, chunk, *cols)
    #   state = est.merge_states(a, b)   # associative combine (parallel
    #                                    # readers); chunk order still
    #                                    # matters for tie-break ordering
    #   model = est.finish_fit(state)    # NOT uid-wired; use fit_streaming
    #                                    # or adopt_model for DAG use
    # Implementations must be equivalent to ``fit_columns`` on the
    # concatenated chunks — exact for counting-based fits (vocabs, modes),
    # within documented float tolerance for moment-based fits.

    def begin_fit(self):
        raise NotImplementedError(
            f"{type(self).__name__} does not support streaming fit")

    def update_chunk(self, state, data: ColumnarDataset,
                     *cols: FeatureColumn):
        raise NotImplementedError(
            f"{type(self).__name__} does not support streaming fit")

    def merge_states(self, a, b):
        raise NotImplementedError(
            f"{type(self).__name__} does not support streaming fit")

    def finish_fit(self, state) -> Model:
        raise NotImplementedError(
            f"{type(self).__name__} does not support streaming fit")

    # -- checkpoint hooks (workflow/checkpoint.py) --------------------------
    #
    # The out-of-core driver periodically persists in-flight streaming-fit
    # states so a killed process resumes instead of refitting.  The default
    # hooks hand the state straight to the checkpoint codec, which handles
    # primitives, ndarrays, (nested) lists/dicts, and the sketch types with
    # to_state/from_state (WelfordMoments, PearsonSketch, TopKSketch,
    # TextStats).  Estimators whose state holds anything else override
    # these to translate to/from codec-safe structures; the round trip
    # must be EXACT — resume parity is asserted against uninterrupted runs.

    def export_fit_state(self, state):
        """Streaming-fit state -> checkpoint-codec-safe structure."""
        return state

    def import_fit_state(self, payload):
        """Inverse of ``export_fit_state``."""
        return payload

    def fit_streaming(self, chunks) -> Model:
        """Fit from an iterable of ``ColumnarDataset`` chunks via the
        streaming protocol; the returned model is uid-wired exactly like
        ``fit``'s."""
        import time as _time

        from ..utils.profiling import current_collector

        coll = current_collector()
        t0 = _time.perf_counter()
        state = self.begin_fit()
        for chunk in chunks:
            cols = [chunk[n] for n in self.input_names]
            state = self.update_chunk(state, chunk, *cols)
        model = self.finish_fit(state)
        self._record_fit_wall(coll, _time.perf_counter() - t0)
        return self.adopt_model(model)

    def _record_fit_wall(self, coll, dt: float) -> None:
        if coll is not None:
            # per-stage fit attribution (the Spark listener's per-stage
            # metrics analogue) — custom tags, not OpStep enum entries
            name = f"fit:{type(self).__name__}"
            prev = float(coll.metrics.custom_tags.get(name, 0.0) or 0.0)
            coll.metrics.custom_tags[name] = round(prev + dt, 3)

    def adopt_model(self, model: Model) -> Model:
        """Wire a freshly-built model to answer for this estimator's output
        feature / uid (shared by ``fit`` and the streaming driver)."""
        model.uid = self.uid
        model.operation_name = self.operation_name
        model.input_features = list(self.input_features)
        model._output_feature = self._output_feature
        model.metadata = self.metadata
        return model

    def fit(self, data: ColumnarDataset) -> Model:
        import time as _time

        from ..utils.profiling import current_collector

        cols = [data[n] for n in self.input_names]
        coll = current_collector()
        t0 = _time.perf_counter()
        model = self.fit_columns(data, *cols)
        self._record_fit_wall(coll, _time.perf_counter() - t0)
        # the model answers for the same output feature / uid
        return self.adopt_model(model)


# ---------------------------------------------------------------------------
# Arity-typed conveniences (reference stages/base/{unary,binary,...})
# ---------------------------------------------------------------------------

class UnaryTransformer(Transformer):
    input_arity = (1, 1)


class BinaryTransformer(Transformer):
    input_arity = (2, 2)


class TernaryTransformer(Transformer):
    input_arity = (3, 3)


class QuaternaryTransformer(Transformer):
    input_arity = (4, 4)


class SequenceTransformer(Transformer):
    """Variadic same-typed inputs (reference SequenceTransformer)."""
    input_arity = (1, None)


class BinarySequenceTransformer(Transformer):
    """One distinguished input + variadic same-typed rest."""
    input_arity = (2, None)


class UnaryModel(Model):
    input_arity = (1, 1)


class BinaryModel(Model):
    input_arity = (2, 2)


class TernaryModel(Model):
    input_arity = (3, 3)


class QuaternaryModel(Model):
    input_arity = (4, 4)


class SequenceModel(Model):
    input_arity = (1, None)


class BinarySequenceModel(Model):
    input_arity = (2, None)


class UnaryEstimator(Estimator):
    input_arity = (1, 1)


class BinaryEstimator(Estimator):
    input_arity = (2, 2)


class TernaryEstimator(Estimator):
    input_arity = (3, 3)


class QuaternaryEstimator(Estimator):
    input_arity = (4, 4)


class SequenceEstimator(Estimator):
    input_arity = (1, None)


class BinarySequenceEstimator(Estimator):
    input_arity = (2, None)


class LambdaTransformer(UnaryTransformer):
    """Wrap a plain column function as a stage (FeatureBuilder/DSL helper).

    ``fn`` maps FeatureColumn -> FeatureColumn of ``output_type``.
    Note: lambdas are not JSON-persistable; persistable pipelines should use
    named stages (same caveat as the reference's macro-captured lambdas).
    """

    def __init__(
        self,
        fn: Callable[[FeatureColumn], FeatureColumn],
        output_type: Type[FeatureType],
        operation_name: str = "lambda",
        uid: Optional[str] = None,
    ):
        super().__init__(operation_name=operation_name, output_type=output_type, uid=uid)
        self.fn = fn

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        return self.fn(col)
