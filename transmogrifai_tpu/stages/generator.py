"""Raw feature generation — stage #0 of every feature.

Reference: ``FeatureGeneratorStage`` (features/stages/FeatureGeneratorStage.scala:67):
holds the record->value ``extract_fn``, a default monoid aggregator for
event-aggregated readers, and an optional aggregation time window.
"""
from __future__ import annotations

import os
import sys
from typing import Any, Callable, Optional, Sequence, Type

from ..features.feature import Feature
from ..types.columns import FeatureColumn
from ..types.feature_types import FeatureType
from .base import PipelineStage

__all__ = ["FeatureGeneratorStage"]

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _construction_site() -> Optional[str]:
    """``file:line`` of the first caller frame OUTSIDE this package — where
    the user declared the feature.  The event-time lint (TM060,
    analysis/linter.py) anchors its findings and ``# tmog: disable=``
    suppressions there, not at the stage class definition."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover - interpreter without frames
        return None
    while f is not None:
        fn = f.f_code.co_filename
        if not os.path.abspath(fn).startswith(_PKG_ROOT + os.sep):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return None


class FeatureGeneratorStage(PipelineStage):
    """Generates one raw feature column from raw records.

    ``extract_fn(record) -> value`` runs host-side over the reader's records
    (the analogue of the reference's macro-captured extract lambdas); when the
    reader yields ready-made columns (CSV/Parquet fast path) the stage simply
    names the column.
    """

    input_arity = (0, 0)

    def __init__(
        self,
        name: str,
        output_type: Type[FeatureType],
        extract_fn: Optional[Callable[[Any], Any]] = None,
        is_response: bool = False,
        aggregator: Optional[str] = None,
        aggregate_window_ms: Optional[int] = None,
        event_field: Optional[str] = None,
        uid: Optional[str] = None,
    ):
        super().__init__(
            operation_name="FeatureGenerator", output_type=output_type, uid=uid
        )
        self.name = name
        self.extract_fn = extract_fn
        self.is_response = is_response
        # name of a registered monoid aggregator (aggregators module); None =
        # the per-type default (MonoidAggregatorDefaults.aggregatorOf parity)
        self.aggregator = aggregator
        self.aggregate_window_ms = aggregate_window_ms
        # declared event-record field this feature reads — provenance for
        # the event-time leakage lint (TM060): an ``extract_fn`` is opaque
        # to static analysis, so features over event readers declare their
        # source field here (features without one fall back to ``name``
        # when extract_fn is None, the r.get(name) default)
        self.event_field = event_field
        # where the USER declared this feature (``file:line``), for
        # clickable TM060 findings and line-precise suppressions
        self.source_location = _construction_site()
        self._output_feature = Feature(
            name=name,
            ftype=output_type,
            is_response=is_response,
            origin_stage=self,
            parents=[],
        )

    def make_output_name(self) -> str:
        return self.name

    def output_is_response(self) -> bool:
        return self.is_response

    def extract_column(self, records: Sequence[Any]) -> FeatureColumn:
        fn = self.extract_fn or (lambda r: r.get(self.name) if isinstance(r, dict) else getattr(r, self.name))
        return FeatureColumn.from_values(self.output_type, [fn(r) for r in records])
