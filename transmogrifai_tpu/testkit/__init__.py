"""Test infrastructure (reference testkit/ module, SURVEY §2.16, §4)."""
from .builder import TestFeatureBuilder
from .generators import (
    RandomBinary, RandomIntegral, RandomList, RandomMap, RandomPickList,
    RandomReal, RandomSet, RandomText, RandomVector,
)

__all__ = ["TestFeatureBuilder", "RandomReal", "RandomIntegral",
           "RandomBinary", "RandomText", "RandomPickList", "RandomList",
           "RandomSet", "RandomMap", "RandomVector"]
