"""TestFeatureBuilder — in-memory typed datasets + features for tests.

Reference: ``TestFeatureBuilder.apply(Seq[FeatureType]...)`` builds a
DataFrame plus typed features from in-memory values
(testkit/.../test/TestFeatureBuilder.scala:50-265, ``random`` :298).
"""
from __future__ import annotations

from typing import Any, List, Sequence, Tuple, Type

from ..features.builder import FeatureBuilder
from ..features.feature import Feature
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import FeatureType

__all__ = ["TestFeatureBuilder"]


class TestFeatureBuilder:
    @staticmethod
    def build(*named_columns: Tuple[str, Type[FeatureType], Sequence[Any]],
              response: str = "") -> Tuple[ColumnarDataset, List[Feature]]:
        """``build(("age", Real, [1, None, 3]), ...)`` ->
        (ColumnarDataset, [features])."""
        data = ColumnarDataset()
        feats: List[Feature] = []
        for name, ftype, values in named_columns:
            data.set(name, FeatureColumn.from_values(ftype, list(values)))
            builder = getattr(FeatureBuilder, ftype.type_name())(name)
            f = (builder.as_response() if name == response
                 else builder.as_predictor())
            feats.append(f)
        return data, feats

    @staticmethod
    def random(n: int, *named_generators, response: str = "",
               types=None) -> Tuple[ColumnarDataset, List[Feature]]:
        """``random(100, ("x", Real, RandomReal.normal()), ...)``."""
        cols = []
        for name, ftype, gen in named_generators:
            cols.append((name, ftype, gen.take(n)))
        return TestFeatureBuilder.build(*cols, response=response)
