"""Random typed-data generators for property-based tests.

Reference: ``testkit`` Random generators — infinite streams of typed feature
values with a ``ProbabilityOfEmpty`` knob
(testkit/src/main/scala/com/salesforce/op/testkit/Random*.scala), used by
model-selection property tests (SURVEY §4).
"""
from __future__ import annotations

import string
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

__all__ = [
    "RandomReal", "RandomIntegral", "RandomBinary", "RandomText",
    "RandomPickList", "RandomList", "RandomSet", "RandomMap", "RandomVector",
]


class _RandomBase:
    """Infinite generator with P(empty) (RandomData trait parity)."""

    def __init__(self, probability_of_empty: float = 0.0, seed: int = 42):
        self.probability_of_empty = probability_of_empty
        self.rng = np.random.default_rng(seed)

    def _one(self) -> Any:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        while True:
            if self.rng.random() < self.probability_of_empty:
                yield None
            else:
                yield self._one()

    def take(self, n: int) -> List[Any]:
        it = iter(self)
        return [next(it) for _ in range(n)]

    def with_probability_of_empty(self, p: float) -> "_RandomBase":
        self.probability_of_empty = p
        return self


class RandomReal(_RandomBase):
    def __init__(self, distribution: str = "normal", loc: float = 0.0,
                 scale: float = 1.0, **kw):
        super().__init__(**kw)
        self.distribution = distribution
        self.loc = loc
        self.scale = scale

    @staticmethod
    def normal(loc=0.0, scale=1.0, **kw):
        return RandomReal("normal", loc, scale, **kw)

    @staticmethod
    def uniform(lo=0.0, hi=1.0, **kw):
        return RandomReal("uniform", lo, hi, **kw)

    @staticmethod
    def poisson(lam=1.0, **kw):
        return RandomReal("poisson", lam, 0.0, **kw)

    def _one(self):
        if self.distribution == "normal":
            return float(self.rng.normal(self.loc, self.scale))
        if self.distribution == "uniform":
            return float(self.rng.uniform(self.loc, self.scale))
        if self.distribution == "poisson":
            return float(self.rng.poisson(self.loc))
        raise ValueError(self.distribution)


class RandomIntegral(_RandomBase):
    def __init__(self, lo: int = 0, hi: int = 100, **kw):
        super().__init__(**kw)
        self.lo, self.hi = lo, hi

    def _one(self):
        return int(self.rng.integers(self.lo, self.hi))


class RandomBinary(_RandomBase):
    def __init__(self, probability_of_true: float = 0.5, **kw):
        super().__init__(**kw)
        self.p = probability_of_true

    def _one(self):
        return bool(self.rng.random() < self.p)


class RandomText(_RandomBase):
    def __init__(self, min_len: int = 3, max_len: int = 12, **kw):
        super().__init__(**kw)
        self.min_len, self.max_len = min_len, max_len

    def _one(self):
        n = int(self.rng.integers(self.min_len, self.max_len + 1))
        letters = self.rng.choice(list(string.ascii_lowercase), n)
        return "".join(letters)


class RandomPickList(_RandomBase):
    def __init__(self, domain: Sequence[str], **kw):
        super().__init__(**kw)
        self.domain = list(domain)

    def _one(self):
        return str(self.rng.choice(self.domain))


class RandomList(_RandomBase):
    def __init__(self, element: _RandomBase, min_len: int = 0,
                 max_len: int = 5, **kw):
        super().__init__(**kw)
        self.element = element
        self.min_len, self.max_len = min_len, max_len

    def _one(self):
        n = int(self.rng.integers(self.min_len, self.max_len + 1))
        return [self.element._one() for _ in range(n)]


class RandomSet(RandomList):
    def _one(self):
        return set(super()._one())


class RandomMap(_RandomBase):
    def __init__(self, value: _RandomBase, keys: Sequence[str], **kw):
        super().__init__(**kw)
        self.value = value
        self.keys = list(keys)

    def _one(self):
        n = int(self.rng.integers(0, len(self.keys) + 1))
        ks = self.rng.choice(self.keys, n, replace=False)
        return {str(k): self.value._one() for k in ks}


class RandomVector(_RandomBase):
    def __init__(self, dim: int, **kw):
        super().__init__(**kw)
        self.dim = dim

    def _one(self):
        return self.rng.normal(size=self.dim).astype(np.float32)
