"""tuning/ — learned cost model + adaptive model selection + plan choices.

The repo records rich per-stage/per-candidate telemetry (PlanProfiler,
IngestProfiler, ``benchmarks/cost_history.json``); this subsystem SPENDS
it:

* :mod:`costmodel` — a fitted log-space ridge per stage kind over
  ``(rows, cols, dtype, backend)`` features, trained from the history
  every ``train()`` appends, with an analytic cold-start fallback.
* :mod:`halving` — successive-halving model selection over the
  selector's candidate grid (subsampled rows + scaled boosting rounds,
  deterministic promotion), driven through the selector's schedulable
  sweep queue.
* :mod:`budget` — the BenchBudgeter that replaces bench.py's hand-rolled
  estimate logic (measured history > cost model > stated assumption).
* :mod:`planner` — cost-predicted plan-level choices (stream vs in-core,
  chunk_rows / prefetch depth / spill threshold), surfaced via
  ``ExecutionPlan.advise`` and ``OpWorkflow.train(tuner=...)``.

Everything is opt-in: ``train(tuner=None)`` and selector
``strategy="full"`` keep the default paths byte-identical.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .budget import BenchBudgeter
from .costmodel import (CostModel, ServingCostLookup, StageObservation,
                        append_observations, default_history_path,
                        load_observations, observations_from_profiler,
                        record_train_observations)
from .halving import (HalvingConfig, Rung, halving_validate,
                      nested_subsample_order, rung_schedule)
from .planner import (MeshAdvice, PlanAdvice, advise_mesh, advise_plan,
                      default_host_budget_bytes)

__all__ = [
    "Tuner", "HalvingConfig", "Rung", "halving_validate", "rung_schedule",
    "nested_subsample_order", "CostModel", "ServingCostLookup",
    "StageObservation",
    "load_observations", "append_observations",
    "observations_from_profiler", "record_train_observations",
    "default_history_path", "BenchBudgeter", "PlanAdvice", "advise_plan",
    "MeshAdvice", "advise_mesh", "default_host_budget_bytes",
]


@dataclass
class Tuner:
    """The ``OpWorkflow.train(tuner=...)`` handle — one object that opts a
    train into the adaptive machinery.

    ``strategy`` is applied to every ModelSelector stage in the DAG for
    THIS train only (the stage's own setting is restored afterwards, the
    same contract as ``with_mesh``).  ``auto_plan=True`` additionally asks
    the cost planner to choose stream-vs-in-core and the chunk geometry
    when the reader can estimate its row count and the caller didn't pass
    ``chunk_rows`` explicitly.
    """

    strategy: str = "halving"          # "halving" | "full"
    halving: Optional[HalvingConfig] = None
    auto_plan: bool = False
    cost_model: Optional[CostModel] = None
    host_budget_bytes: Optional[int] = None

    def resolved_cost_model(self) -> CostModel:
        if self.cost_model is None:
            self.cost_model = CostModel.from_history()
        return self.cost_model
