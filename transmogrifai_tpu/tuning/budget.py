"""BenchBudgeter — measured-history + cost-model budget decisions.

Replaces bench.py's hand-rolled estimate plumbing ("estimated 2200s
exceeds remaining budget", ROADMAP item 2): estimates come from, in
order, (1) measured history of the SAME config and workload signature
recorded by the previous bench run, (2) the learned cost model's
whole-pipeline prediction at the config's (rows, cols) shape when the
signature encodes one, (3) the caller's stated assumption — and the
source is always reported next to the number.  All history writes are
atomic (tmp + ``os.replace``).
"""
from __future__ import annotations

import re
import time
from typing import Dict, Optional, Tuple

from .costmodel import CostModel

__all__ = ["BenchBudgeter", "estimate_from_history", "record_measurement"]

_SIG_SHAPE = re.compile(r"^(\d+)x(\d+)")


def _load_history(path: Optional[str]) -> dict:
    from ..utils.jsonio import read_json_tolerant

    if not path:
        return {}
    hist = read_json_tolerant(path, {})
    return hist if isinstance(hist, dict) else {}


def estimate_from_history(path: Optional[str], name: str,
                          fallback_s: float,
                          sig: str = "") -> Tuple[float, str]:
    """(estimate_s, source) — measured history of the same config AND the
    same workload signature if present, else the stated fallback.  (The
    bench.py `_estimate` contract, relocated verbatim.)"""
    h = _load_history(path).get(name)
    if isinstance(h, dict) and "measured_s" in h and h.get("sig", "") == sig:
        return float(h["measured_s"]), "measured_history"
    return fallback_s, "assumed"


def record_measurement(path: Optional[str], name: str, measured_s: float,
                       cold: bool, sig: str = "") -> None:
    """Self-updating measured-cost history (the next run's estimates),
    written atomically and preserving every other key (including the
    cost model's ``stage_observations``)."""
    from ..utils.jsonio import write_json_atomic

    if not path:
        return
    hist = _load_history(path)
    hist[name] = {"measured_s": round(measured_s, 1), "cold": cold,
                  "sig": sig, "recorded_unix": int(time.time())}
    try:
        write_json_atomic(path, hist, indent=2, sort_keys=True)
    except OSError:
        pass


class BenchBudgeter:
    """Wall-clock budget arbiter for a bench suite.

    One instance per run: it owns the clock, the headline reserve, the
    estimate sources and the skip bookkeeping, so drivers stop
    re-implementing "does this config still fit" by hand.
    """

    def __init__(self, history_path: Optional[str], budget_s: float,
                 clock=time.perf_counter,
                 cost_model: Optional[CostModel] = None,
                 t0: Optional[float] = None):
        self.history_path = history_path
        self.budget_s = float(budget_s)
        self._clock = clock
        self._t0 = clock() if t0 is None else t0
        self.reserve_s = 0.0
        #: lazily fitted from the shared history when first needed
        self._cost_model = cost_model
        self.decisions: Dict[str, dict] = {}

    # -- clock ---------------------------------------------------------------

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def remaining(self) -> float:
        return max(0.0, self.budget_s - self.reserve_s - self.elapsed())

    def set_reserve(self, seconds: float) -> None:
        """Reserve budget for an unconditional config that runs last."""
        self.reserve_s = max(0.0, float(seconds))

    # -- estimates -----------------------------------------------------------

    def cost_model(self) -> CostModel:
        if self._cost_model is None:
            self._cost_model = CostModel.from_history(self.history_path)
        return self._cost_model

    def estimate(self, name: str, fallback_s: float,
                 sig: str = "") -> Tuple[float, str]:
        """(estimate_s, source): measured_history > cost_model > assumed.

        The cost-model tier engages only when the signature encodes a
        ``<rows>x<cols>`` shape and the model has fitted stage kinds; its
        whole-pipeline sum is a floor (it knows per-stage walls, not grid
        width), so it is only trusted when it EXCEEDS the fallback —
        predicting "bigger than you assumed" is the useful direction for
        a budgeter, "smaller" may just be missing stages.
        """
        est, src = estimate_from_history(self.history_path, name,
                                         fallback_s, sig)
        if src == "measured_history":
            return est, src
        m = _SIG_SHAPE.match(sig or "")
        if m:
            rows, cols = int(m.group(1)), int(m.group(2))
            pred = self.cost_model().predict_total(rows, cols)
            if pred > fallback_s:
                return pred, "cost_model"
        return fallback_s, "assumed"

    def record(self, name: str, measured_s: float, cold: bool,
               sig: str = "") -> None:
        record_measurement(self.history_path, name, measured_s, cold, sig)

    # -- decisions -----------------------------------------------------------

    def should_skip(self, name: str, fallback_s: float,
                    sig: str = "") -> Optional[str]:
        """Skip reason when the estimate no longer fits the remaining
        budget (after the reserve), else None.  Every decision — run or
        skip — is kept in ``decisions`` for the emitted JSON."""
        est, src = self.estimate(name, fallback_s, sig)
        remaining = self.remaining()
        decision = {"estimate_s": round(est, 1), "source": src,
                    "remaining_s": round(remaining, 1)}
        if est > remaining:
            reason = (f"estimated {est:.0f}s ({src}) exceeds remaining "
                      f"budget ({remaining:.0f}s of {self.budget_s:.0f}s"
                      + (f" after reserving {self.reserve_s:.0f}s for the "
                         f"unconditional 1M default-grid headline)"
                         if self.reserve_s else ")"))
            decision["skipped"] = reason
            self.decisions[name] = decision
            return reason
        self.decisions[name] = decision
        return None

    def to_json(self) -> dict:
        return {"budgetSecs": self.budget_s,
                "reserveSecs": round(self.reserve_s, 1),
                "elapsedSecs": round(self.elapsed(), 1),
                "decisions": dict(self.decisions)}
