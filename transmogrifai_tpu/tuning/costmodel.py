"""Learned per-stage cost model — fitted log-space ridge over run telemetry.

In the spirit of "A Learned Performance Model for TPUs" and "TpuGraphs"
(PAPERS.md): a small fitted model over cheap static features —
``(rows, cols, dtype, backend, stage kind)`` — predicts per-stage wall
well enough to *decide* things (successive-halving promotion budgets,
bench budgeting, stream-vs-in-core plan choices) without ever running the
stage.  The features come from the telemetry the repo already records:
every ``OpWorkflow.train()`` appends its ``PlanProfiler`` stage profiles
(which since this PR carry rows/cols/dtype/backend/stage-kind) to
``benchmarks/cost_history.json`` — atomically, tmp + ``os.replace``.

Model shape: one ridge regression per ``(stage_kind, backend)`` bucket in
log space — ``log(wall) ~ w · [1, log1p(rows), log1p(cols),
log1p(rows)·log1p(cols)]`` — with a per-``stage_kind`` bucket as the
first fallback and an analytic elements-per-second law as the cold-start
fallback, so predictions are always available and only *sharpen* as
history accumulates.
"""
from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "StageObservation", "CostModel", "ServingCostLookup",
    "load_observations",
    "append_observations", "observations_from_profiler",
    "record_train_observations", "default_history_path",
    "HISTORY_OBSERVATION_CAP",
]

#: FIFO cap on persisted stage observations — bounds the history file and
#: keeps the fit weighted toward recent code (old implementations of a
#: stage kind age out instead of anchoring the regression forever)
HISTORY_OBSERVATION_CAP = 4000

#: key under which stage observations live inside cost_history.json —
#: sibling to bench.py's per-config entries (which key by config name and
#: carry "measured_s"), so both consumers share one atomic file
HISTORY_STAGES_KEY = "stage_observations"

#: analytic cold-start law: host-side columnar transform throughput in
#: matrix elements/second (conservative; measured host featurizers run
#: 1e7-1e9 elem/s depending on dtype).  Only used for stage kinds with no
#: recorded history at all.
DEFAULT_ELEMS_PER_S = 5e7

#: no stage dispatch is free — floor on any prediction (seconds)
PREDICTION_FLOOR_S = 1e-4


@dataclass
class StageObservation:
    """One observed stage execution — the cost model's training row."""

    stage_kind: str          # "OpClass:kind", e.g. "RealVectorizer:transform"
    rows: int
    cols: int                # total scalar width of the stage's inputs
    dtype: str               # primary input dtype ("float32", "object", ...)
    backend: str             # jax backend serving the run ("cpu", "tpu", ...)
    wall_s: float
    t: int = 0               # unix seconds (0 = unknown)
    #: devices the stage ran on (1 = single chip; mesh fits record their
    #: mesh size so the model can learn measured multi-chip scaling)
    n_devices: int = 1
    mesh_shape: str = ""     # e.g. "data=4,grid=2" ("" = no mesh)
    #: compiled-program features from a traced run (obs/hlo.py via
    #: StageProfile.hlo): {"programs", "flops", "bytes_accessed",
    #: "ops": {opcode: count}} — the "predict from the program, not just
    #: (rows, cols)" feature source for the cost model v2 (ROADMAP item
    #: 4, per "A Learned Performance Model for TPUs"/"TpuGraphs").
    #: Empty for untraced runs; the current ridge ignores it.
    hlo: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        out = {"stageKind": self.stage_kind, "rows": self.rows,
               "cols": self.cols, "dtype": self.dtype,
               "backend": self.backend, "wallSecs": round(self.wall_s, 6),
               "t": self.t}
        # backward-compatible JSON: single-chip records look exactly like
        # the pre-mesh history (old readers never see the new keys)
        if self.n_devices != 1:
            out["nDevices"] = self.n_devices
        if self.mesh_shape:
            out["meshShape"] = self.mesh_shape
        if self.hlo:
            out["hlo"] = dict(self.hlo)
        return out

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "StageObservation":
        return StageObservation(
            stage_kind=str(d.get("stageKind", "")),
            rows=int(d.get("rows", 0)), cols=int(d.get("cols", 0)),
            dtype=str(d.get("dtype", "")),
            backend=str(d.get("backend", "")),
            wall_s=float(d.get("wallSecs", 0.0)), t=int(d.get("t", 0)),
            n_devices=int(d.get("nDevices", 1)),
            mesh_shape=str(d.get("meshShape", "")),
            hlo=dict(d.get("hlo", {}) or {}))


def _features(rows: int, cols: int, n_devices: int = 1) -> np.ndarray:
    lr = math.log1p(max(rows, 0))
    lc = math.log1p(max(cols, 0))
    # log2(n_devices): perfect data-parallel scaling fits a -log(2)
    # coefficient; measured sub-linear scaling (collective overhead) fits
    # whatever the telemetry actually shows.  Old histories (all
    # n_devices=1) contribute 0 here, so the feature is backward-inert.
    ld = math.log2(max(n_devices, 1))
    return np.array([1.0, lr, lc, lr * lc, ld], dtype=np.float64)


class CostModel:
    """Per-stage-kind fitted wall-clock predictor with analytic fallback.

    ``fit`` is a closed-form ridge solve per bucket (4 coefficients), so
    training on thousands of observations is microseconds — cheap enough
    to refit from history at the top of every bench/tuning run.
    """

    def __init__(self, ridge: float = 1e-3, min_obs: int = 1,
                 elems_per_s: float = DEFAULT_ELEMS_PER_S):
        self.ridge = ridge
        self.min_obs = min_obs
        self.elems_per_s = elems_per_s
        #: fitted coefficients keyed by (stage_kind, backend), plus a
        #: backend-agnostic fallback bucket keyed by (stage_kind, None)
        self._coef: Dict[Tuple[str, Optional[str]], np.ndarray] = {}
        self._n_obs: Dict[Tuple[str, Optional[str]], int] = {}

    # -- training ------------------------------------------------------------

    def fit(self, observations: Sequence[StageObservation]) -> "CostModel":
        buckets: Dict[Tuple[str, Optional[str]],
                      Dict[Tuple[int, int], float]] = {}
        for o in observations:
            if o.wall_s <= 0 or not o.stage_kind:
                continue
            # duplicates at the same (kind, backend, shape) collapse to
            # their MINIMUM wall: a stage's first execution in a process
            # pays XLA compile, which inflates wall upward only — the
            # scheduler wants the steady-state cost, and min over repeats
            # is its unbiased-from-above estimate
            for key in ((o.stage_kind, o.backend or None),
                        (o.stage_kind, None)):
                pts = buckets.setdefault(key, {})
                loc = (o.rows, o.cols, max(o.n_devices, 1))
                pts[loc] = min(pts.get(loc, float("inf")), o.wall_s)
        self._coef.clear()
        self._n_obs.clear()
        for key, pts in buckets.items():
            if len(pts) < self.min_obs:
                continue
            A = np.stack([_features(r, c, nd) for r, c, nd in pts])
            b = np.log(np.array(list(pts.values())) + 1e-6)
            G = A.T @ A + self.ridge * np.eye(A.shape[1])
            self._coef[key] = np.linalg.solve(G, A.T @ b)
            self._n_obs[key] = len(pts)
        return self

    @property
    def fitted_kinds(self) -> List[str]:
        return sorted({k for k, be in self._coef if be is None})

    # -- prediction ----------------------------------------------------------

    def analytic(self, rows: int, cols: int) -> float:
        """Cold-start fallback: an elements/throughput law."""
        elems = max(rows, 1) * max(cols, 1)
        return max(elems / self.elems_per_s, PREDICTION_FLOOR_S)

    def predict(self, stage_kind: str, rows: int, cols: int,
                dtype: str = "float32",
                backend: Optional[str] = None,
                n_devices: int = 1) -> float:
        """Predicted wall seconds; never raises, never returns <= 0."""
        for key in ((stage_kind, backend or None), (stage_kind, None)):
            w = self._coef.get(key)
            if w is not None:
                pred = float(np.exp(
                    w @ _features(rows, cols, n_devices))) - 1e-6
                return max(pred, PREDICTION_FLOOR_S)
        return self.analytic(rows, cols)

    def source(self, stage_kind: str,
               backend: Optional[str] = None) -> str:
        """Which estimator answers for this stage kind: 'fitted' (the
        backend-specific or kind-level ridge) or 'analytic'."""
        if ((stage_kind, backend or None) in self._coef
                or (stage_kind, None) in self._coef):
            return "fitted"
        return "analytic"

    def predict_total(self, rows: int, cols: int,
                      backend: Optional[str] = None,
                      n_devices: int = 1) -> float:
        """Sum of per-stage-kind predictions over every fitted kind — a
        crude whole-pipeline estimate for budgeting when no same-config
        measured history exists.  0.0 when the model is fully cold (the
        caller should fall back to its stated assumption)."""
        kinds = self.fitted_kinds
        if not kinds:
            return 0.0
        return float(sum(self.predict(k, rows, cols, backend=backend,
                                      n_devices=n_devices)
                         for k in kinds))

    # -- evaluation ----------------------------------------------------------

    def within_factor(self, observations: Sequence[StageObservation],
                      factor: float = 2.0,
                      noise_floor_s: float = 0.005) -> Tuple[float, int]:
        """Fraction of held-out observations whose prediction lands within
        ``factor``x of the observed wall (either direction).  Stages under
        ``noise_floor_s`` also count as hits when the absolute error is
        under the floor — sub-5ms stage walls are scheduler noise, not
        model error.  Returns (fraction, n_evaluated)."""
        hits, n = 0, 0
        for o in observations:
            if o.wall_s <= 0 or not o.stage_kind:
                continue
            pred = self.predict(o.stage_kind, o.rows, o.cols,
                                dtype=o.dtype, backend=o.backend,
                                n_devices=o.n_devices)
            n += 1
            ratio = max(pred, o.wall_s) / max(min(pred, o.wall_s), 1e-9)
            if ratio <= factor or abs(pred - o.wall_s) <= noise_floor_s:
                hits += 1
        return (hits / n if n else 0.0), n

    # -- history -------------------------------------------------------------

    @classmethod
    def from_history(cls, path: Optional[str] = None,
                     **kwargs) -> "CostModel":
        path = path or default_history_path()
        obs = load_observations(path) if path else []
        return cls(**kwargs).fit(obs)


# ---------------------------------------------------------------------------
# History file plumbing (shared with bench.py's per-config entries)
# ---------------------------------------------------------------------------

def default_history_path() -> Optional[str]:
    """Where stage observations accumulate.  ``TMOG_COST_HISTORY`` wins
    (empty or "0" disables recording entirely); otherwise the repo's
    ``benchmarks/cost_history.json`` when that directory exists next to
    the package (site-installed copies without a benchmarks/ dir simply
    don't record)."""
    env = os.environ.get("TMOG_COST_HISTORY")
    if env is not None:
        return None if env in ("", "0") else env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    bench_dir = os.path.join(root, "benchmarks")
    if os.path.isdir(bench_dir):
        return os.path.join(bench_dir, "cost_history.json")
    return None


def load_observations(path: Optional[str]) -> List[StageObservation]:
    from ..utils.jsonio import read_json_tolerant

    if not path:
        return []
    hist = read_json_tolerant(path, {})
    if not isinstance(hist, dict):
        return []
    raw = hist.get(HISTORY_STAGES_KEY, [])
    out = []
    for d in raw if isinstance(raw, list) else []:
        try:
            out.append(StageObservation.from_json(d))
        except (TypeError, ValueError):
            continue
    return out


def append_observations(path: Optional[str],
                        observations: Sequence[StageObservation],
                        cap: int = HISTORY_OBSERVATION_CAP) -> bool:
    """Append stage observations to the shared cost-history file,
    FIFO-capped, atomically (tmp + ``os.replace``).  Preserves every other
    key (bench.py's per-config measured entries).  Returns True when a
    write happened."""
    from ..utils.jsonio import read_json_tolerant, write_json_atomic

    if not path or not observations:
        return False
    hist = read_json_tolerant(path, {})
    if not isinstance(hist, dict):
        hist = {}
    raw = hist.get(HISTORY_STAGES_KEY, [])
    if not isinstance(raw, list):
        raw = []
    raw.extend(o.to_json() for o in observations)
    hist[HISTORY_STAGES_KEY] = raw[-cap:]
    try:
        write_json_atomic(path, hist, indent=2, sort_keys=True)
    except OSError:
        return False
    return True


def observations_from_profiler(profiler,
                               backend: str = "") -> List[StageObservation]:
    """StageObservations out of a PlanProfiler's stage records (the
    rows/cols/dtype/backend/stage-kind feature fields landed on
    ``StageProfile`` in this PR)."""
    now = int(time.time())
    out: List[StageObservation] = []
    for sp in getattr(profiler, "stages", []):
        if sp.wall_s <= 0:
            continue
        out.append(StageObservation(
            stage_kind=sp.stage_kind or f"{sp.op}:{sp.kind}",
            rows=sp.rows, cols=max(getattr(sp, "cols", 0), 1),
            dtype=getattr(sp, "dtype", "") or "",
            backend=getattr(sp, "backend", "") or backend,
            wall_s=sp.wall_s, t=now,
            n_devices=max(int(getattr(sp, "n_devices", 1) or 1), 1),
            mesh_shape=getattr(sp, "mesh_shape", "") or "",
            hlo=dict(getattr(sp, "hlo", {}) or {})))
    return out


class ServingCostLookup:
    """Per-bucket serving batch-cost estimates for continuous batch
    formation (serving/batcher.py).

    Three tiers, sharpest first: an ONLINE per-bucket EWMA of measured
    batch walls (the batcher feeds every executed batch back in), the
    fitted :class:`CostModel` under the ``Serving:batch`` stage kind, and
    the analytic per-row law — so the batcher's greedy bucket choice and
    late-admission window always have a number, and the number converges
    on the replica's actual measured behavior within a few dozen batches.
    Thread-safe: read by the dispatch thread, written after every batch.
    """

    STAGE_KIND = "Serving:batch"

    def __init__(self, cost_model: Optional["CostModel"] = None,
                 cols: int = 0, alpha: float = 0.3):
        self.cost_model = cost_model
        self.cols = int(cols)
        self.alpha = float(alpha)
        self._ewma: Dict[int, float] = {}
        self._counts: Dict[int, int] = {}
        import threading

        self._lock = threading.Lock()

    @classmethod
    def from_history(cls, cols: int = 0,
                     path: Optional[str] = None) -> "ServingCostLookup":
        return cls(cost_model=CostModel.from_history(path), cols=cols)

    def observe(self, bucket: int, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            prev = self._ewma.get(bucket)
            self._ewma[bucket] = seconds if prev is None else (
                self.alpha * seconds + (1.0 - self.alpha) * prev)
            self._counts[bucket] = self._counts.get(bucket, 0) + 1

    @staticmethod
    def _analytic(bucket: int) -> float:
        # dispatch floor + per-row host/transform cost
        return PREDICTION_FLOOR_S + bucket * 2e-5

    def predict_s(self, bucket: int) -> float:
        """Predicted wall seconds for one executed batch at ``bucket``.

        An unmeasured bucket must not look spuriously cheap next to a
        measured one (the raw analytic law is optimistic): once ANY
        bucket has an EWMA, unmeasured buckets extrapolate from the
        nearest measured bucket (log-space nearest), scaled by the
        analytic shape — measured LEVEL, analytic SLOPE."""
        with self._lock:
            measured = self._ewma.get(bucket)
            ewma = dict(self._ewma) if measured is None else None
        if measured is not None:
            return max(measured, PREDICTION_FLOOR_S)
        if ewma:
            near = min(ewma, key=lambda b: abs(
                math.log(max(b, 1) / max(bucket, 1))))
            scaled = ewma[near] * (self._analytic(bucket)
                                   / self._analytic(near))
            return max(scaled, PREDICTION_FLOOR_S)
        if self.cost_model is not None:
            return self.cost_model.predict(self.STAGE_KIND, rows=bucket,
                                           cols=max(self.cols, 1))
        return self._analytic(bucket)

    def source(self, bucket: int) -> str:
        with self._lock:
            if bucket in self._ewma:
                return "measured"
        if self.cost_model is not None and self.cost_model.source(
                self.STAGE_KIND) == "fitted":
            return "fitted"
        return "analytic"

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ewmaMs": {str(b): round(v * 1000.0, 4)
                           for b, v in sorted(self._ewma.items())},
                "observedBatches": dict(
                    sorted((str(k), v)
                           for k, v in self._counts.items())),
            }


def record_train_observations(profiler,
                              path: Optional[str] = None) -> bool:
    """Called by ``OpWorkflow.train()`` after every fit: persist the run's
    stage profiles into the cost history.  Never raises — telemetry must
    not break a train.  Pod trains append through the COORDINATOR only
    (every process would otherwise race the same history file with
    identical observations — TM047's durable-write convention)."""
    try:
        from ..distributed.runtime import current_pod

        pod = current_pod()
        if pod.active and not pod.is_coordinator():
            return False
        path = path if path is not None else default_history_path()
        if not path or profiler is None:
            return False
        from ..utils.profiling import backend_name

        obs = observations_from_profiler(profiler, backend=backend_name())
        return append_observations(path, obs)
    except Exception:
        return False
