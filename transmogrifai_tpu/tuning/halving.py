"""Successive-halving model selection over the selector's candidate grid.

The classic successive-halving/hyperband move (Li et al., JMLR 18(185);
cf. the scheduling framing of "A Learned Performance Model for TPUs" in
PAPERS.md): fit EVERY candidate cheaply — on a stratified row subsample
and proportionally reduced boosting rounds — keep the top ``1/eta``
fraction, and repeat with ``eta``x the resource until the survivors fit
on the full data.  The full-data final rung is authoritative, so the
winner's reported metric is always a full-fidelity number; early rungs
only decide *who gets to spend* full-fidelity compute.

Built on the selector's schedulable sweep queue (``selector.validators.
SweepWorkQueue``): each rung is one scheduled ``validator.validate`` call
over the surviving candidates, so the rung inherits the full sweep's CV
folds, failure isolation, ``max_wait`` budgeting and device batching
semantics unchanged.

Everything is deterministic: the rung schedule is a pure function of
``(n_rows, n_candidates, eta, min_rows)``, the nested subsample order is
seeded and stratified, and promotion ties break toward the lower
candidate index — two runs on the same data produce byte-identical rung
schedules and winners.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["HalvingConfig", "Rung", "rung_schedule",
           "nested_subsample_order", "halving_validate"]


@dataclass
class HalvingConfig:
    """Knobs for the successive-halving scheduler (all deterministic)."""

    #: promotion factor: each rung keeps ceil(k/eta) candidates and grows
    #: the row budget by ~eta
    eta: int = 3
    #: smallest rung row budget — below this, subsample metrics are too
    #: noisy to rank candidates on
    min_rows: int = 2048
    #: subsample-order seed (stratified nested prefixes)
    seed: int = 7
    #: scale per-candidate iteration params (max_iter/num_round) with the
    #: rung's row fraction, flooring at ``min_round_frac``
    scale_rounds: bool = True
    min_round_frac: float = 0.1
    #: iteration-count param names eligible for rung scaling
    round_keys: Tuple[str, ...] = ("max_iter", "num_round")
    #: below this many candidates halving cannot save anything — fall back
    #: to the full sweep
    min_candidates: int = 3

    def to_json(self) -> Dict[str, Any]:
        return {"eta": self.eta, "minRows": self.min_rows,
                "seed": self.seed, "scaleRounds": self.scale_rounds,
                "minRoundFrac": self.min_round_frac,
                "minCandidates": self.min_candidates}


@dataclass
class Rung:
    """One rung of the schedule (static part computed up front)."""

    index: int
    rows: int
    fraction: float          # rows / n_rows
    survivors_in: int        # candidates entering this rung
    survivors_out: int       # candidates promoted out of this rung
    # filled in during execution:
    wall_s: float = 0.0
    candidate_seconds: float = 0.0
    promoted: List[int] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"rung": self.index, "rows": self.rows,
                "fraction": round(self.fraction, 6),
                "survivorsIn": self.survivors_in,
                "survivorsOut": self.survivors_out,
                "wallSecs": round(self.wall_s, 4),
                "candidateSeconds": round(self.candidate_seconds, 4),
                "promoted": list(self.promoted)}


def rung_schedule(n_rows: int, n_candidates: int,
                  config: Optional[HalvingConfig] = None) -> List[Rung]:
    """The deterministic rung ladder for (n_rows, n_candidates).

    ``s`` reduction steps where ``s = min(steps the rows allow before
    hitting min_rows, steps the candidate count needs to reach ~1
    survivor)``; rung ``i`` runs ``ceil(n / eta^(s-i))`` rows with
    ``ceil(k / eta^i)`` candidates; the final rung is always the full
    ``n`` rows.  A schedule of length <= 1 means "just run the full
    sweep" (the caller falls back)."""
    cfg = config or HalvingConfig()
    n, k, eta = int(n_rows), int(n_candidates), max(int(cfg.eta), 2)
    if k < max(cfg.min_candidates, 2) or n <= 0:
        return []
    s_rows = int(math.floor(math.log(max(n / max(cfg.min_rows, 1), 1.0),
                                     eta)))
    s_cands = int(math.ceil(math.log(k, eta)))
    s = max(0, min(s_rows, s_cands))
    if s == 0:
        return []
    rungs: List[Rung] = []
    alive = k
    for i in range(s + 1):
        rows = n if i == s else int(math.ceil(n / eta ** (s - i)))
        out = 1 if i == s else max(1, int(math.ceil(alive / eta)))
        rungs.append(Rung(index=i, rows=rows, fraction=rows / n,
                          survivors_in=alive, survivors_out=out))
        alive = out
    return rungs


def nested_subsample_order(y: np.ndarray, seed: int,
                           stratify: bool = True) -> np.ndarray:
    """A permutation of row indices whose every prefix is (approximately)
    class-stratified — so rung r+1's rows are a superset of rung r's and
    each rung sees the full label ratio.  Deterministic for (y, seed)."""
    n = len(y)
    rng = np.random.default_rng(seed)
    if not stratify:
        return rng.permutation(n)
    keys = np.empty(n, dtype=np.float64)
    finite = np.isfinite(y)
    classes = np.unique(y[finite]) if finite.any() else []
    seen = np.zeros(n, dtype=bool)
    for cls in classes:
        idx = np.where(y == cls)[0]
        perm = rng.permutation(idx)
        # fractional within-class rank: sorting by it interleaves classes
        # proportionally, so any prefix holds ~the global label ratio
        keys[perm] = (np.arange(len(idx)) + rng.random()) / max(len(idx), 1)
        seen[idx] = True
    rest = np.where(~seen)[0]
    if len(rest):
        perm = rng.permutation(rest)
        keys[perm] = (np.arange(len(rest)) + rng.random()) / max(len(rest), 1)
    return np.argsort(keys, kind="stable")


def _scaled_params(params: Dict[str, Any], fraction: float,
                   cfg: HalvingConfig) -> Dict[str, Any]:
    """Rung-scaled fit params: iteration counts shrink with the row
    fraction (floored) so early rungs are cheap in BOTH rows and rounds."""
    if not cfg.scale_rounds or fraction >= 1.0:
        return params
    f = max(fraction, cfg.min_round_frac)
    out = dict(params)
    for key in cfg.round_keys:
        v = out.get(key)
        if isinstance(v, (int, float)) and v > 1:
            out[key] = max(int(math.ceil(v * f)), 2)
    return out


def halving_validate(
    validator,
    candidates: Sequence[Tuple],
    X: np.ndarray,
    y: np.ndarray,
    base_weights: np.ndarray,
    eval_fn,
    metric_name: str,
    larger_better: bool = True,
    config: Optional[HalvingConfig] = None,
    stratify: bool = True,
    checkpoint=None,
    regroup=None,
    elastic=None,
) -> Tuple[int, List, Dict[str, Any]]:
    """Run the candidate sweep under successive halving.

    Returns ``(best_index, results, schedule_json)`` where ``results`` has
    one ValidationResult per ORIGINAL candidate (eliminated candidates
    keep their last subsample metric, annotated with an ``error`` note so
    downstream selection and summaries never mistake a subsample score
    for a full-fidelity one) and ``best_index`` indexes ``candidates``.

    Falls back to one full ``validator.validate`` sweep (recorded in the
    schedule json) whenever the shape doesn't admit a useful ladder.

    ``checkpoint`` (workflow.checkpoint.SweepCheckpointManager) persists
    the rung state after every rung and a per-rung unit cursor inside it,
    so a killed sweep resumes at its rung (everything here is already
    deterministic in the inputs — the ladder, the nested subsample order
    and the promotions replay identically).  ``regroup(alive_indices,
    fit_params_list)`` lets the caller rebuild same-family batched groups
    over a rung's survivors (the sharded sweep packs each rung's
    candidates onto the mesh's grid axis); returning None keeps the
    per-candidate path.  Because the regroup runs fresh at EVERY rung —
    including the first rung of a resumed sweep — a checkpoint written on
    one mesh shape resumes with its surviving candidates re-batched onto
    whatever mesh the resuming process has.

    ``elastic`` (parallel.elastic.ElasticContext) rides into every rung's
    ``validator.validate`` call: device-loss retry/quarantine and the
    straggler watchdog apply per rung unit.

    Rung elimination is an ON-DEVICE reduction on the async path
    (``checkpoint is None`` and no ``TMOG_SYNC_SWEEP=1``): each rung's
    sweep returns DEFERRED device metrics (``validate(..., defer=True)``),
    promotion is a device finite-mean + top-k whose only host round-trip
    is ``survivors_out`` int32 indices, and every rung's full metrics
    materialize in ONE end-of-ladder fetch.  Checkpointed sweeps keep the
    per-rung materialization (the rung cursor needs durable host metrics)
    — that sync is the durability cost, exactly as in ``run_all``.
    """
    cfg = config or HalvingConfig()
    n, k = len(y), len(candidates)
    schedule = rung_schedule(n, k, cfg)
    sched_json: Dict[str, Any] = {"strategy": "halving",
                                  "config": cfg.to_json(),
                                  "nRows": n, "nCandidates": k}
    if not schedule:
        t0 = time.perf_counter()
        best, results = validator.validate(
            candidates, X, y, base_weights, eval_fn, metric_name,
            larger_better=larger_better, checkpoint=checkpoint,
            elastic=elastic)
        sched_json.update({
            "fallback": "full sweep (schedule admits no reduction rung)",
            "rungs": [], "candidateSeconds":
                round(time.perf_counter() - t0, 4)})
        return best, results, sched_json

    from ..selector.async_dispatch import sync_sweep_forced

    order = nested_subsample_order(y, cfg.seed, stratify=stratify)
    worst = float("-inf") if larger_better else float("inf")
    # the deferred-rung path needs a queue-capable validator and no
    # per-rung durability cursor; the kill-switch restores host promotion
    use_defer = (checkpoint is None
                 and getattr(validator, "supports_defer", False)
                 and not sync_sweep_forced())
    #: rung outputs applied to ``last_result`` IN ORDER after the ladder
    #: (deferred rungs resolve in one combined end-of-ladder fetch; a
    #: rung that fell back to host promotion stores results eagerly) —
    #: (alive_snapshot, queue, all_vals, errors, results_or_None)
    deferred_rungs: List[List[Any]] = []
    alive = list(range(k))
    last_result: Dict[int, Any] = {}
    #: original index -> (rung index, rung rows) at elimination
    eliminated: Dict[int, Tuple[int, int]] = {}
    total_cand_s = 0.0
    rungs_done: List[Dict[str, Any]] = []
    start_rung = 0
    if checkpoint is not None:
        st = checkpoint.rung_state()
        if st is not None:
            from ..selector.validators import ValidationResult

            start_rung = int(st.get("nextRung", 0))
            alive = [int(i) for i in st.get("alive", alive)]
            last_result = {int(i): ValidationResult.from_json(r)
                           for i, r in st.get("last", {}).items()}
            eliminated = {int(i): (int(v[0]), int(v[1]))
                          for i, v in st.get("eliminated", {}).items()}
            rungs_done = list(st.get("rungJson", []))

    for rung in schedule[start_rung:]:
        full = rung.rows >= n
        if full:
            Xs, ys, ws = X, y, base_weights
        else:
            idx = np.sort(order[:rung.rows])
            Xs, ys, ws = X[idx], y[idx], base_weights[idx]
        rung_cands = []
        fit_params_list = []
        for i in alive:
            name, params, fitter, *_ = candidates[i]
            fit_params = params if full else _scaled_params(
                params, rung.fraction, cfg)
            fit_params_list.append(fit_params)
            rung_cands.append((name, fit_params, fitter))
        if regroup is not None:
            regrouped = regroup(list(alive), fit_params_list)
            if regrouped is not None:
                rung_cands = regrouped
        rung_ckpt = (checkpoint.scoped(f"rung{rung.index}")
                     if checkpoint is not None else None)
        t0 = time.perf_counter()
        from ..obs.trace import span as _obs_span

        with _obs_span(f"sweep.rung[{rung.index}]", cat="sweep",
                       rows=rung.rows, candidates=len(rung_cands),
                       full=full, deferred=use_defer):
            if use_defer:
                queue, all_vals, errs = validator.validate(
                    rung_cands, Xs, ys, ws, eval_fn, metric_name,
                    larger_better=larger_better, checkpoint=rung_ckpt,
                    elastic=elastic, defer=True)
            else:
                _, results = validator.validate(
                    rung_cands, Xs, ys, ws, eval_fn, metric_name,
                    larger_better=larger_better, checkpoint=rung_ckpt,
                    elastic=elastic)
        rung.wall_s = time.perf_counter() - t0
        rung.candidate_seconds = rung.wall_s
        total_cand_s += rung.wall_s
        if use_defer:
            entry = [list(alive), queue, all_vals, errs, None]
            deferred_rungs.append(entry)
            if all(e is not None for e in errs):
                # every unit errored at DISPATCH time: collect raises the
                # same "every candidate errored" the sync rung would
                queue.collect(all_vals, errs, metric_name, larger_better)
            if full:
                rung.promoted = list(alive)
                rungs_done.append(rung.to_json())
                break
            from ..selector.async_dispatch import (device_promote,
                                                   device_rung_scores)

            try:
                scores_dev = device_rung_scores(all_vals, errs,
                                                larger_better)
                pos = device_promote(scores_dev, rung.survivors_out,
                                     larger_better)
                promoted = sorted(alive[p] for p in pos)
            except Exception:  # async device fault surfacing in the
                # reduction: materialize this rung now (NaN fallbacks
                # isolate the faulted values) and promote on host
                _, results = queue.collect(all_vals, errs, metric_name,
                                           larger_better,
                                           overlap_tail=True)
                entry[4] = results
                scores = {i: (r.metric_value if r.error is None
                              else worst)
                          for i, r in zip(alive, results)}
                sign = -1.0 if larger_better else 1.0
                ranked = sorted(alive,
                                key=lambda i: (sign * scores[i], i))
                promoted = sorted(ranked[:rung.survivors_out])
            rung.promoted = promoted
            for i in alive:
                if i not in promoted:
                    eliminated[i] = (rung.index, rung.rows)
            alive = promoted
            rungs_done.append(rung.to_json())
            continue
        scores: Dict[int, float] = {}
        for i, r in zip(alive, results):
            # report under the candidate's ORIGINAL params (rung scaling
            # is an execution detail, not the candidate's identity)
            r.params = candidates[i][1]
            last_result[i] = r
            scores[i] = r.metric_value if r.error is None else worst
        if full:
            rung.promoted = list(alive)
            rungs_done.append(rung.to_json())
            break
        sign = -1.0 if larger_better else 1.0
        ranked = sorted(alive, key=lambda i: (sign * scores[i], i))
        promoted = sorted(ranked[:rung.survivors_out])
        rung.promoted = promoted
        for i in alive:
            if i not in promoted:
                eliminated[i] = (rung.index, rung.rows)
        alive = promoted
        rungs_done.append(rung.to_json())
        if checkpoint is not None:
            checkpoint.save_rung_state({
                "nextRung": rung.index + 1,
                "alive": [int(i) for i in alive],
                "last": {str(i): r.to_json()
                         for i, r in last_result.items()},
                "eliminated": {str(i): [ri, rr]
                               for i, (ri, rr) in eliminated.items()},
                "rungJson": rungs_done})

    if deferred_rungs:
        # ONE end-of-ladder fetch resolves every deferred rung's metrics
        # (the ladder's single materialization point); rung results then
        # apply to last_result IN RUNG ORDER so a candidate surviving to
        # a later rung reports that rung's (higher-fidelity) metric —
        # byte-identical to the sync ladder's incremental overwrites
        from ..selector.validators import _materialize

        unresolved = [e for e in deferred_rungs if e[4] is None]
        combined: List[Any] = []
        for _, _, vals, _, _ in unresolved:
            combined.extend(vals)
        host_all = _materialize(combined, tag="sweep.final",
                                overlap_tail=True)
        off = 0
        for e in unresolved:
            hv = host_all[off:off + len(e[2])]
            off += len(e[2])
            _, res = e[1].collect(hv, e[3], metric_name, larger_better)
            e[4] = res
        for snap, _, _, _, results in deferred_rungs:
            for i, r in zip(snap, results):
                r.params = candidates[i][1]
                last_result[i] = r

    for i, (ri, rrows) in eliminated.items():
        r = last_result[i]
        note = (f"halving: eliminated at rung {ri} "
                f"({rrows} of {n} rows); metric is the subsample "
                f"score, not a full-data result")
        if r.error is None or not str(r.error).startswith("halving:"):
            r.error = note if r.error is None else f"{note}; {r.error}"

    # winner: best FULL-rung result (ties -> lowest index)
    final_alive = [i for i in alive if last_result[i].error is None]
    pool = final_alive or alive
    sign = -1.0 if larger_better else 1.0
    best_i = min(pool, key=lambda i: (sign * (
        last_result[i].metric_value
        if last_result[i].error is None else worst), i))

    sched_json.update({
        "rungs": rungs_done,
        "candidateSeconds": round(total_cand_s, 4),
        "survivors": list(alive),
        "bestIndex": best_i,
    })
    results_out = [last_result[i] for i in range(k)]
    return best_i, results_out, sched_json
