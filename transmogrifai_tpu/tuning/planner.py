"""Cost-predicted plan-level choices: stream vs in-core, chunk geometry.

The execution machinery already HAS every knob — ``train(chunk_rows=k,
prefetch_chunks=p)`` switches to the out-of-core driver and
``TMOG_STREAM_RETAIN_MB`` bounds block retention — but until now picking
them was folklore.  This module turns the knobs into a deterministic
decision from (rows, cols, host budget) plus, when history exists, the
learned cost model's read-vs-transform rates for the prefetch depth.

Surfaced via ``ExecutionPlan.advise()`` / ``explain(advice=...)``
(workflow/plan.py) and consumed by ``OpWorkflow.train(tuner=Tuner(
auto_plan=True))``, which routes to the streaming driver with the advised
geometry when the advice says "stream".
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .costmodel import CostModel

__all__ = ["PlanAdvice", "advise_plan", "default_host_budget_bytes",
           "MeshAdvice", "advise_mesh"]

#: in-core peak is ~this multiple of the packed (N, D) f32 matrix: the
#: packed output + full-width raw/intermediate columns + device staging
#: (measured on the titanic-shaped benches; conservative on purpose)
IN_CORE_PEAK_MULTIPLIER = 3.0

#: target bytes per streamed chunk — big enough to amortize per-chunk
#: dispatch, small enough that prefetch depth x chunk stays modest
CHUNK_TARGET_BYTES = 64 << 20

_MIN_CHUNK_ROWS = 1024


def default_host_budget_bytes() -> int:
    """Host-memory budget for plan decisions: ``TMOG_HOST_BUDGET_MB`` or
    half of physical RAM (leave room for the OS, the device runtime and
    the allocator's slack), floored at 1 GB."""
    env = os.environ.get("TMOG_HOST_BUDGET_MB")
    if env:
        try:
            return max(int(float(env) * (1 << 20)), 1 << 20)
        except ValueError:
            pass
    try:
        total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):  # pragma: no cover
        total = 8 << 30
    return max(total // 2, 1 << 30)


@dataclass
class PlanAdvice:
    """A deterministic plan recommendation with its arithmetic shown."""

    mode: str                       # "in-core" | "stream"
    rows: int
    cols: int
    est_matrix_bytes: int
    est_in_core_peak_bytes: int
    host_budget_bytes: int
    chunk_rows: Optional[int]       # None for in-core
    prefetch_chunks: int
    retain_mb: int
    predicted_wall_s: Optional[float]   # cost-model total; None when cold
    reasons: List[str] = field(default_factory=list)
    #: optional MeshAdvice (ExecutionPlan.advise(queue_width=...))
    mesh: Optional["MeshAdvice"] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "mode": self.mode, "rows": self.rows, "cols": self.cols,
            "estMatrixBytes": self.est_matrix_bytes,
            "estInCorePeakBytes": self.est_in_core_peak_bytes,
            "hostBudgetBytes": self.host_budget_bytes,
            "chunkRows": self.chunk_rows,
            "prefetchChunks": self.prefetch_chunks,
            "retainMb": self.retain_mb,
            "predictedWallSecs": (round(self.predicted_wall_s, 3)
                                  if self.predicted_wall_s else None),
            "reasons": list(self.reasons),
            "mesh": self.mesh.to_json() if self.mesh is not None else None,
        }

    def format(self) -> str:
        mb = 1 << 20
        lines = [
            f"plan advice: {self.mode} "
            f"(matrix ~{self.est_matrix_bytes / mb:.0f} MB, in-core peak "
            f"~{self.est_in_core_peak_bytes / mb:.0f} MB vs host budget "
            f"{self.host_budget_bytes / mb:.0f} MB)"]
        if self.mode == "stream":
            lines.append(
                f"  chunk_rows={self.chunk_rows}, "
                f"prefetch_chunks={self.prefetch_chunks}, "
                f"retain_mb={self.retain_mb}")
        if self.predicted_wall_s:
            lines.append(
                f"  cost-model predicted wall ~{self.predicted_wall_s:.1f}s")
        for r in self.reasons:
            lines.append(f"  - {r}")
        if self.mesh is not None:
            lines.append(
                f"  mesh advice: {self.mesh.n_devices} device(s) "
                f"(data={self.mesh.data_axis}, grid={self.mesh.grid_axis})")
            for r in self.mesh.reasons:
                lines.append(f"  - {r}")
        return "\n".join(lines)


@dataclass
class MeshAdvice:
    """A deterministic mesh recommendation for a selector sweep."""

    n_devices: int                 # 1 = stay single-chip
    data_axis: int
    grid_axis: int
    rows: int
    cols: int
    queue_width: int
    #: predicted sweep wall per candidate device count (cost model with
    #: the n_devices feature); empty when the model is cold
    predicted_wall_s: Dict[int, float] = field(default_factory=dict)
    reasons: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"nDevices": self.n_devices, "dataAxis": self.data_axis,
                "gridAxis": self.grid_axis, "rows": self.rows,
                "cols": self.cols, "queueWidth": self.queue_width,
                "predictedWallSecs": {str(k): round(v, 4) for k, v
                                      in self.predicted_wall_s.items()},
                "reasons": list(self.reasons)}


#: below this many matrix elements a sweep mesh costs more in collective
#: and padding overhead than it saves (measured: titanic-scale sweeps are
#: dispatch-bound, not FLOP-bound)
MESH_MIN_ELEMS = 1 << 22

#: mesh-fit stage kinds the scaling prediction consults — the selector
#: totals plus the tree grid units (grid_groups records RandomForest:
#: fit-grid / GBT:fit-grid per batched run since PR 11, so advise_mesh
#: sees measured tree-grid scaling as soon as one sweep has run)
_MESH_KINDS = ("ModelSelector:fit", "ModelSelector:fit-halving",
               "RandomForest:fit-grid", "GBT:fit-grid")


def advise_mesh(rows: int, cols: int, queue_width: int,
                devices_available: Optional[int] = None,
                cost_model: Optional[CostModel] = None,
                backend: Optional[str] = None) -> MeshAdvice:
    """Recommend a ("data", "grid") sweep-mesh shape for a sweep of
    ``queue_width`` candidates over a (rows, cols) matrix.

    Tiers, mirroring the BenchBudgeter's philosophy (measured evidence
    beats a model beats an assumption):

    1. With a cost model whose selector buckets carry MEASURED multi-chip
       history (the ``n_devices`` feature), pick the device count with
       the lowest predicted sweep wall.
    2. Cold model: a size heuristic — meshes below ``MESH_MIN_ELEMS``
       matrix elements stay single-chip (dispatch-bound), larger shapes
       take every available device.

    Deterministic for fixed inputs; the grid axis always comes from
    :func:`transmogrifai_tpu.parallel.auto_grid_axis`.
    """
    import jax

    from ..parallel.mesh import auto_grid_axis

    rows, cols = max(int(rows), 1), max(int(cols), 1)
    queue_width = max(int(queue_width), 1)
    n_avail = (int(devices_available) if devices_available
               else len(jax.devices()))
    reasons: List[str] = []
    predicted: Dict[int, float] = {}

    candidates = [1]
    d = 2
    while d <= n_avail:
        candidates.append(d)
        d *= 2
    if cost_model is not None:
        fitted = [k for k in _MESH_KINDS
                  if cost_model.source(k, backend) == "fitted"]
        if fitted:
            for nd in candidates:
                predicted[nd] = sum(
                    cost_model.predict(k, rows, cols, backend=backend,
                                       n_devices=nd) for k in fitted)
            best = min(predicted, key=lambda nd: (predicted[nd], nd))
            reasons.append(
                f"measured scaling history: predicted sweep wall "
                f"{ {k: round(v, 3) for k, v in predicted.items()} } "
                f"-> {best} device(s)")
            n = best
        else:
            n = n_avail if rows * cols >= MESH_MIN_ELEMS else 1
            reasons.append(
                "cost model has no selector scaling history; size "
                f"heuristic ({rows * cols} elems vs {MESH_MIN_ELEMS} "
                f"floor) -> {n} device(s)")
    else:
        n = n_avail if rows * cols >= MESH_MIN_ELEMS else 1
        reasons.append(
            f"no cost model; size heuristic ({rows * cols} elems vs "
            f"{MESH_MIN_ELEMS} floor) -> {n} device(s)")
    n = max(1, min(n, n_avail))
    g = auto_grid_axis(n, queue_width)
    return MeshAdvice(n_devices=n, data_axis=n // g, grid_axis=g,
                      rows=rows, cols=cols, queue_width=queue_width,
                      predicted_wall_s=predicted, reasons=reasons)


def advise_plan(rows: int, cols: int, dtype_bytes: int = 4,
                host_budget_bytes: Optional[int] = None,
                cost_model: Optional[CostModel] = None,
                backend: Optional[str] = None) -> PlanAdvice:
    """Pick stream-vs-in-core and the streaming geometry for a workload of
    ``rows`` x ``cols`` (the packed feature-matrix shape, or the raw
    column count as a proxy before featurization).

    Pure and deterministic given its inputs: same shape + same budget →
    same advice, so plans are reproducible and testable.
    """
    rows, cols = max(int(rows), 1), max(int(cols), 1)
    budget = (int(host_budget_bytes) if host_budget_bytes
              else default_host_budget_bytes())
    matrix = rows * cols * dtype_bytes
    peak = int(matrix * IN_CORE_PEAK_MULTIPLIER)
    reasons: List[str] = []
    predicted = None
    if cost_model is not None:
        total = cost_model.predict_total(rows, cols, backend=backend)
        predicted = total or None

    if peak <= budget:
        reasons.append(
            f"projected in-core peak {peak >> 20} MB fits the "
            f"{budget >> 20} MB host budget")
        return PlanAdvice(
            mode="in-core", rows=rows, cols=cols,
            est_matrix_bytes=matrix, est_in_core_peak_bytes=peak,
            host_budget_bytes=budget, chunk_rows=None, prefetch_chunks=2,
            retain_mb=0, predicted_wall_s=predicted, reasons=reasons)

    row_bytes = max(cols * dtype_bytes, 1)
    chunk_rows = max(min(CHUNK_TARGET_BYTES // row_bytes, rows),
                     _MIN_CHUNK_ROWS)
    prefetch = 2
    if cost_model is not None:
        # read-bound pipelines benefit from deeper parse-ahead: compare
        # the model's ingest-read kinds against its transform kinds
        kinds = cost_model.fitted_kinds
        read_s = sum(cost_model.predict(k, chunk_rows, cols,
                                        backend=backend)
                     for k in kinds if "read" in k.lower())
        tx_s = sum(cost_model.predict(k, chunk_rows, cols, backend=backend)
                   for k in kinds if "transform" in k.lower())
        if read_s > 0 and tx_s > 0 and read_s > 1.5 * tx_s:
            prefetch = 4
            reasons.append(
                f"cost model predicts read-bound chunks "
                f"(read ~{read_s:.3f}s vs transform ~{tx_s:.3f}s) — "
                f"prefetch depth raised to 4")
    # spill threshold: retained blocks may use ~a quarter of the budget
    # before the block store spills to disk
    retain_mb = max(64, int(budget // 4) >> 20)
    reasons.append(
        f"projected in-core peak {peak >> 20} MB exceeds the "
        f"{budget >> 20} MB host budget — streaming with "
        f"~{CHUNK_TARGET_BYTES >> 20} MB chunks")
    return PlanAdvice(
        mode="stream", rows=rows, cols=cols,
        est_matrix_bytes=matrix, est_in_core_peak_bytes=peak,
        host_budget_bytes=budget, chunk_rows=int(chunk_rows),
        prefetch_chunks=prefetch, retain_mb=retain_mb,
        predicted_wall_s=predicted, reasons=reasons)
