from .feature_types import *  # noqa: F401,F403
from .columns import ColumnarDataset, FeatureColumn  # noqa: F401
