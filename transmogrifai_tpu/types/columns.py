"""Columnar storage for feature values.

The TPU-native replacement for the reference's row-oriented Spark DataFrame:
each feature is stored as one ``FeatureColumn`` — a batch of N values in the
representation best suited to its semantic type.  Numeric-like columns are
(values, mask) numpy/JAX arrays ready to move to device; text/list/map columns
stay host-side as Python object arrays until a vectorizer turns them into
device arrays.

Reference analogue: ``FeatureTypeSparkConverter`` / ``FeatureSparkTypes``
(features/src/main/scala/com/salesforce/op/features/FeatureTypeSparkConverter.scala:44)
which map each FeatureType to a Spark SQL storage type.  Here the mapping is to
array layouts instead:

    real/integral/binary/date  -> float64/int64 values + bool mask
    text (incl. subtypes)      -> object ndarray of str|None
    text_list/date_list        -> object ndarray of tuple
    multi_pick_list            -> object ndarray of frozenset
    geolocation                -> (N,3) float64 + bool mask
    map                        -> object ndarray of dict
    vector                     -> (N,D) float32 dense matrix (device-ready)
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence, Type

import numpy as np

from .feature_types import (
    FeatureType, OPVector, Prediction, RealMap, type_by_name,
)

__all__ = ["FeatureColumn", "ColumnarDataset"]

_NUMERIC_STORAGE = ("real", "integral", "binary", "date")


@dataclasses.dataclass
class FeatureColumn:
    """A batch of N values of one semantic feature type.

    ``values``: layout depends on ``ftype.storage`` (see module docstring).
    ``mask``: bool ndarray of shape (N,) — True where the value is present.
              Always present for numeric storages; None for object storages
              (presence is encoded in the objects themselves) and vectors.
    """

    ftype: Type[FeatureType]
    values: Any
    mask: Optional[np.ndarray] = None
    #: for OPVector columns: per-slot provenance (ops.vector_metadata.VectorMetadata)
    vmeta: Any = None

    def __post_init__(self):
        if self.ftype.storage in _NUMERIC_STORAGE and self.mask is None:
            vals = np.asarray(self.values)
            self.mask = ~np.isnan(vals) if vals.dtype.kind == "f" else np.ones(len(vals), bool)

    def __len__(self) -> int:
        return len(self.values)

    @property
    def storage(self) -> str:
        return self.ftype.storage

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_values(ftype: Type[FeatureType], raw: Sequence[Any]) -> "FeatureColumn":
        """Build a column from Python values (None/NaN = missing).

        This is the boundary where untyped host data becomes typed columnar
        data — the analogue of ``FeatureTypeSparkConverter.fromSpark``.
        """
        st = ftype.storage
        n = len(raw)
        # numeric ndarray fast path: per-element Python costs ~1 µs/value —
        # minutes at 1M rows × 100 columns (the 1M-row bench's bottleneck).
        # Coercions must match the slow path exactly: binary -> {0,1},
        # integral NaN -> 0 with mask False, real NaN -> NaN with mask False.
        if (st in ("real", "date", "integral", "binary")
                and isinstance(raw, np.ndarray)
                and raw.dtype.kind in "fiub"):
            vals = raw.astype(np.float64)
            mask = ~np.isnan(vals) if raw.dtype.kind == "f" \
                else np.ones(n, dtype=bool)
            if st == "binary":
                vals = np.where(mask, vals != 0, False).astype(np.float64)
            elif st == "integral":
                # trunc, not floor: the slow path coerces via int() which
                # truncates toward zero
                vals = np.where(mask, np.trunc(np.nan_to_num(vals)), 0.0)
            else:
                vals = np.where(mask, vals, np.nan)
            return FeatureColumn(ftype, vals, mask)
        if st in ("real", "date"):
            vals = np.array(
                [np.nan if _is_missing(v) else float(v) for v in raw], dtype=np.float64
            )
            return FeatureColumn(ftype, vals, ~np.isnan(vals))
        if st == "integral":
            mask = np.array([not _is_missing(v) for v in raw], dtype=bool)
            vals = np.array(
                [0 if _is_missing(v) else int(v) for v in raw], dtype=np.int64
            ).astype(np.float64)
            return FeatureColumn(ftype, vals, mask)
        if st == "binary":
            mask = np.array([not _is_missing(v) for v in raw], dtype=bool)
            vals = np.array(
                [False if _is_missing(v) else bool(v) for v in raw], dtype=bool
            ).astype(np.float64)
            return FeatureColumn(ftype, vals, mask)
        if st == "text":
            arr = np.empty(n, dtype=object)
            for i, v in enumerate(raw):
                arr[i] = None if _is_missing(v) else str(v)
            return FeatureColumn(ftype, arr)
        if st in ("text_list", "date_list"):
            arr = np.empty(n, dtype=object)
            for i, v in enumerate(raw):
                arr[i] = tuple(v) if v is not None else ()
            return FeatureColumn(ftype, arr)
        if st == "multi_pick_list":
            arr = np.empty(n, dtype=object)
            for i, v in enumerate(raw):
                arr[i] = frozenset(v) if v is not None else frozenset()
            return FeatureColumn(ftype, arr)
        if st == "geolocation":
            vals = np.full((n, 3), np.nan)
            mask = np.zeros(n, dtype=bool)
            for i, v in enumerate(raw):
                if v is not None and len(v) == 3:
                    vals[i] = v
                    mask[i] = True
            return FeatureColumn(ftype, vals, mask)
        if st == "map":
            arr = np.empty(n, dtype=object)
            for i, v in enumerate(raw):
                arr[i] = dict(v) if v is not None else {}
            return FeatureColumn(ftype, arr)
        if st == "vector":
            return FeatureColumn(ftype, np.asarray(raw, dtype=np.float32))
        raise ValueError(f"unknown storage {st!r} for {ftype.type_name()}")

    # -- conversions --------------------------------------------------------

    def to_list(self) -> List[Any]:
        """Back to plain Python values (None for missing). For tests/local scoring."""
        st = self.storage
        if st in _NUMERIC_STORAGE:
            out = []
            for v, m in zip(np.asarray(self.values), np.asarray(self.mask)):
                if not m:
                    out.append(None)
                elif st == "binary":
                    out.append(bool(v))
                elif st in ("integral", "date"):
                    out.append(int(v))
                else:
                    out.append(float(v))
            return out
        if st == "geolocation":
            return [
                list(map(float, v)) if m else []
                for v, m in zip(self.values, self.mask)
            ]
        if st == "vector":
            return [np.asarray(v) for v in self.values]
        return list(self.values)

    def masked_values(self, fill: float = 0.0) -> np.ndarray:
        """Numeric values with missing entries replaced by ``fill``."""
        assert self.storage in _NUMERIC_STORAGE
        vals = np.asarray(self.values, dtype=np.float64)
        return np.where(np.asarray(self.mask), np.nan_to_num(vals), fill)

    def take(self, idx: np.ndarray) -> "FeatureColumn":
        mask = self.mask[idx] if self.mask is not None else None
        return FeatureColumn(self.ftype, self.values[idx], mask, self.vmeta)

    def slice(self, start: int, stop: int) -> "FeatureColumn":
        """Zero-copy row-range view (the chunked-ingestion fallback path
        slices a materialized dataset into bounded chunks)."""
        mask = self.mask[start:stop] if self.mask is not None else None
        return FeatureColumn(self.ftype, self.values[start:stop], mask,
                             self.vmeta)


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    if isinstance(v, str) and v == "":
        return True
    return False


class ColumnarDataset:
    """An ordered {feature name -> FeatureColumn} batch — the working dataset.

    Plays the role of the Spark DataFrame flowing through
    ``FitStagesUtil.fitAndTransformDAG`` (reference FitStagesUtil.scala:212):
    stages read input columns and attach new output columns.
    """

    def __init__(self, columns: Optional[Dict[str, FeatureColumn]] = None,
                 *, _validated: bool = False):
        self.columns: Dict[str, FeatureColumn] = dict(columns or {})
        if not _validated:
            lengths = {len(c) for c in self.columns.values()}
            if len(lengths) > 1:
                raise ValueError(f"ragged dataset: column lengths {lengths}")

    # -- basic container ----------------------------------------------------

    def __len__(self) -> int:
        for c in self.columns.values():
            return len(c)
        return 0

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def __getitem__(self, name: str) -> FeatureColumn:
        return self.columns[name]

    def set(self, name: str, col: FeatureColumn) -> None:
        if self.columns and len(col) != len(self):
            raise ValueError(
                f"column {name!r} length {len(col)} != dataset length {len(self)}"
            )
        self.columns[name] = col

    def names(self) -> List[str]:
        return list(self.columns.keys())

    def with_columns(self, new: Dict[str, FeatureColumn]) -> "ColumnarDataset":
        """Copy-on-write append/override: a NEW dataset sharing every existing
        ``FeatureColumn`` buffer by reference, with ``new`` layered on top.

        This is what ``Transformer.transform`` returns — the analogue of the
        reference's immutable ``DataFrame.select(...)`` chaining, without
        Spark's plan machinery: untouched column buffers keep their identity
        (no O(rows) array copies; only O(columns) pointer copies), and the
        input dataset is never mutated, so the layer-parallel executor can
        hand the same dataset to concurrent stages safely.
        """
        n = len(self)
        for name, col in new.items():
            if self.columns and len(col) != n:
                raise ValueError(
                    f"column {name!r} length {len(col)} != dataset length {n}"
                )
        merged = dict(self.columns)
        merged.update(new)
        return ColumnarDataset(merged, _validated=True)

    def select(self, names: Iterable[str]) -> "ColumnarDataset":
        return ColumnarDataset({n: self.columns[n] for n in names},
                               _validated=True)

    def drop(self, names: Iterable[str]) -> "ColumnarDataset":
        dropset = set(names)
        return ColumnarDataset(
            {n: c for n, c in self.columns.items() if n not in dropset},
            _validated=True,
        )

    def take(self, idx: np.ndarray) -> "ColumnarDataset":
        return ColumnarDataset({n: c.take(idx) for n, c in self.columns.items()})

    def slice(self, start: int, stop: int) -> "ColumnarDataset":
        """Zero-copy row-range view over every column."""
        return ColumnarDataset(
            {n: c.slice(start, stop) for n, c in self.columns.items()},
            _validated=True)

    def copy(self) -> "ColumnarDataset":
        return ColumnarDataset(dict(self.columns), _validated=True)

    # -- pandas bridge ------------------------------------------------------

    @staticmethod
    def from_pandas(df, schema: Dict[str, Type[FeatureType]]) -> "ColumnarDataset":
        cols = {}
        for name, ftype in schema.items():
            cols[name] = FeatureColumn.from_values(ftype, df[name].tolist())
        return ColumnarDataset(cols)

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({n: c.to_list() for n, c in self.columns.items()})
