"""Semantic feature type system.

TPU-native re-design of TransmogrifAI's sealed ``FeatureType`` hierarchy
(reference: features/src/main/scala/com/salesforce/op/features/types/FeatureType.scala:44,
Numerics.scala:40-147, Text.scala:48-301, Lists.scala:40-76, Sets.scala:38,
Maps.scala:40-394, OPVector.scala:41, Geolocation.scala:47).

Design shift vs the reference: in the Scala/Spark original every *row value* is
boxed into a ``FeatureType`` instance wrapping an ``Option`` so that nullability
lives in the type.  On TPU the unit of work is a *column batch*, so here the
types are lightweight class tags describing the ML semantics of a whole column,
and nullability is carried by an explicit mask array in the columnar storage
(see ``transmogrifai_tpu.types.columns``).  The class hierarchy, trait mix-ins
(``NonNullable``, ``Categorical``, ``SingleResponse`` ...) and the full set of
~35 concrete types are preserved so that user-facing semantics (which
vectorizer a column gets, which types may be responses, etc.) match the
reference one-to-one.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

__all__ = [
    "FeatureType",
    "NonNullable",
    "SingleResponse",
    "MultiResponse",
    "Categorical",
    "Location",
    # numerics
    "OPNumeric", "Real", "RealNN", "Binary", "Integral", "Percent", "Currency",
    "Date", "DateTime",
    # text
    "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea", "PickList",
    "ComboBox", "Country", "State", "PostalCode", "City", "Street",
    # collections
    "OPCollection", "OPList", "TextList", "DateList", "DateTimeList",
    "OPSet", "MultiPickList", "OPVector", "Geolocation",
    # maps
    "OPMap", "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap", "URLMap",
    "TextAreaMap", "PickListMap", "ComboBoxMap", "CountryMap", "StateMap",
    "PostalCodeMap", "CityMap", "StreetMap", "NameStats", "RealMap",
    "IntegralMap", "BinaryMap", "CurrencyMap", "PercentMap", "DateMap",
    "DateTimeMap", "MultiPickListMap", "GeolocationMap", "Prediction",
    # registry helpers
    "type_by_name", "all_feature_types", "is_subtype",
]


class FeatureType:
    """Root of the semantic type hierarchy.

    Subclasses are used as *tags* (never instantiated to hold data); columnar
    data for a feature of type ``T`` lives in a ``FeatureColumn`` whose
    ``ftype`` attribute is ``T``.
    """

    #: storage kind understood by the columnar runtime:
    #: one of "real", "integral", "binary", "date", "text", "text_list",
    #: "date_list", "multi_pick_list", "vector", "geolocation", "map"
    storage: str = "real"

    @classmethod
    def type_name(cls) -> str:
        return cls.__name__

    @classmethod
    def is_nullable(cls) -> bool:
        return not issubclass(cls, NonNullable)

    @classmethod
    def default_value(cls):
        """Python-side empty value for this type (parity with FeatureType.empty)."""
        if cls.storage in ("real", "integral", "binary", "date"):
            return None
        if cls.storage == "text":
            return None
        if cls.storage in ("text_list", "date_list", "geolocation"):
            return []
        if cls.storage == "multi_pick_list":
            return set()
        if cls.storage == "vector":
            return []
        if cls.storage == "map":
            return {}
        return None


# ---------------------------------------------------------------------------
# Trait mix-ins (reference FeatureType.scala:122-158)
# ---------------------------------------------------------------------------

class NonNullable:
    """Marker: values of this type can never be empty."""


class SingleResponse:
    """Marker: type usable as a single response (label)."""


class MultiResponse:
    """Marker: type usable as a multi response."""


class Categorical:
    """Marker: type is categorical (pivot/one-hot by default)."""


class Location:
    """Marker: type carries geographic location semantics."""


# ---------------------------------------------------------------------------
# Numerics (reference features/types/Numerics.scala:40-147)
# ---------------------------------------------------------------------------

class OPNumeric(FeatureType):
    """Base for all numeric types."""
    storage = "real"


class Real(OPNumeric):
    storage = "real"


class RealNN(Real, NonNullable, SingleResponse):
    """Non-nullable real — the required label/response type for regression."""
    storage = "real"


class Binary(OPNumeric, SingleResponse, Categorical):
    storage = "binary"


class Integral(OPNumeric):
    storage = "integral"


class Percent(Real):
    storage = "real"


class Currency(Real):
    storage = "real"


class Date(Integral):
    storage = "date"


class DateTime(Date):
    storage = "date"


# ---------------------------------------------------------------------------
# Text (reference features/types/Text.scala:48-301)
# ---------------------------------------------------------------------------

class Text(FeatureType):
    storage = "text"


class Email(Text):
    pass


class Base64(Text):
    pass


class Phone(Text):
    pass


class ID(Text):
    pass


class URL(Text):
    pass


class TextArea(Text):
    pass


class PickList(Text, SingleResponse, Categorical):
    pass


class ComboBox(Text):
    pass


class Country(Text, Location):
    pass


class State(Text, Location):
    pass


class PostalCode(Text, Location):
    pass


class City(Text, Location):
    pass


class Street(Text, Location):
    pass


# ---------------------------------------------------------------------------
# Collections (reference Lists.scala, Sets.scala, OPVector.scala, Geolocation.scala)
# ---------------------------------------------------------------------------

class OPCollection(FeatureType):
    storage = "text_list"


class OPList(OPCollection):
    storage = "text_list"


class TextList(OPList):
    storage = "text_list"


class DateList(OPList):
    storage = "date_list"


class DateTimeList(DateList):
    storage = "date_list"


class OPSet(OPCollection, MultiResponse):
    storage = "multi_pick_list"


class MultiPickList(OPSet, Categorical):
    storage = "multi_pick_list"


class OPVector(OPCollection):
    """The assembled feature vector — a dense/sparse float row per example.

    Reference wraps Spark ml ``Vector`` (OPVector.scala:41); here columns of
    this type are (n, d) float arrays plus ``VectorMetadata`` provenance.
    """
    storage = "vector"


class Geolocation(OPList, Location):
    """(lat, lon, accuracy) triple (reference Geolocation.scala:47)."""
    storage = "geolocation"


# ---------------------------------------------------------------------------
# Maps (reference features/types/Maps.scala:40-394)
# ---------------------------------------------------------------------------

class OPMap(FeatureType):
    """Key -> value map; one key per raw column group."""
    storage = "map"
    #: semantic type of the map's values
    value_type: Type[FeatureType] = Text


class TextMap(OPMap):
    value_type = Text


class EmailMap(OPMap):
    value_type = Email


class Base64Map(OPMap):
    value_type = Base64


class PhoneMap(OPMap):
    value_type = Phone


class IDMap(OPMap):
    value_type = ID


class URLMap(OPMap):
    value_type = URL


class TextAreaMap(OPMap):
    value_type = TextArea


class PickListMap(OPMap, Categorical):
    value_type = PickList


class ComboBoxMap(OPMap):
    value_type = ComboBox


class CountryMap(OPMap, Location):
    value_type = Country


class StateMap(OPMap, Location):
    value_type = State


class PostalCodeMap(OPMap, Location):
    value_type = PostalCode


class CityMap(OPMap, Location):
    value_type = City


class StreetMap(OPMap, Location):
    value_type = Street


class NameStats(OPMap):
    """Name-detection statistics map (reference Maps.scala:326)."""
    value_type = Text


class RealMap(OPMap):
    value_type = Real


class IntegralMap(OPMap):
    value_type = Integral


class BinaryMap(OPMap, Categorical):
    value_type = Binary


class CurrencyMap(OPMap):
    value_type = Currency


class PercentMap(OPMap):
    value_type = Percent


class DateMap(OPMap):
    value_type = Date


class DateTimeMap(OPMap):
    value_type = DateTime


class MultiPickListMap(OPMap, Categorical):
    value_type = MultiPickList


class GeolocationMap(OPMap, Location):
    value_type = Geolocation


class Prediction(RealMap, NonNullable):
    """Model output map with reserved keys (reference Maps.scala:339-394).

    Keys: ``prediction``, ``probability_{i}``, ``rawPrediction_{i}``.
    """

    KEY_PREDICTION = "prediction"
    KEY_PROBABILITY = "probability_"
    KEY_RAW_PREDICTION = "rawPrediction_"

    @staticmethod
    def keys_for(n_classes: int) -> List[str]:
        keys = [Prediction.KEY_PREDICTION]
        keys += [f"{Prediction.KEY_RAW_PREDICTION}{i}" for i in range(n_classes)]
        keys += [f"{Prediction.KEY_PROBABILITY}{i}" for i in range(n_classes)]
        return keys


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

def _collect_types() -> Dict[str, Type[FeatureType]]:
    out: Dict[str, Type[FeatureType]] = {}
    stack: List[Type[FeatureType]] = [FeatureType]
    while stack:
        t = stack.pop()
        out[t.__name__] = t
        stack.extend(t.__subclasses__())
    return out


_REGISTRY: Dict[str, Type[FeatureType]] = _collect_types()


def type_by_name(name: str) -> Type[FeatureType]:
    """Resolve a feature type by its class name (for (de)serialization)."""
    global _REGISTRY
    if name not in _REGISTRY:
        _REGISTRY = _collect_types()
    return _REGISTRY[name]


def all_feature_types() -> List[Type[FeatureType]]:
    return list(_collect_types().values())


def is_subtype(t: Type[FeatureType], of: type) -> bool:
    return isinstance(t, type) and issubclass(t, of)
