from .profiling import (AppMetrics, MetricsCollector, OpStep,  # noqa: F401
                        profile_to, with_job_group)
