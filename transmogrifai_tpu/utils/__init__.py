from .profiling import (AppMetrics, MetricsCollector, OpStep,  # noqa: F401
                        profile_to, with_job_group)
from .sensitive import (GenderDetectionResults,  # noqa: F401
                        SensitiveFeatureInformation, SensitiveNameInformation,
                        sensitive_map_from_json, sensitive_map_to_json)
from .version import VERSION, VersionInfo, version_info  # noqa: F401
