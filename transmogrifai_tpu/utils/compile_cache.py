"""Persistent XLA compilation cache enablement.

The AutoML sweep's wall-clock on a tunneled TPU is dominated by XLA
compile time (the Titanic sweep compiles ~28 programs, ~50 s). JAX's
persistent compilation cache eliminates that on every run after the first,
but two things stand in the way on this backend:

* the cache dir config is only honored via ``jax.config.update`` (the
  ``JAX_COMPILATION_CACHE_DIR`` env var is not read by this jax version), and
* the experimental tunneled-TPU platform is not in JAX's platform allowlist,
  so the cache silently disables itself even though the backend supports
  executable serialization (verified: serialized executables round-trip and
  deserialized programs produce identical results).

``enable_persistent_cache`` handles both. Spark-analogue: the reference has
no equivalent (the JVM JITs per process); this is TPU-specific plumbing.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

__all__ = ["enable_persistent_cache", "record_compile", "record_hit",
           "cache_stats", "reset_cache_stats"]

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")

#: Platforms JAX itself allows persistent caching on (no poke needed).
_ALLOWLISTED_PLATFORMS = ("tpu", "gpu", "cuda", "rocm", "cpu")
#: Off-allowlist platforms where executable (de)serialization was verified
#: to round-trip with identical results, per jax version prefix.
_VALIDATED_POKE_PLATFORMS = ("axon",)
_VALIDATED_JAX_PREFIXES = ("0.9.",)

_enabled = False


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_secs: float = 0.15) -> bool:
    """Turn on the persistent compilation cache; safe to call repeatedly.

    Returns True if the cache is (now) enabled. Call before the first
    compilation for full effect; programs compiled earlier in the process
    are not retroactively cached.
    """
    global _enabled
    if _enabled:
        return True
    try:
        import jax
        import jax._src.compilation_cache as cc

        path = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           _DEFAULT_DIR)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        # Platforms outside JAX's allowlist (e.g. the tunneled-TPU plugin)
        # disable the cache during the first compile; pre-mark it usable —
        # but ONLY for the (platform, jax-version) combos where executable
        # serialization was actually verified to round-trip.  The poke
        # touches jax-internal state that renames freely across versions,
        # and a backend whose serialization is unsafe would silently load
        # wrong executables; unknown combos keep the upstream gate.
        # Resolve the platform WITHOUT initializing the backend when the
        # user has pinned it via config/env — enable_persistent_cache is
        # documented as safe to call at import time, before platform
        # selection would otherwise be latched.  Only fall back to
        # default_backend() (which does initialize) when nothing is pinned.
        pinned = (getattr(jax.config, "jax_platforms", None)
                  or os.environ.get("JAX_PLATFORMS") or "")
        platform = (pinned.split(",")[0].strip().lower() if pinned
                    else jax.default_backend())
        validated = (platform in _VALIDATED_POKE_PLATFORMS
                     and any(jax.__version__.startswith(v)
                             for v in _VALIDATED_JAX_PREFIXES))
        if platform not in _ALLOWLISTED_PLATFORMS:
            if not validated:
                import warnings
                warnings.warn(
                    "persistent compile cache NOT force-enabled: platform "
                    f"{platform!r} on jax {jax.__version__} is outside the "
                    "validated set "
                    f"{_VALIDATED_POKE_PLATFORMS}×{_VALIDATED_JAX_PREFIXES};"
                    " re-verify executable round-trip before extending",
                    RuntimeWarning, stacklevel=2)
                return False  # not latched: a fixed env can retry
            with cc._cache_initialized_mutex:
                cc._cache_checked = True
                cc._cache_used = True
        _enabled = True
    except Exception:  # pragma: no cover - cache is an optimization only
        return False
    return True


# ---------------------------------------------------------------------------
# in-process compile accounting
# ---------------------------------------------------------------------------
#
# The persistent cache above removes *cross-process* recompiles; serving
# additionally needs to PROVE that its steady state never compiles at all
# (docs/performance.md: a cold XLA compile is multi-second — two orders of
# magnitude over a serving deadline).  These counters are the ledger: every
# warm-program site (the serving executor's shape buckets) records a
# ``compile`` when it builds/first-executes a program for a key and a
# ``hit`` when it reuses one, so tests can assert "N requests, zero new
# compiles after warmup" instead of trusting timing.

_stats_lock = threading.Lock()
_compiles: Dict[str, int] = {}
_hits: Dict[str, int] = {}


def record_compile(key: str, n: int = 1) -> None:
    """Count a program build (first execution at a new shape) for ``key``."""
    with _stats_lock:
        _compiles[key] = _compiles.get(key, 0) + n


def record_hit(key: str, n: int = 1) -> None:
    """Count a warm reuse of the already-compiled program for ``key``."""
    with _stats_lock:
        _hits[key] = _hits.get(key, 0) + n


def cache_stats() -> Dict[str, Dict[str, int]]:
    """Snapshot: {'compiles': {key: n}, 'hits': {key: n}, 'totals': ...}."""
    with _stats_lock:
        compiles = dict(_compiles)
        hits = dict(_hits)
    return {
        "compiles": compiles,
        "hits": hits,
        "totals": {"compiles": sum(compiles.values()),
                   "hits": sum(hits.values())},
    }


def reset_cache_stats() -> None:
    with _stats_lock:
        _compiles.clear()
        _hits.clear()
