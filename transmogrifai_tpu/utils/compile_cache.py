"""Persistent XLA compilation cache enablement.

The AutoML sweep's wall-clock on a tunneled TPU is dominated by XLA
compile time (the Titanic sweep compiles ~28 programs, ~50 s). JAX's
persistent compilation cache eliminates that on every run after the first,
but two things stand in the way on this backend:

* the cache dir config is only honored via ``jax.config.update`` (the
  ``JAX_COMPILATION_CACHE_DIR`` env var is not read by this jax version), and
* the experimental tunneled-TPU platform is not in JAX's platform allowlist,
  so the cache silently disables itself even though the backend supports
  executable serialization (verified: serialized executables round-trip and
  deserialized programs produce identical results).

``enable_persistent_cache`` handles both. Spark-analogue: the reference has
no equivalent (the JVM JITs per process); this is TPU-specific plumbing.
"""
from __future__ import annotations

import os
from typing import Optional

__all__ = ["enable_persistent_cache"]

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")

_enabled = False


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_secs: float = 0.15) -> bool:
    """Turn on the persistent compilation cache; safe to call repeatedly.

    Returns True if the cache is (now) enabled. Call before the first
    compilation for full effect; programs compiled earlier in the process
    are not retroactively cached.
    """
    global _enabled
    if _enabled:
        return True
    try:
        import jax
        import jax._src.compilation_cache as cc

        path = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           _DEFAULT_DIR)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        # Platforms outside JAX's allowlist (e.g. the tunneled-TPU plugin)
        # disable the cache during the first compile; pre-mark it usable.
        # Correctness still depends on executable serialization, which the
        # put/get path verifies per entry.
        with cc._cache_initialized_mutex:
            cc._cache_checked = True
            cc._cache_used = True
        _enabled = True
    except Exception:  # pragma: no cover - cache is an optimization only
        return False
    return True
