"""Persistent XLA compilation cache enablement.

The AutoML sweep's wall-clock on a tunneled TPU is dominated by XLA
compile time (the Titanic sweep compiles ~28 programs, ~50 s). JAX's
persistent compilation cache eliminates that on every run after the first,
but two things stand in the way on this backend:

* the cache dir config is only honored via ``jax.config.update`` (the
  ``JAX_COMPILATION_CACHE_DIR`` env var is not read by this jax version), and
* the experimental tunneled-TPU platform is not in JAX's platform allowlist,
  so the cache silently disables itself even though the backend supports
  executable serialization (verified: serialized executables round-trip and
  deserialized programs produce identical results).

``enable_persistent_cache`` handles both. Spark-analogue: the reference has
no equivalent (the JVM JITs per process); this is TPU-specific plumbing.
"""
from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["enable_persistent_cache", "record_compile", "record_hit",
           "record_aot_load", "record_aot_miss",
           "cache_stats", "reset_cache_stats",
           "AOTStore", "AOT_FORMAT_VERSION", "default_aot_dir"]

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")

#: Platforms JAX itself allows persistent caching on (no poke needed).
_ALLOWLISTED_PLATFORMS = ("tpu", "gpu", "cuda", "rocm", "cpu")
#: Off-allowlist platforms where executable (de)serialization was verified
#: to round-trip with identical results, per jax version prefix.
_VALIDATED_POKE_PLATFORMS = ("axon",)
_VALIDATED_JAX_PREFIXES = ("0.9.",)

_enabled = False


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_secs: float = 0.15) -> bool:
    """Turn on the persistent compilation cache; safe to call repeatedly.

    Returns True if the cache is (now) enabled. Call before the first
    compilation for full effect; programs compiled earlier in the process
    are not retroactively cached.
    """
    global _enabled
    if _enabled:
        return True
    try:
        import jax
        import jax._src.compilation_cache as cc

        path = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                           _DEFAULT_DIR)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        # Platforms outside JAX's allowlist (e.g. the tunneled-TPU plugin)
        # disable the cache during the first compile; pre-mark it usable —
        # but ONLY for the (platform, jax-version) combos where executable
        # serialization was actually verified to round-trip.  The poke
        # touches jax-internal state that renames freely across versions,
        # and a backend whose serialization is unsafe would silently load
        # wrong executables; unknown combos keep the upstream gate.
        # Resolve the platform WITHOUT initializing the backend when the
        # user has pinned it via config/env — enable_persistent_cache is
        # documented as safe to call at import time, before platform
        # selection would otherwise be latched.  Only fall back to
        # default_backend() (which does initialize) when nothing is pinned.
        pinned = (getattr(jax.config, "jax_platforms", None)
                  or os.environ.get("JAX_PLATFORMS") or "")
        platform = (pinned.split(",")[0].strip().lower() if pinned
                    else jax.default_backend())
        validated = (platform in _VALIDATED_POKE_PLATFORMS
                     and any(jax.__version__.startswith(v)
                             for v in _VALIDATED_JAX_PREFIXES))
        if platform not in _ALLOWLISTED_PLATFORMS:
            if not validated:
                import warnings
                warnings.warn(
                    "persistent compile cache NOT force-enabled: platform "
                    f"{platform!r} on jax {jax.__version__} is outside the "
                    "validated set "
                    f"{_VALIDATED_POKE_PLATFORMS}×{_VALIDATED_JAX_PREFIXES};"
                    " re-verify executable round-trip before extending",
                    RuntimeWarning, stacklevel=2)
                return False  # not latched: a fixed env can retry
            with cc._cache_initialized_mutex:
                cc._cache_checked = True
                cc._cache_used = True
        _enabled = True
    except Exception:  # pragma: no cover - cache is an optimization only
        return False
    return True


# ---------------------------------------------------------------------------
# in-process compile accounting
# ---------------------------------------------------------------------------
#
# The persistent cache above removes *cross-process* recompiles; serving
# additionally needs to PROVE that its steady state never compiles at all
# (docs/performance.md: a cold XLA compile is multi-second — two orders of
# magnitude over a serving deadline).  These counters are the ledger: every
# warm-program site (the serving executor's shape buckets) records a
# ``compile`` when it builds/first-executes a program for a key and a
# ``hit`` when it reuses one, so tests can assert "N requests, zero new
# compiles after warmup" instead of trusting timing.

_stats_lock = threading.Lock()
_compiles: Dict[str, int] = {}
_hits: Dict[str, int] = {}
_aot_loads: Dict[str, int] = {}
_aot_misses: Dict[str, int] = {}


def record_compile(key: str, n: int = 1) -> None:
    """Count a program build (first execution at a new shape) for ``key``."""
    with _stats_lock:
        _compiles[key] = _compiles.get(key, 0) + n


def record_hit(key: str, n: int = 1) -> None:
    """Count a warm reuse of the already-compiled program for ``key``."""
    with _stats_lock:
        _hits[key] = _hits.get(key, 0) + n


def record_aot_load(key: str, n: int = 1) -> None:
    """Count a serialized executable loaded from the AOT store (a warm
    cold-start: no trace, no XLA compile)."""
    with _stats_lock:
        _aot_loads[key] = _aot_loads.get(key, 0) + n


def record_aot_miss(key: str, n: int = 1) -> None:
    """Count an AOT-store lookup that fell back to a JIT compile (absent,
    corrupted, or version-mismatched entry)."""
    with _stats_lock:
        _aot_misses[key] = _aot_misses.get(key, 0) + n


def cache_stats() -> Dict[str, Dict[str, int]]:
    """Snapshot: {'compiles': {key: n}, 'hits': {key: n}, 'totals': ...}."""
    with _stats_lock:
        compiles = dict(_compiles)
        hits = dict(_hits)
        aot_loads = dict(_aot_loads)
        aot_misses = dict(_aot_misses)
    return {
        "compiles": compiles,
        "hits": hits,
        "aotLoads": aot_loads,
        "aotMisses": aot_misses,
        "totals": {"compiles": sum(compiles.values()),
                   "hits": sum(hits.values()),
                   "aotLoads": sum(aot_loads.values()),
                   "aotMisses": sum(aot_misses.values())},
    }


def reset_cache_stats() -> None:
    with _stats_lock:
        _compiles.clear()
        _hits.clear()
        _aot_loads.clear()
        _aot_misses.clear()


# ---------------------------------------------------------------------------
# AOT executable store — content-addressed serialized XLA executables
# ---------------------------------------------------------------------------
#
# The persistent compilation cache above shortcuts the XLA *compile*; the
# AOT store goes further and persists the COMPILED EXECUTABLE itself
# (``jax.experimental.serialize_executable``), so a fresh serving process
# skips tracing, lowering AND compilation — cold start to first scored
# request drops from seconds (the Titanic-shaped DAG compiles ~28
# programs, ~50 s on the tunneled TPU) to milliseconds of deserialization.
#
# Entries are content-addressed: the key is a digest over the model's
# scoring parameters + shape bucket + backend + jax version + format
# version, so a changed model, a different backend, or a jax upgrade can
# NEVER load a stale executable — they simply miss and fall back to JIT
# (which writes the fresh entry through).  Writes are atomic (tmp +
# ``os.replace``, the utils/jsonio pattern) and every payload carries a
# sha256 checksum in its sidecar meta; a corrupted or truncated entry
# reads as a miss and is deleted, never served.

#: bump to invalidate every persisted executable (layout/semantic change)
AOT_FORMAT_VERSION = 1

_DEFAULT_AOT_DIR = os.path.join(_DEFAULT_DIR, "aot")


def default_aot_dir() -> str:
    """Resolve the AOT store root: ``TMOG_AOT_CACHE_DIR`` or
    ``<repo>/.jax_cache/aot``."""
    return os.environ.get("TMOG_AOT_CACHE_DIR", _DEFAULT_AOT_DIR)


class AOTStore:
    """On-disk content-addressed store of serialized XLA executables.

    One entry = ``<key>.bin`` (the serialized executable payload) +
    ``<key>.json`` (sidecar meta: checksum, backend, jax version, format
    version, output arity — everything a loader needs to validate the
    entry and rebuild the call trees without tracing).

    The store is a FLEET-shared artifact cache, not a per-process one:
    keys are content digests of (model, bucket, backend, jax version), a
    write is atomic tmp+fsync+``os.replace``, and ``get`` validates the
    checksummed sidecar before trusting a payload — so N serving hosts
    (or a host and its replacement) can safely point at one shared
    directory (``TMOG_AOT_CACHE_DIR``, e.g. on NFS).  The first host to
    compile a bucket warms every later cold start: a fresh replica loads
    the serialized executable byte-identically instead of compiling
    (bench_serving's shared-cache leg gates ``compiles == 0`` on the
    second process).  Concurrent writers of the same key race benignly —
    content addressing makes both payloads identical.
    """

    def __init__(self, root: Optional[str] = None):
        self.root = root or default_aot_dir()

    # -- paths --------------------------------------------------------------

    def _paths(self, key: str) -> Tuple[str, str]:
        return (os.path.join(self.root, f"{key}.bin"),
                os.path.join(self.root, f"{key}.json"))

    # -- write --------------------------------------------------------------

    def put(self, key: str, payload: bytes, meta: Dict[str, Any]) -> None:
        """Persist one executable atomically.  ``meta`` is augmented with
        the payload checksum + size and the format version; a crashed
        writer leaves either the previous complete entry or none."""
        from .jsonio import write_json_atomic

        os.makedirs(self.root, exist_ok=True)
        bin_path, meta_path = self._paths(key)
        tmp = bin_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, bin_path)
        doc = dict(meta)
        doc["sha256"] = hashlib.sha256(payload).hexdigest()
        doc["bytes"] = len(payload)
        doc["formatVersion"] = AOT_FORMAT_VERSION
        write_json_atomic(meta_path, doc)

    # -- read ---------------------------------------------------------------

    def get(self, key: str,
            expect: Optional[Dict[str, Any]] = None
            ) -> Optional[Tuple[bytes, Dict[str, Any]]]:
        """Load + validate one entry; None on ANY problem (absent,
        truncated, checksum mismatch, format/field mismatch vs ``expect``)
        — the caller falls back to JIT.  Invalid entries are deleted so
        the write-through replaces them instead of tripping forever."""
        from .jsonio import read_json_tolerant

        bin_path, meta_path = self._paths(key)
        meta = read_json_tolerant(meta_path, default={})
        if not meta:
            return None
        try:
            with open(bin_path, "rb") as f:
                payload = f.read()
        except OSError:
            return None
        ok = (meta.get("formatVersion") == AOT_FORMAT_VERSION
              and meta.get("bytes") == len(payload)
              and meta.get("sha256")
              == hashlib.sha256(payload).hexdigest())
        if ok and expect:
            ok = all(meta.get(k) == v for k, v in expect.items())
        if not ok:
            self.invalidate(key)
            return None
        return payload, meta

    def contains(self, key: str,
                 expect: Optional[Dict[str, Any]] = None) -> bool:
        """Cheap validity probe (meta-only: checksum is verified at
        ``get`` time, field/version match here)."""
        from .jsonio import read_json_tolerant

        bin_path, meta_path = self._paths(key)
        if not os.path.exists(bin_path):
            return False
        meta = read_json_tolerant(meta_path, default={})
        if not meta or meta.get("formatVersion") != AOT_FORMAT_VERSION:
            return False
        if expect and any(meta.get(k) != v for k, v in expect.items()):
            return False
        return True

    def invalidate(self, key: str) -> None:
        for p in self._paths(key):
            try:
                os.unlink(p)
            except OSError:
                pass

    def keys(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n[:-4] for n in names if n.endswith(".bin"))

    def stats(self) -> Dict[str, Any]:
        """Fleet-operator view of the shared cache directory: entry count
        + payload bytes (the answer to "is the shared cache actually
        warming cold starts, and how big has it grown")."""
        entries = self.keys()
        payload_bytes = 0
        for k in entries:
            try:
                payload_bytes += os.path.getsize(self._paths(k)[0])
            except OSError:
                pass
        return {"root": self.root, "entries": len(entries),
                "payloadBytes": payload_bytes}
