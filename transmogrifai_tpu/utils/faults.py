"""Deterministic fault injection — the test substrate for the resilience layer.

The reference inherited fault tolerance from Spark and could test it by
killing executors; this port runs in one process, so recovery paths (reader
retry, bad-record quarantine, checkpoint/resume — docs/robustness.md) would
otherwise only ever execute in production.  This module plants named
injection points on the hot paths and lets tests arm them with a
deterministic plan: *this* chunk fails with an IO error twice, *that*
transform raises, the process is SIGKILLed at the k-th checkpoint barrier.

Injection points (each a single ``fire()`` call, a no-op global check when
no plan is armed):

  ``reader.chunk``       before chunk ``index`` leaves the reader's
                         ChunkStream (readers/base.py) — an ``io_error``
                         here exercises retry/backoff
  ``avro.block``         before Avro container block ``index`` decodes
                         (readers/avro.py)
  ``stage.transform``    before a stage transform runs (stages/base.py);
                         ``tag`` is the stage class name
  ``checkpoint.barrier`` right after checkpoint save ``index`` hits disk
                         (workflow/checkpoint.py) — a ``kill`` here is the
                         canonical crash-resume test
  ``sweep.checkpoint``   right after mid-sweep cursor save ``index`` hits
                         disk (workflow/checkpoint.SweepCheckpointManager)
                         — a ``kill`` here is the mid-SWEEP crash-resume
                         test (tests/test_parallel_mesh.py)
  ``unit.slow``          at the top of every sweep-unit attempt
                         (selector/validators.SweepWorkQueue.run_unit);
                         ``index`` is the unit's queue index — a ``slow``
                         here exercises the straggler watchdog
  ``device.loss``        same site — a ``device_loss`` action here
                         exercises the elastic shrink/retry/quarantine
                         ladder (parallel/elastic.py)
  ``drift.window``       at every drift-window evaluation
                         (serving/drift.DriftMonitor.evaluate); ``index``
                         is the window ordinal — a ``raise`` here
                         exercises a monitor that cannot evaluate
  ``swap.shadow``        at every guarded-swap shadow evaluation
                         (serving/guarded.GuardedSwap.propose); ``index``
                         is the proposal ordinal — a ``raise`` here lands
                         as a structured gate REJECTION
                         (``shadow_error:FaultError``), never a swap
  ``swap.bake``          at every post-swap bake probe
                         (serving/guarded.GuardedSwap.bake_probe);
                         ``index`` is the probe ordinal — a ``raise``
                         here triggers the automatic ROLLBACK to the
                         pinned generation (``probe_error:FaultError``)
  ``rff.pass``           at the start of each RawFeatureFilter streaming
                         distribution pass (filters/raw_feature_filter.
                         filter_streaming); ``index`` 0 = train pass,
                         1 = scoring pass, ``tag`` = "train"/"score" —
                         an ``io_error`` below it (reader.chunk)
                         exercises retry on the profile pass
  ``cv.fold``            as each streaming workflow-CV fold context
                         builds its matrices (workflow/streaming_cv.
                         StreamingCVContext.run_validation); ``index``
                         is the fold ordinal
  ``soak.phase``         at every phase boundary of the soak scenario
                         (examples/bench_soak.py); ``index`` is the
                         phase ordinal, ``tag`` the phase name — the
                         handle for aiming any fault at "during phase k"
  ``event.window``       before each finalized key-window chunk leaves
                         the streamed event fold (readers/events.py);
                         ``index`` is the output chunk ordinal — an
                         ``io_error`` here exercises retry over the
                         whole scan+fold re-run
  ``join.chunk``         before each streamed sort-merge join chunk
                         (readers/events.stream_join); ``index`` is the
                         joined chunk ordinal
  ``pod.barrier``        at the top of every pod barrier
                         (distributed/runtime.PodContext.barrier);
                         ``tag`` is the barrier name — a ``skip`` here
                         (with a ``process`` selector) makes ONE host
                         silently skip the rendezvous, the canonical
                         collective-divergence (TM074) test
  ``host.heartbeat``     before the fabric router probes one host's
                         /healthz (serving/fabric.ServingFabric.
                         probe_once); ``tag`` is the host id — a ``skip``
                         SUPPRESSES the heartbeat (age grows toward
                         eviction), a ``slow``/``io_error`` delays/fails
                         the probe; the hysteresis test handle
  ``router.forward``     before the router forwards a request to a host
                         (serving/fabric.ServingFabric.score); ``tag``
                         is the host id — an ``io_error`` here exercises
                         single-retry failover to a survivor, a ``slow``
                         burns the deadline budget
  ``swap.propagate``     after each control-channel exchange delivers
                         (serving/fabric.ControlChannel.publish);
                         ``index`` is the channel sequence, ``tag`` the
                         op ("swap"/"drift") — a ``skip`` (with a
                         ``process`` selector) drops the message on ONE
                         replica only: the transport stays lockstep, the
                         fleet-swap verdict gather detects non-receipt
                         and repairs or vetoes

Actions: ``io_error`` (raise OSError — the transient class the reader
retry policy handles), ``raise`` (RuntimeError — non-transient), ``slow``
(sleep ``delay_s``), ``kill`` (SIGKILL this process; subprocess tests
only), ``device_loss`` (raise :class:`DeviceLossError`, whose message is
shaped like the XLA backend-loss family so the shared classifier
``parallel.elastic.is_device_loss`` recognizes it), ``skip`` (raise
:class:`FaultSkip`, which the injection SITE catches to skip the guarded
operation entirely — only sites documented as skippable catch it).

Determinism: a spec matches by explicit call index (``at``/``every``) or by
a seeded per-point Bernoulli draw (``p`` + plan ``seed``) — same plan, same
call sequence, same faults, every run.  ``times`` bounds how often a spec
fires (so a retried chunk can succeed on attempt N+1).

Arming: programmatic (``install_faults`` / the ``inject`` context manager)
or via the ``TMOG_FAULTS`` env var (JSON, read once at first ``fire``) so a
kill-target subprocess can be armed from the outside::

    TMOG_FAULTS='{"faults": [{"point": "checkpoint.barrier",
                              "action": "kill", "at": 0}]}'
"""
from __future__ import annotations

import contextlib
import json
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["FaultSpec", "FaultPlan", "FaultError", "DeviceLossError",
           "FaultSkip", "install_faults", "clear_faults", "current_plan",
           "inject", "fire", "ENV_VAR"]

ENV_VAR = "TMOG_FAULTS"

_ACTIONS = ("io_error", "raise", "slow", "kill", "device_loss", "skip")


class FaultError(RuntimeError):
    """Raised by the ``raise`` action (non-transient by design: the retry
    policy must NOT swallow it)."""


class DeviceLossError(RuntimeError):
    """Raised by the ``device_loss`` action — the injected stand-in for a
    chip/backend dying mid-program.  The message carries the XLA
    backend-loss needles so ``parallel.elastic.is_device_loss`` classifies
    it exactly like the real thing."""


class FaultSkip(Exception):
    """Raised by the ``skip`` action; the injection SITE catches it and
    skips the guarded operation (e.g. one pod process silently skipping
    a barrier).  Deliberately not a RuntimeError so generic handlers
    never swallow it by accident."""


@dataclass
class FaultSpec:
    """One armed fault.

    ``at``: explicit call index (int or list of ints) for the point;
    ``every``: fire on every n-th call; ``p``: seeded Bernoulli per call.
    Exactly one selector should be set; ``at`` wins, then ``every``, then
    ``p``; a bare spec matches every call.  ``tag`` restricts matching to
    fires carrying the same tag (e.g. a stage class name); ``skip``
    passes over the first n otherwise-matching calls (the way to target
    "the 3rd transform of stage X" when the point's call counter is
    global).  ``times`` caps total firings (None = unlimited).

    ``process`` restricts the spec to ONE pod process (the
    ``distributed.runtime`` process index): pod children inherit the
    whole ``TMOG_FAULTS`` schedule from the launcher's env, so without a
    ``process`` selector a deterministic spec fires IDENTICALLY on every
    process (replicas stay in lockstep); with one, a fault — e.g. a
    ``device_loss`` — lands on a single host while the others keep
    running, which is the "one host loses a chip" scenario the pod
    barrier protocol must survive without deadlocking.
    """

    point: str
    action: str = "io_error"
    at: Optional[Any] = None
    every: Optional[int] = None
    p: Optional[float] = None
    tag: Optional[str] = None
    skip: int = 0
    times: Optional[int] = 1
    delay_s: float = 0.05
    message: str = "injected fault"
    process: Optional[int] = None
    fired: int = field(default=0, compare=False)
    seen: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}; "
                             f"expected one of {_ACTIONS}")

    def matches(self, index: int, tag: Optional[str], draw: float) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.tag is not None and tag != self.tag:
            return False
        if self.process is not None:
            from ..distributed.runtime import current_pod

            if current_pod().process_index != self.process:
                return False
        if self.at is not None:
            ats = self.at if isinstance(self.at, (list, tuple)) else [self.at]
            hit = index in ats
        elif self.every is not None:
            hit = self.every > 0 and index % self.every == 0
        elif self.p is not None:
            hit = draw < self.p
        else:
            hit = True  # bare point spec: every matching call
        if not hit:
            return False
        if self.seen < self.skip:
            self.seen += 1
            return False
        return True

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"point": self.point, "action": self.action}
        for k in ("at", "every", "p", "tag", "times", "process"):
            if getattr(self, k) is not None:
                out[k] = getattr(self, k)
        if self.skip:
            out["skip"] = self.skip
        if self.action == "slow":
            out["delay_s"] = self.delay_s
        return out


class FaultPlan:
    """A set of armed FaultSpecs plus the per-point call counters.

    Call counters advance on EVERY fire of a point (hit or miss), so a
    spec's ``at=k`` means "the k-th time execution reaches this point"
    regardless of other specs — deterministic by construction.  The seeded
    RNG stream for ``p`` specs is per point, keyed independent of call
    interleaving across points.
    """

    def __init__(self, faults: List[FaultSpec], seed: int = 0):
        import numpy as np

        self.faults = list(faults)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._rngs: Dict[str, Any] = {}
        self._np = np
        self.log: List[Dict[str, Any]] = []  # fired faults, for assertions

    @classmethod
    def from_json(cls, doc: Any) -> "FaultPlan":
        if isinstance(doc, str):
            doc = json.loads(doc)
        if isinstance(doc, list):
            doc = {"faults": doc}
        specs = [FaultSpec(**f) for f in doc.get("faults", [])]
        return cls(specs, seed=doc.get("seed", 0))

    def to_json(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "faults": [f.to_json() for f in self.faults]}

    def _draw(self, point: str) -> float:
        rng = self._rngs.get(point)
        if rng is None:
            # stable per-point stream: plan seed + point-name hash
            h = sum(ord(c) * 131 ** i for i, c in enumerate(point)) % (1 << 31)
            rng = self._rngs[point] = self._np.random.default_rng(
                self.seed ^ h)
        return float(rng.random())

    def fire(self, point: str, tag: Optional[str] = None,
             index: Optional[int] = None) -> None:
        """``index`` overrides the call counter as the match key — sites
        with a natural coordinate (chunk id, block id) pass it so a spec's
        ``at=k`` means "the k-th CHUNK" even when retries replay calls."""
        with self._lock:
            calls = self._calls.get(point, 0)
            self._calls[point] = calls + 1
            if index is None:
                index = calls
            draw = self._draw(point)
            hit: Optional[FaultSpec] = None
            for spec in self.faults:
                if spec.point == point and spec.matches(index, tag, draw):
                    spec.fired += 1
                    hit = spec
                    break
            if hit is not None:
                self.log.append({"point": point, "index": index, "tag": tag,
                                 "action": hit.action})
        if hit is None:
            return
        from ..obs.flight import record_event

        record_event("fault.fired", point=point, index=index, tag=tag,
                     action=hit.action)
        where = f"{point}[{index}]" + (f" tag={tag}" if tag else "")
        if hit.action == "slow":
            time.sleep(hit.delay_s)
        elif hit.action == "io_error":
            raise OSError(f"{hit.message} ({where})")
        elif hit.action == "raise":
            raise FaultError(f"{hit.message} ({where})")
        elif hit.action == "device_loss":
            raise DeviceLossError(
                f"injected device loss: UNAVAILABLE: TPU backend "
                f"setup/compile error ({where})")
        elif hit.action == "skip":
            raise FaultSkip(f"{hit.message} ({where})")
        elif hit.action == "kill":  # pragma: no cover - dies before report
            os.kill(os.getpid(), signal.SIGKILL)

    def calls(self, point: str) -> int:
        with self._lock:
            return self._calls.get(point, 0)


#: sentinel: "not yet initialized from the environment"
_UNSET = object()
_plan: Any = _UNSET
_plan_lock = threading.Lock()


def install_faults(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Arm ``plan`` process-wide (None disarms); returns the plan."""
    global _plan
    with _plan_lock:
        _plan = plan
    return plan


def clear_faults() -> None:
    install_faults(None)


def current_plan() -> Optional[FaultPlan]:
    """The armed plan; first call resolves the ``TMOG_FAULTS`` env var."""
    global _plan
    if _plan is _UNSET:
        with _plan_lock:
            if _plan is _UNSET:
                raw = os.environ.get(ENV_VAR)
                _plan = FaultPlan.from_json(raw) if raw else None
    return _plan


@contextlib.contextmanager
def inject(*specs: FaultSpec, seed: int = 0):
    """Arm a plan for the enclosed block (tests); restores the previous
    plan (including the not-yet-loaded env state) on exit."""
    global _plan
    with _plan_lock:
        prev = _plan
    plan = FaultPlan(list(specs), seed=seed)
    install_faults(plan)
    try:
        yield plan
    finally:
        with _plan_lock:
            _plan = prev


def fire(point: str, tag: Optional[str] = None,
         index: Optional[int] = None) -> None:
    """Injection-site hook — a single global check when nothing is armed."""
    plan = current_plan()
    if plan is not None:
        plan.fire(point, tag=tag, index=index)
