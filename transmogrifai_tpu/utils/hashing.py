"""Stable 32-bit MurmurHash3 for feature hashing.

Reference: ``OPCollectionHashingVectorizer`` hashes tokens with MurmurHash3
(core/.../impl/feature/OPCollectionHashingVectorizer.scala:59).  Python's
builtin ``hash`` is salted per-process, so we implement murmur3_x86_32
directly; results are cached per token and vectorizers dedupe with
``np.unique`` first, so the per-token Python cost is amortized.
"""
from __future__ import annotations

from functools import lru_cache

__all__ = ["murmur3_32", "hash_to_bucket"]

_M1 = 0xCC9E2D51
_M2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


@lru_cache(maxsize=1 << 20)
def murmur3_32(key: str, seed: int = 42) -> int:
    data = key.encode("utf-8")
    n = len(data)
    h = seed & _MASK
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k = (k * _M1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _M2) & _MASK
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    # tail
    k = 0
    tail = data[nblocks * 4 :]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _M1) & _MASK
        k = _rotl32(k, 15)
        k = (k * _M2) & _MASK
        h ^= k
    # finalize
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


def hash_to_bucket(key: str, num_buckets: int, seed: int = 42) -> int:
    return murmur3_32(key, seed) % num_buckets
