"""Atomic JSON file IO — tmp + ``os.replace`` (same pattern as
workflow/checkpoint.py): a killed process can never leave a truncated
JSON artifact behind, only the previous complete one.

Used by the self-updating cost history (``benchmarks/cost_history.json``,
tuning/costmodel.py), the bench drivers' ``benchmarks/*_latest.json``
snapshots, and anything else that persists run telemetry.
"""
from __future__ import annotations

import json
import os
from typing import Any, Optional

__all__ = ["write_json_atomic", "read_json_tolerant", "dumps_canonical"]


def dumps_canonical(obj: Any, indent: Optional[int] = 2,
                    sort_keys: bool = False) -> str:
    """EXACTLY the text :func:`write_json_atomic` lands on disk for
    ``obj`` (same separators, same trailing newline) — the byte-equality
    anchor the checkpoint round-trip contract (TM026,
    ``analysis/contracts.py``) compares against."""
    return json.dumps(obj, indent=indent, sort_keys=sort_keys,
                      default=str) + "\n"


def write_json_atomic(path: str, obj: Any, indent: Optional[int] = 2,
                      sort_keys: bool = False) -> None:
    """Serialize ``obj`` to ``path`` via a same-directory temp file and
    ``os.replace`` — the rename is atomic on POSIX, so concurrent readers
    (and post-crash readers) only ever see a complete document."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(directory, os.path.basename(path) + ".tmp")
    with open(tmp, "w") as f:
        f.write(dumps_canonical(obj, indent=indent, sort_keys=sort_keys))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json_tolerant(path: str, default: Any = None) -> Any:
    """Load JSON, returning ``default`` on a missing/corrupt file (a
    history file is advisory state — never worth crashing a run over)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return default if default is not None else {}
