"""Tracing / profiling — phase markers and run metrics.

Reference: ``OpStep`` job-group labels (utils/spark/OpStep.scala:38-46),
``JobGroupUtil.withJobGroup`` (core/.../utils/spark/JobGroupUtil.scala),
``OpSparkListener`` per-stage/app metrics collection
(utils/spark/OpSparkListener.scala:62-148, AppMetrics :173).

TPU redesign: there is no Spark scheduler to listen to — phases are explicit
context managers that accumulate wall-clock into a per-run
``MetricsCollector``, and the deep profile comes from XLA itself via
``jax.profiler`` (trace files viewable in TensorBoard/Perfetto), which
replaces the Spark UI.
"""
from __future__ import annotations

import contextlib
import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["OpStep", "MetricsCollector", "AppMetrics", "StepMetrics",
           "with_job_group", "current_collector", "install_collector",
           "profile_to", "RunCounters", "COUNTERS", "reset_counters",
           "count_upload", "count_fetch", "count_drain", "count_launch",
           "fetch_timed", "StageProfile", "PlanProfiler",
           "IngestPass", "IngestProfiler", "LintSnapshot", "backend_name",
           "mesh_desc"]


class OpStep(enum.Enum):
    """Phases of a workflow run (OpStep.scala:38-46 parity)."""

    CrossValidation = "Cross-validation"
    DataReadingAndFiltering = "Data reading and filtering"
    FeatureEngineering = "Feature engineering"
    ModelIO = "Model loading / saving"
    Other = "Other"
    ResultsSaving = "Results saving"
    Scoring = "Scoring"  # TPU addition: batched/streaming score phases
    Serving = "Serving"  # TPU addition: online micro-batch serving (serving/)


@dataclass
class StepMetrics:
    step: str
    duration_secs: float
    count: int = 1

    def to_json(self) -> Dict[str, Any]:
        return {"step": self.step, "durationSecs": self.duration_secs,
                "count": self.count}


@dataclass
class AppMetrics:
    """Aggregate run metrics (OpSparkListener.AppMetrics parity)."""

    app_name: str = "transmogrifai_tpu"
    run_type: Optional[str] = None
    app_start_time: float = field(default_factory=time.time)
    app_end_time: Optional[float] = None
    step_metrics: Dict[str, StepMetrics] = field(default_factory=dict)
    custom_tags: Dict[str, str] = field(default_factory=dict)

    @property
    def app_duration(self) -> float:
        end = self.app_end_time if self.app_end_time is not None else time.time()
        return end - self.app_start_time

    def to_json(self) -> Dict[str, Any]:
        return {
            "appName": self.app_name,
            "runType": self.run_type,
            "appDurationSecs": self.app_duration,
            "stepMetrics": [m.to_json() for m in self.step_metrics.values()],
            "customTags": dict(self.custom_tags),
        }


class MetricsCollector:
    """Accumulates per-step wall-clock for one run; thread-safe."""

    def __init__(self, app_name: str = "transmogrifai_tpu",
                 run_type: Optional[str] = None):
        self.metrics = AppMetrics(app_name=app_name, run_type=run_type)
        self._lock = threading.Lock()
        self._end_handlers: List[Callable[[AppMetrics], None]] = []

    def record(self, step: OpStep, duration_secs: float) -> None:
        with self._lock:
            cur = self.metrics.step_metrics.get(step.name)
            if cur is None:
                self.metrics.step_metrics[step.name] = StepMetrics(
                    step.name, duration_secs)
            else:
                cur.duration_secs += duration_secs
                cur.count += 1

    def add_application_end_handler(
            self, fn: Callable[[AppMetrics], None]) -> None:
        """OpWorkflowRunner.addApplicationEndHandler (:145) parity."""
        self._end_handlers.append(fn)

    def finish(self) -> AppMetrics:
        # end-time write under the same lock record() holds — a serving
        # thread can still be recording when the run finishes; handlers
        # run OUTSIDE the lock (they may read/record themselves)
        with self._lock:
            self.metrics.app_end_time = time.time()
        for fn in self._end_handlers:
            try:
                fn(self.metrics)
            except Exception:  # handlers must not break the run
                pass
        return self.metrics


_local = threading.local()


def current_collector() -> Optional[MetricsCollector]:
    return getattr(_local, "collector", None)


@contextlib.contextmanager
def install_collector(collector: MetricsCollector):
    """Make ``collector`` the thread-current one for the enclosed block
    WITHOUT recording a step for the block itself (the run's total lives in
    AppMetrics.app_duration; steps are for attributed time only)."""
    prev = current_collector()
    _local.collector = collector
    try:
        yield collector
    finally:
        _local.collector = prev


@contextlib.contextmanager
def with_job_group(step: OpStep, collector: Optional[MetricsCollector] = None):
    """Label a phase of the run (JobGroupUtil.withJobGroup parity).

    The first entered group installs its collector as the thread-current one
    so nested library code can record into the same run.
    """
    coll = collector or current_collector()
    installed = False
    if coll is not None and current_collector() is None:
        _local.collector = coll
        installed = True
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if coll is not None:
            coll.record(step, dt)
        if installed:
            _local.collector = None


@dataclass
class RunCounters:
    """Transfer / dispatch accounting for one run.

    Uploads and fetches are counted at the framework's own transfer sites
    (``trees._dev_memo`` builds, ``validators._materialize``, binned-matrix
    uploads); ``upload_s``/``fetch_s`` time the enqueuing call — through a
    remote-device tunnel that call blocks for most of the wire time, so
    these are honest lower bounds on transfer cost.  ``drain_s`` separates
    QUEUE-DRAIN from transfer at the fetch sites (``fetch_timed``): a
    stacked metric fetch after an async sweep blocks first on the enqueued
    device work finishing, and booking that wait as "fetch" misdirected
    round-3's optimization targeting (VERDICT r3 Weak #6) — drain is
    compute-to-wait-for, fetch is bytes-on-the-wire.  On backends where
    ``block_until_ready`` returns early (the tunneled axon TPU — see
    ``fetch_timed``), ``drain_s`` under-attributes and ``fetch_s`` may
    still include drain: read the split as a lower bound on drain.  ``launches`` counts
    explicit kernel dispatches at our call sites (tree-growth chunks,
    grid-solver programs, scoring programs) — a design-level dispatch
    count, not an XLA op count.

    ``overlap_s`` separates OVERLAPPED waits from stalls: a drain during
    which later work is already enqueued (the double-buffered sweep loop's
    lagged checkpoint flush, GBT's lagged ES fetch) keeps the accelerator
    busy, so its wall belongs in neither ``drain_s`` (host stalled, device
    idle-after-finish) nor ``fetch_s``.  ``drain_tags`` attributes both
    kinds of wait to the launch site that caused them ("sweep.final",
    "sweep.checkpoint", "halving.promote", ...), keyed ``tag`` or
    ``tag+"+overlap"`` — the ledger a drain regression is debugged from.
    """

    upload_bytes: int = 0
    upload_s: float = 0.0
    uploads: int = 0
    fetch_bytes: int = 0
    fetch_s: float = 0.0
    fetches: int = 0
    drain_s: float = 0.0
    drains: int = 0
    overlap_s: float = 0.0
    overlaps: int = 0
    drain_tags: Dict[str, float] = field(default_factory=dict)
    launches: int = 0
    launch_tags: Dict[str, int] = field(default_factory=dict)
    #: elastic-sweep accounting (parallel/elastic.py mirrors its per-sweep
    #: ElasticCounters here): retries / mesh_shrinks / mesh_repacks /
    #: quarantined / watchdog_fires / device_losses
    elastic: Dict[str, int] = field(default_factory=dict)
    #: warm-start refresh accounting (workflow/refresh.py RefreshContext):
    #: merged / refit / invalidated / geometry_changed estimator counts
    refresh: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "uploadBytes": self.upload_bytes,
            "uploadSecs": round(self.upload_s, 3),
            "uploads": self.uploads,
            "fetchBytes": self.fetch_bytes,
            "fetchSecs": round(self.fetch_s, 3),
            "fetches": self.fetches,
            "drainSecs": round(self.drain_s, 3),
            "drains": self.drains,
            "overlapSecs": round(self.overlap_s, 3),
            "overlaps": self.overlaps,
            "drainTags": {k: round(v, 3) for k, v in self.drain_tags.items()},
            "launches": self.launches,
            "launchTags": dict(self.launch_tags),
            "elastic": dict(self.elastic),
            "refresh": dict(self.refresh),
        }


COUNTERS = RunCounters()

#: guards every mutation of the global ``COUNTERS`` — the count sites run
#: concurrently from the plan's host-stage pool, the serving dispatch
#: thread, and request-handler threads, and unguarded ``+=`` on shared
#: ints drops increments under contention (TM052's runtime twin; the
#: regression test hammers these from threads and asserts exact totals)
_COUNTERS_LOCK = threading.Lock()


def reset_counters() -> RunCounters:
    """Zero the global transfer/dispatch counters; returns the new object."""
    global COUNTERS
    with _COUNTERS_LOCK:
        COUNTERS = RunCounters()
        return COUNTERS


def count_upload(nbytes: int, seconds: float) -> None:
    with _COUNTERS_LOCK:
        COUNTERS.upload_bytes += int(nbytes)
        COUNTERS.upload_s += seconds
        COUNTERS.uploads += 1


def count_fetch(nbytes: int, seconds: float) -> None:
    with _COUNTERS_LOCK:
        COUNTERS.fetch_bytes += int(nbytes)
        COUNTERS.fetch_s += seconds
        COUNTERS.fetches += 1


def count_drain(seconds: float, tag: Optional[str] = None,
                overlapped: bool = False) -> None:
    """Book a device wait.  ``overlapped=True`` means later work was
    already enqueued when the wait started (the device stays busy), so the
    time goes to ``overlap_s`` rather than ``drain_s`` — only genuine
    stalls (nothing behind the wait) count against the drain budget the
    SWEEP_ASYNC smoke gates.  ``tag`` attributes the wait to its launch
    site in ``drain_tags`` (suffixed ``+overlap`` for overlapped waits)."""
    with _COUNTERS_LOCK:
        if overlapped:
            COUNTERS.overlap_s += seconds
            COUNTERS.overlaps += 1
        else:
            COUNTERS.drain_s += seconds
            COUNTERS.drains += 1
        if tag is not None:
            key = tag + "+overlap" if overlapped else tag
            COUNTERS.drain_tags[key] = (
                COUNTERS.drain_tags.get(key, 0.0) + seconds)


def count_launch(tag: str, n: int = 1) -> None:
    with _COUNTERS_LOCK:
        COUNTERS.launches += n
        COUNTERS.launch_tags[tag] = COUNTERS.launch_tags.get(tag, 0) + n


def count_elastic(kind: str, n: int = 1) -> None:
    """Elastic-sweep event (retries / mesh_shrinks / quarantined /
    watchdog_fires / ...) — the process-wide mirror of the per-sweep
    ``parallel.elastic.ElasticCounters``, read by the bench scripts."""
    with _COUNTERS_LOCK:
        COUNTERS.elastic[kind] = COUNTERS.elastic.get(kind, 0) + n


def count_refresh(kind: str, n: int = 1) -> None:
    """Warm-start refresh event (merged / refit / invalidated /
    geometry_changed) — the process-wide mirror of the per-run
    ``workflow.refresh.RefreshReport``, read by the bench scripts."""
    with _COUNTERS_LOCK:
        COUNTERS.refresh[kind] = COUNTERS.refresh.get(kind, 0) + n


def refresh_snapshot() -> Dict[str, int]:
    """The run's refresh counters with every key present (zeros when no
    refresh ran) — the shape ``benchmarks/refresh_latest.json`` records."""
    base = {"merged": 0, "refit": 0, "invalidated": 0,
            "geometry_changed": 0}
    with _COUNTERS_LOCK:
        base.update(COUNTERS.refresh)
    return base


def elastic_snapshot() -> Dict[str, int]:
    """The run's elastic counters with every key present (zeros when the
    sweep never degraded) — the shape ``benchmarks/multichip_latest.json``
    records."""
    base = {"retries": 0, "mesh_shrinks": 0, "mesh_repacks": 0,
            "quarantined": 0, "watchdog_fires": 0, "device_losses": 0}
    with _COUNTERS_LOCK:
        base.update(COUNTERS.elastic)
    return base


def fetch_timed(x, dtype=None, tag=None, overlapped=False):
    """Device→host fetch with drain/transfer split accounting.

    ``block_until_ready`` first (time booked as ``drain_s`` — the async
    queue finishing its enqueued compute), then the actual ``np.asarray``
    copy (booked as ``fetch_s`` against the fetched bytes).  Plain
    ``np.asarray`` conflated the two, which at r3's default grid booked
    ~42 s of sweep compute as "fetch time".

    ``overlapped=True`` routes the wait into ``overlap_s`` instead of
    ``drain_s``: use it ONLY when later device work is already enqueued
    behind this value, so the wait runs concurrently with useful compute
    (the async sweep loop's lagged fetches).  TM042 treats a bare
    ``fetch_timed`` inside a dispatch loop as a forbidden sync point; the
    statically-visible ``overlapped=True`` kwarg is the opt-out.  ``tag``
    names the launch site in ``drain_tags``.

    Platform caveat (ADVICE r4): on the tunneled axon TPU backend,
    ``block_until_ready`` has been observed to return EARLY — the
    subsequent ``np.asarray`` then still blocks for queue drain.  There
    ``drain_s`` is a LOWER bound and ``fetch_s`` may still include drain;
    treat the split as directional, not definitive, when targeting
    optimizations."""
    import numpy as np

    t0 = time.perf_counter()
    try:
        x.block_until_ready()
    except AttributeError:  # host value already
        pass
    t1 = time.perf_counter()
    out = np.asarray(x) if dtype is None else np.asarray(x, dtype)
    t2 = time.perf_counter()
    count_drain(t1 - t0, tag=tag, overlapped=overlapped)
    count_fetch(out.nbytes, t2 - t1)
    return out


_BACKEND_NAME: Optional[str] = None


def backend_name() -> str:
    """The jax backend serving this process, cached after first use (a
    cost-model feature on every stage profile — one import per stage
    would be waste)."""
    global _BACKEND_NAME
    if _BACKEND_NAME is None:
        try:
            import jax

            _BACKEND_NAME = jax.default_backend()
        except Exception:  # pragma: no cover - jax must be importable
            _BACKEND_NAME = "unknown"
    return _BACKEND_NAME


@dataclass
class StageProfile:
    """One executed DAG stage, as recorded by the execution plan
    (workflow/plan.py) — the per-stage analogue of the reference's
    OpSparkListener stage metrics, with TPU-relevant extras: device
    launches dispatched (from ``RunCounters``) and the dataset's column
    delta (liveness accounting).

    ``cols``/``dtype``/``backend``/``stage_kind`` are the learned cost
    model's feature fields (tuning/costmodel.py): total scalar width of
    the stage's inputs, the primary input dtype, the serving jax backend,
    and the ``"Op:kind"`` bucket key.  Backward-compatible additions —
    absent in old profiles, defaulted here."""

    uid: str
    op: str
    output: str
    layer: int
    kind: str            # "fit" | "transform" | "substitute"
    device_heavy: bool
    wall_s: float
    rows: int
    cols_added: int = 0
    cols_dropped: int = 0   # columns freed after this stage's layer
    launches: int = 0       # device dispatches attributed (serial stages only)
    cols: int = 0           # total scalar input width (matrix cols count)
    dtype: str = ""         # primary input dtype
    backend: str = ""       # jax backend for the run
    stage_kind: str = ""    # cost-model bucket key, "Op:kind"
    n_devices: int = 1      # devices the stage ran on (mesh size; 1 = chip)
    mesh_shape: str = ""    # e.g. "data=4,grid=2" ("" = no mesh)
    #: compiled-program features attributed to this stage when a trace
    #: was active (obs/hlo.py): {"programs", "flops", "bytes_accessed",
    #: "ops": {...}} — empty when untraced or nothing compiled
    hlo: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        out = {"uid": self.uid, "op": self.op, "output": self.output,
               "layer": self.layer, "kind": self.kind,
               "deviceHeavy": self.device_heavy,
               "wallSecs": round(self.wall_s, 4), "rows": self.rows,
               "colsAdded": self.cols_added,
               "colsDropped": self.cols_dropped, "launches": self.launches,
               "cols": self.cols, "dtype": self.dtype,
               "backend": self.backend,
               "stageKind": self.stage_kind or f"{self.op}:{self.kind}"}
        # backward-compatible additions: single-chip profiles serialize
        # exactly as before this field existed
        if self.n_devices != 1:
            out["nDevices"] = self.n_devices
        if self.mesh_shape:
            out["meshShape"] = self.mesh_shape
        if self.hlo:
            out["hlo"] = dict(self.hlo)
        return out


def mesh_desc(mesh) -> tuple:
    """(n_devices, "axis=size,..." ) of a jax Mesh — (1, "") for None."""
    if mesh is None:
        return 1, ""
    try:
        shape = {name: int(mesh.shape[name]) for name in mesh.axis_names}
    except Exception:  # pragma: no cover - exotic mesh-likes
        return 1, ""
    n = 1
    for v in shape.values():
        n *= v
    return n, ",".join(f"{k}={v}" for k, v in shape.items())


#: per-pass chunk records kept verbatim before aggregate-only accounting
#: takes over (bounds profiler memory on million-chunk ingests)
_INGEST_CHUNK_DETAIL_CAP = 512


@dataclass
class IngestPass:
    """One streaming pass over the chunked reader (fit pass or the final
    materialize pass of the two-pass out-of-core driver,
    workflow/streaming.py).

    ``read_s`` is producer-side time (parse/IO on the prefetch thread),
    ``transform_s`` consumer-side stage time; with prefetch overlap the
    pass wall should approach max(read_s, transform_s) rather than their
    sum — ``overlap_efficiency`` reports how much of the smaller phase was
    hidden (1.0 = fully overlapped, 0.0 = strictly serial)."""

    label: str
    chunks: int = 0
    rows: int = 0
    bytes_read: int = 0
    read_s: float = 0.0
    transform_s: float = 0.0
    wall_s: float = 0.0
    #: transient-IO retry count / backoff wall for this pass (the reader's
    #: RetryingChunkStream wrapper, readers/resilience.py)
    retries: int = 0
    retry_wait_s: float = 0.0
    #: chunks fast-skipped on a checkpoint resume (read but not
    #: re-transformed; workflow/checkpoint.py)
    chunks_skipped: int = 0
    #: first _INGEST_CHUNK_DETAIL_CAP chunks as (rows, read_s, transform_s)
    chunk_detail: List[Tuple[int, float, float]] = field(default_factory=list)

    def note_read(self, rows: int, seconds: float, nbytes: int = 0) -> None:
        self.chunks += 1
        self.rows += rows
        self.read_s += seconds
        self.bytes_read += int(nbytes)
        if len(self.chunk_detail) < _INGEST_CHUNK_DETAIL_CAP:
            self.chunk_detail.append([rows, round(seconds, 6), 0.0])

    def note_transform(self, chunk_index: int, seconds: float) -> None:
        self.transform_s += seconds
        if chunk_index < len(self.chunk_detail):
            self.chunk_detail[chunk_index][2] = round(seconds, 6)

    def note_retry(self, wait_s: float) -> None:
        self.retries += 1
        self.retry_wait_s += wait_s

    @property
    def overlap_efficiency(self) -> float:
        smaller = min(self.read_s, self.transform_s)
        if smaller <= 0 or self.wall_s <= 0:
            return 0.0
        hidden = self.read_s + self.transform_s - self.wall_s
        return max(0.0, min(1.0, hidden / smaller))

    @property
    def rows_per_s(self) -> float:
        return self.rows / self.wall_s if self.wall_s > 0 else 0.0

    def to_json(self) -> Dict[str, Any]:
        out = {
            "label": self.label, "chunks": self.chunks, "rows": self.rows,
            "bytesRead": self.bytes_read,
            "readSecs": round(self.read_s, 4),
            "transformSecs": round(self.transform_s, 4),
            "wallSecs": round(self.wall_s, 4),
            "rowsPerSec": round(self.rows_per_s, 1),
            "overlapEfficiency": round(self.overlap_efficiency, 3),
            "chunkDetail": [list(c) for c in self.chunk_detail],
        }
        if self.retries:
            out["retries"] = self.retries
            out["retryWaitSecs"] = round(self.retry_wait_s, 4)
        if self.chunks_skipped:
            out["chunksSkipped"] = self.chunks_skipped
        return out


class IngestProfiler:
    """Chunked-ingestion counters for one out-of-core train: one
    ``IngestPass`` per streaming pass, plus the chunk geometry."""

    def __init__(self, chunk_rows: int = 0):
        self.chunk_rows = chunk_rows
        self.passes: List[IngestPass] = []
        #: bytes of retained blocks the fused pass spilled to disk
        #: (workflow/streaming._BlockStore; 0 = everything stayed in RAM)
        self.spilled_bytes: int = 0
        #: quarantined bad records: sidecar entries / data rows dropped
        #: (readers/resilience.QuarantineSink; 0/0 under the fail policy)
        self.quarantined_records: int = 0
        self.quarantined_rows: int = 0
        #: checkpoint accounting (workflow/checkpoint.py): durable saves,
        #: time spent writing them, and whether this run resumed
        self.checkpoint_saves: int = 0
        self.checkpoint_wall_s: float = 0.0
        self.resumed: bool = False
        #: RawFeatureFilter streaming-profile pass accounting (rows /
        #: retries per pass) when the train ran with a filter; None else
        self.rff: "Optional[Dict[str, Any]]" = None
        #: pod-train record (distributed/podstream.py): shard plan, this
        #: process's entries, post-ingest peak RSS, resume repacks; None
        #: on single-process trains
        self.pod: "Optional[Dict[str, Any]]" = None
        self._lock = threading.Lock()

    def begin_pass(self, label: str) -> IngestPass:
        p = IngestPass(label=label)
        with self._lock:
            self.passes.append(p)
        return p

    @property
    def total_rows(self) -> int:
        return max((p.rows for p in self.passes), default=0)

    @property
    def total_bytes(self) -> int:
        return max((p.bytes_read for p in self.passes), default=0)

    @property
    def total_retries(self) -> int:
        return sum(p.retries for p in self.passes)

    @property
    def total_retry_wait_s(self) -> float:
        return sum(p.retry_wait_s for p in self.passes)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "chunkRows": self.chunk_rows,
                "rows": self.total_rows,
                "bytesRead": self.total_bytes,
                "spilledBytes": self.spilled_bytes,
                "retries": self.total_retries,
                "retryWaitSecs": round(self.total_retry_wait_s, 4),
                "quarantinedRecords": self.quarantined_records,
                "quarantinedRows": self.quarantined_rows,
                "checkpointSaves": self.checkpoint_saves,
                "checkpointWallSecs": round(self.checkpoint_wall_s, 4),
                "resumed": self.resumed,
                "rff": self.rff,
                "pod": self.pod,
                "passes": [p.to_json() for p in self.passes],
            }

    def format(self) -> str:
        with self._lock:
            passes = list(self.passes)
        lines = [f"chunked ingest: {len(passes)} passes, "
                 f"chunk_rows={self.chunk_rows}, rows={self.total_rows}, "
                 f"bytes={self.total_bytes}"]
        for p in passes:
            lines.append(
                f"  {p.label}: {p.chunks} chunks, {p.rows} rows, "
                f"{p.wall_s:.3f}s wall (read {p.read_s:.3f}s | transform "
                f"{p.transform_s:.3f}s), {p.rows_per_s:,.0f} rows/s, "
                f"overlap {p.overlap_efficiency:.0%}"
                + (f", {p.bytes_read} bytes" if p.bytes_read else "")
                + (f", {p.retries} retries ({p.retry_wait_s:.2f}s backoff)"
                   if p.retries else "")
                + (f", {p.chunks_skipped} chunks resumed-past"
                   if p.chunks_skipped else ""))
        if self.quarantined_records:
            lines.append(f"  quarantined: {self.quarantined_records} "
                         f"record(s) / {self.quarantined_rows} row(s)")
        if self.checkpoint_saves:
            lines.append(
                f"  checkpoints: {self.checkpoint_saves} save(s), "
                f"{self.checkpoint_wall_s:.3f}s"
                + (" (resumed run)" if self.resumed else ""))
        return "\n".join(lines)


@dataclass
class LintSnapshot:
    """The DAG-lint result attached to a trained model
    (``OpWorkflow.train(validate=True)``, analysis/linter.py): per-rule
    finding counts, the formatted warnings (errors raise before training
    starts, so a snapshot on a *trained* model can only carry warnings),
    and the lint wall time — tracked so the always-on validation stays
    provably cheap next to train wall (bench contract: <1%)."""

    wall_s: float = 0.0
    rule_counts: Dict[str, int] = field(default_factory=dict)
    warnings: List[str] = field(default_factory=list)

    @staticmethod
    def from_findings(findings, wall_s: float) -> "LintSnapshot":
        counts: Dict[str, int] = {}
        for d in findings:
            counts[d.rule] = counts.get(d.rule, 0) + 1
        return LintSnapshot(
            wall_s=wall_s, rule_counts=counts,
            warnings=[d.format() for d in findings.warnings])

    def to_json(self) -> Dict[str, Any]:
        return {"wallSecs": round(self.wall_s, 5),
                "ruleCounts": dict(self.rule_counts),
                "warnings": list(self.warnings)}

    def format(self) -> str:
        head = (f"dag lint: {sum(self.rule_counts.values())} finding(s) "
                f"in {self.wall_s * 1e3:.1f} ms")
        return "\n".join([head] + [f"  {w}" for w in self.warnings])


class PlanProfiler:
    """Accumulates StageProfile entries for one plan execution; thread-safe
    (host-side stages record from pool threads).  Also tracks the peak
    resident column count — the number liveness pruning exists to bound."""

    def __init__(self):
        self.stages: List[StageProfile] = []
        self.peak_columns: int = 0
        self.final_columns: int = 0
        self.wall_s: float = 0.0
        self.layer_drops: Dict[int, List[str]] = {}
        #: IngestProfiler when the run went through the chunked two-pass
        #: driver (workflow/streaming.py); None for in-core runs
        self.ingest: Optional[IngestProfiler] = None
        #: LintSnapshot when the run came from train(validate=True)
        self.lint: Optional[LintSnapshot] = None
        self._lock = threading.Lock()

    def record_stage(self, sp: StageProfile) -> None:
        with self._lock:
            self.stages.append(sp)

    def note_columns(self, count: int) -> None:
        with self._lock:
            self.peak_columns = max(self.peak_columns, count)
            self.final_columns = count

    def note_drops(self, layer: int, names: List[str]) -> None:
        with self._lock:
            self.layer_drops.setdefault(layer, []).extend(names)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            stages = sorted(self.stages, key=lambda s: (s.layer, s.output))
            out = {
                "wallSecs": round(self.wall_s, 4),
                "peakColumns": self.peak_columns,
                "finalColumns": self.final_columns,
                "layerDrops": {str(k): list(v) for k, v in
                               sorted(self.layer_drops.items())},
                "stages": [s.to_json() for s in stages],
            }
        if self.ingest is not None:
            out["ingest"] = self.ingest.to_json()
        if self.lint is not None:
            out["lint"] = self.lint.to_json()
        return out

    def format(self, top_k: int = 20) -> str:
        """Human-readable per-stage summary (workflow.train(profile=True))."""
        with self._lock:
            stages = list(self.stages)
            peak, final, wall = (self.peak_columns, self.final_columns,
                                 self.wall_s)
        backend = next((s.backend for s in stages if s.backend), "")
        lines = [f"plan execution: {len(stages)} stages, "
                 f"{wall:.3f}s wall, peak {peak} resident columns "
                 f"(final {final})"
                 + (f", backend={backend}" if backend else "")]
        by_cost = sorted(stages, key=lambda s: -s.wall_s)[:top_k]
        for s in by_cost:
            lines.append(
                f"  [{s.layer}] {s.kind:<9} {s.op:<24} {s.wall_s*1e3:8.1f} ms"
                f"  rows={s.rows}  +{s.cols_added}/-{s.cols_dropped} cols"
                + (f"  w={s.cols}" if s.cols else "")
                + (f"  launches={s.launches}" if s.launches else "")
                + ("  [device]" if s.device_heavy else ""))
        if self.ingest is not None:
            lines.append(self.ingest.format())
        if self.lint is not None:
            lines.append(self.lint.format())
        return "\n".join(lines)


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Capture an XLA device trace for the enclosed block (the TPU analogue
    of the Spark UI): view with TensorBoard's profile plugin or Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
